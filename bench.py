"""Benchmark: flagship-model training throughput on the available chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: model FLOPs utilization (MFU) of a dense Llama-style decoder
training step (fwd+bwd+Adam) on one chip. Baseline: the north-star 40% MFU
target from BASELINE.json (reference DeepSpeed's ZeRO-3 Llama claim class);
vs_baseline = achieved_MFU / 0.40.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# bf16 peak FLOPs by TPU device kind (public spec sheets); CPU nominal.
PEAK_FLOPS_BY_KIND = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # Trillium
    "TPU v6e": 918e12,
}


def peak_flops(platform: str) -> float:
    if platform == "tpu":
        kind = jax.devices()[0].device_kind
        for prefix, peak in PEAK_FLOPS_BY_KIND.items():
            if kind.startswith(prefix):
                return peak
        return 197e12  # unknown TPU: assume v5e class
    return 1e12  # CPU / non-TPU: nominal figure, MFU not meaningful


def _bench_7b_streamed_at(peak: float, bsz: int):
    import deepspeed_tpu
    from deepspeed_tpu.models import (
        TransformerConfig,
        flops_per_token,
        init_params,
        make_loss_fn,
        num_params,
    )

    cfg = TransformerConfig(
        vocab_size=32000, hidden_size=4096, n_layers=32, n_heads=32,
        n_kv_heads=32, ffn_hidden_size=11008, max_seq_len=2048,
        dtype="bfloat16", remat_policy="nothing", weight_stream=True,
    )
    engine, _, _, _ = deepspeed_tpu.initialize(
        # deferred init: the full param tree must NEVER materialize in HBM
        model=make_loss_fn(cfg),
        model_parameters=deepspeed_tpu.zero.Init(lambda: init_params(cfg, jax.random.key(0))),
        config={
            "train_batch_size": bsz,
            "bf16": {"enabled": True},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {
                "stage": 3,
                "offload_param": {"device": "cpu"},
                # int8 moment streaming (sqrt-compressed blocks): the tier is
                # PCIe-wire-limited, so state bytes are the throughput lever
                # (PERF.md streamed-7B roofline; parity guard in
                # tests/unit/test_weight_stream.py)
                "offload_optimizer": {
                    "device": "cpu",
                    "stream_quant_bits": int(os.environ.get("DSTPU_STREAM_QUANT", "8")),
                },
            },
            "steps_per_print": 10**9,
        },
    )
    n_params = num_params(engine.params)
    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, size=(bsz, 2049)).astype(np.int32)
    batch = {"input_ids": toks}
    float(engine.train_batch(batch=batch))  # compile + leaf-jit warmup
    float(engine.train_batch(batch=batch))
    t0 = time.perf_counter()
    steps = 3
    for _ in range(steps):
        loss = float(engine.train_batch(batch=batch))
    dt = (time.perf_counter() - t0) / steps
    tok_s = bsz * 2048 / dt
    return {
        "params_b": round(n_params / 1e9, 2),
        "batch": bsz,
        "tok_s": round(tok_s, 1),
        "s_per_step": round(dt, 2),
        "mfu_pct": round(tok_s * flops_per_token(cfg, 2048) / peak * 100, 2),
        "loss": round(loss, 3),
    }


def bench_7b_streamed(peak: float):
    """North-star proof (BASELINE.json): a Llama-2-7B-shaped ZeRO-3 step on
    ONE chip via the weight-streaming tier — params rest in pinned_host,
    layers stage per scan step, grads stream back, and the chunk-streamed
    AdamW updates ~81 GB of host-resident fp32 state (ZeRO-Infinity
    semantics).

    The step is PCIe-bound and its wire traffic (weight staging + grad
    return + optimizer-state round trip, ~230 GB) is per-STEP, not
    per-token — so a larger micro-batch amortizes it almost linearly
    (PERF.md "Streamed-7B roofline"). The ladder tries the largest batch
    first and falls back if HBM or host memory rejects it."""
    import gc

    from deepspeed_tpu.parallel.topology import reset_topology

    last_err = None
    # 16 measured as the largest batch that compiles at 7B (24/32 exceed
    # HBM); the wire traffic is per-STEP so batch 8 -> 16 bought
    # 770 -> 1175 tok/s on top of the int8 moment streaming (PERF.md)
    for bsz in (16, 8, 4, 1):
        try:
            out = _bench_7b_streamed_at(peak, bsz)
            if last_err:
                out["fallback_from"] = last_err[:120]
            return out
        except Exception as e:
            # keep only the string: e.__traceback__ pins the failed attempt's
            # frames (engine, compiled programs) and would survive into the
            # next rung's memory budget if gc ran inside this clause
            last_err = f"bsz={bsz}: {type(e).__name__}: {e}"
        reset_topology()
        gc.collect()
    raise RuntimeError(last_err)


def bench_overlap_ab(cfg, seq, steps=5, warmup=2):
    """A/B the bucketed ZeRO-3 comm/compute overlap (``overlap_comm``):
    the same ZeRO-3 data-parallel engine with the default bucketed
    collectives + chunked-scan prefetch vs the per-leaf escape hatch
    (``overlap_comm: false``). The two runs must report the same loss —
    the bucketed exchange is bitwise-identical — so the delta is pure
    schedule. Only meaningful with >1 device (collectives are what gets
    bucketed); single-device boxes skip."""
    import gc

    import deepspeed_tpu
    from deepspeed_tpu.models import init_params, make_loss_fn
    from deepspeed_tpu.parallel.topology import reset_topology

    ndev = len(jax.devices())
    if ndev < 2:
        return {"skipped": "needs >1 device"}
    bsz = ndev * max(1, int(os.environ.get("DSTPU_BENCH_AB_MICRO", "2")))
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(bsz, seq + 1)
    ).astype(np.int32)
    out = {}
    for label, overlap in (("overlap_on", True), ("overlap_off", False)):
        reset_topology()
        gc.collect()
        params = init_params(cfg, jax.random.key(0))
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=make_loss_fn(cfg),
            model_parameters=params,
            config={
                "train_batch_size": bsz,
                "bf16": {"enabled": jax.default_backend() == "tpu"},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 3, "overlap_comm": overlap},
                "mesh": {"data": ndev},
                "steps_per_print": 10**9,
            },
        )
        batch = {"input_ids": toks}
        for _ in range(warmup):
            float(engine.train_batch(batch=batch))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch=batch)
        loss = float(loss)  # device sync before stopping the clock
        dt = (time.perf_counter() - t0) / steps
        out[label] = {"s_per_step": round(dt, 4), "loss": round(loss, 5)}
        del engine, params
    out["speedup"] = round(
        out["overlap_off"]["s_per_step"] / out["overlap_on"]["s_per_step"], 3
    )
    reset_topology()
    gc.collect()
    return out


def bench_long_context_cp(steps=3, warmup=1):
    """Multi-chip long-sequence leg: one train step (fwd+bwd+Adam) with the
    sequence axis sharded over the ``context`` mesh — ring attention keeps
    per-device activations at O(s/N) — A/B'd against the dense reference
    attention on the SAME mesh (what long-context training falls back to
    without a fused kernel: the [b, h, s, s] score matrix materializes).
    Reports per-step wall clock for both arms, the ring arm's MFU against
    the N-device aggregate peak, and the losses (close but not bitwise —
    flash vs dense summation order). Knobs: DSTPU_BENCH_CP_SEQ,
    DSTPU_BENCH_CP_SKIP_DENSE=1 drops the dense arm (at 32k+ the score
    matrix is the OOM the ring exists to avoid)."""
    import gc

    import deepspeed_tpu
    from deepspeed_tpu.models import (
        TransformerConfig,
        flops_per_token,
        init_params,
        make_loss_fn,
    )
    from deepspeed_tpu.parallel.topology import reset_topology

    ndev = len(jax.devices())
    if ndev < 2:
        return {"skipped": "needs >1 device"}
    on_tpu = jax.default_backend() == "tpu"
    seq = int(os.environ.get("DSTPU_BENCH_CP_SEQ", 16384 if on_tpu else 1024))
    if on_tpu:
        base = dict(
            vocab_size=32000, hidden_size=2048, n_layers=4, n_heads=16,
            n_kv_heads=16, max_seq_len=seq, dtype="bfloat16",
            remat_policy="flash",
        )
    else:  # CPU dev boxes: tiny widths, d=64 so the kernel path is exercised
        base = dict(
            vocab_size=512, hidden_size=256, n_layers=2, n_heads=4,
            max_seq_len=seq, dtype="float32",
        )
    arms = [("ring", "flash_ring")]
    if os.environ.get("DSTPU_BENCH_CP_SKIP_DENSE", "0") != "1":
        arms.append(("dense", "reference"))
    out = {"seq": seq, "context": ndev}
    toks = np.random.default_rng(0).integers(
        0, base["vocab_size"], size=(1, seq + 1)).astype(np.int32)
    for label, impl in arms:
        reset_topology()
        gc.collect()
        cfg = TransformerConfig(attention_impl=impl, **base)
        params = init_params(cfg, jax.random.key(0))
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=make_loss_fn(cfg),
            model_parameters=params,
            config={
                "train_micro_batch_size_per_gpu": 1,
                "bf16": {"enabled": on_tpu},
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
                "zero_optimization": {"stage": 0},
                # every device on the context axis: the whole mesh rings
                # over one sequence (the N-chips-one-document regime)
                "mesh": {"context": ndev},
                "steps_per_print": 10**9,
            },
        )
        batch = {"input_ids": toks}
        for _ in range(warmup):
            float(engine.train_batch(batch=batch))
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch=batch)
        loss = float(loss)  # device sync before stopping the clock
        dt = (time.perf_counter() - t0) / steps
        arm = {"s_per_step": round(dt, 4), "loss": round(loss, 4)}
        if label == "ring":
            tok_s = seq / dt
            mfu = tok_s * flops_per_token(cfg, seq) / (
                peak_flops(jax.default_backend()) * ndev)
            arm["tok_s"] = round(tok_s, 1)
            arm["mfu_pct"] = round(mfu * 100, 2)
        out[label] = arm
        del engine, params
    if "dense" in out:
        out["ring_speedup_vs_dense"] = round(
            out["dense"]["s_per_step"] / out["ring"]["s_per_step"], 3)
    reset_topology()
    gc.collect()
    return out


def bench_splash_ab(steps=5, warmup=2):
    """Splash scheduled sparse attention A/B (DSTPU_BENCH_SPLASH=1 rider).

    Two legs:
      * sparse-vs-dense at a fixed sequence with a local-window mask — on
        CPU the speedup is COUNTED (kernel grid block-visits; interpret
        wall-clock measures the emulator, not the machine), on TPU it is
        wall-clock fwd+bwd of ``attention(impl='splash')`` vs the dense
        flash kernel on the same shapes;
      * dense long-context (s>=16k on TPU): the splash grid streams K/V one
        [block, d] tile per step under ``vmem_limit_bytes`` — no full-K/V
        VMEM residency — reported as achieved MFU against platform peak.
    Knobs: DSTPU_BENCH_SPLASH_SEQ, DSTPU_BENCH_SPLASH_WINDOW,
    DSTPU_BENCH_SPLASH_LONG_SEQ.
    """
    from deepspeed_tpu.ops.attention import attention
    from deepspeed_tpu.ops.sparse_attention import LocalMask, schedule_from_mask

    on_tpu = jax.default_backend() == "tpu"
    seq = int(os.environ.get("DSTPU_BENCH_SPLASH_SEQ", 8192 if on_tpu else 2048))
    window = int(os.environ.get("DSTPU_BENCH_SPLASH_WINDOW", max(256, seq // 8)))
    block = 512 if on_tpu else 256
    sched = schedule_from_mask(LocalMask((seq, seq), window), block)
    dense_visits = sched.nq * sched.nk
    out = {
        "seq": seq, "window": window, "block": block,
        "density": round(sched.density, 4),
        "block_visits": {"dense": dense_visits, "splash": sched.num_active},
        # the structural speedup — what the schedule provably prunes
        "visit_speedup": round(dense_visits / max(sched.num_active, 1), 2),
    }
    if not on_tpu:
        out["wall_clock"] = "skipped (interpret mode times the emulator)"
        return out

    b, h, d = 1, 8, 128
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, seq, d), jnp.bfloat16) for kk in ks)

    def timed(fn):
        g = jax.jit(jax.grad(lambda q: jnp.sum(fn(q).astype(jnp.float32))))
        g(q).block_until_ready()
        for _ in range(warmup):
            g(q).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(steps):
            r = g(q)
        r.block_until_ready()
        return (time.perf_counter() - t0) / steps

    t_splash = timed(lambda q: attention(q, k, v, causal=True, window=window,
                                         impl="splash"))
    t_dense = timed(lambda q: attention(q, k, v, causal=True, impl="flash"))
    out["wall_clock"] = {
        "splash_s": round(t_splash, 5), "dense_s": round(t_dense, 5),
        "speedup": round(t_dense / t_splash, 2),
    }

    # dense long-context leg: causal splash at s>=16k — K/V stream block
    # by block (the grid's kv index map), never resident whole in VMEM
    ls = int(os.environ.get("DSTPU_BENCH_SPLASH_LONG_SEQ", 16384))
    kq, kk_, kv_ = jax.random.split(jax.random.key(1), 3)
    ql = jax.random.normal(kq, (1, h, ls, d), jnp.bfloat16)
    kl = jax.random.normal(kk_, (1, h, ls, d), jnp.bfloat16)
    vl = jax.random.normal(kv_, (1, h, ls, d), jnp.bfloat16)
    g = jax.jit(jax.grad(lambda q: jnp.sum(attention(
        q, kl, vl, causal=True, impl="splash").astype(jnp.float32))))
    g(ql).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        r = g(ql)
    r.block_until_ready()
    dt = (time.perf_counter() - t0) / steps
    # causal attention fwd+bwd: 3.5 * 4*h*s^2*d/2 matmul flops
    flops = 3.5 * 2.0 * h * ls * ls * d
    out["dense_16k"] = {
        "seq": ls, "s_per_step": round(dt, 4),
        "mfu_pct": round(100 * flops / dt / peak_flops("tpu"), 2),
    }
    return out


def v5e64_projection():
    """Analytic feasibility of the north-star config (Llama-2-7B ZeRO-3 on
    v5e-64) from the autotuner's memory model — per-chip model-state +
    activation bytes vs 16 GB HBM across stages/micro-batches."""
    from deepspeed_tpu.autotuning.autotuner import (
        activation_memory_per_chip,
        zero_memory_per_chip,
    )

    n_params, hidden, layers, seq = 6_738_000_000, 4096, 32, 4096
    hbm = 16e9
    rows = []
    for stage in (2, 3):
        for micro in (1, 2, 4, 8):
            state = zero_memory_per_chip(n_params, stage, dp_world=64)
            # saved_factor 4.0 = the "flash" remat policy (attention out+LSE
            # only), calibrated against the measured 617M bench residency
            act = activation_memory_per_chip(
                micro, seq, hidden, layers, remat=True, saved_factor=4.0
            )
            total = state + act
            rows.append({
                "stage": stage, "micro": micro,
                "state_gb": round(state / 1e9, 1),
                "act_gb": round(act / 1e9, 1),
                "fits": bool(total < hbm * 0.9),
            })
    return rows


def main():
    import deepspeed_tpu
    from deepspeed_tpu.models import (
        TransformerConfig,
        flops_per_token,
        init_params,
        make_loss_fn,
    )

    platform = jax.default_backend()
    on_tpu = platform == "tpu"

    # The 7B streamed phase runs FIRST: its weight-streaming programs need a
    # pristine device allocator (a prior on-chip engine's residency breaks
    # the host-streaming runtime even after its buffers are freed — PERF.md).
    streamed_7b = None
    if on_tpu and os.environ.get("DSTPU_BENCH_SKIP_7B", "0") != "1":
        from deepspeed_tpu.parallel.topology import reset_topology

        try:
            streamed_7b = bench_7b_streamed(peak_flops(platform))
        except Exception as e:  # the headline metric must survive
            streamed_7b = {"error": f"{type(e).__name__}: {e}"[:200]}
        import gc

        reset_topology()
        gc.collect()
    if on_tpu:
        # best MFU shape that fits one v5e chip under ZeRO-3 semantics with
        # full fp32 Adam state on-chip (767M params; 16 GB HBM bounds it).
        # Width beats depth on the MXU: the round-3 sweep (PERF.md) moved
        # h 1536→2304 (d=128 heads, 3:1 GQA, ffn 3x) for 52.7% → 55.4%;
        # deeper/wider variants at the same budget OOM at b=6. remat="flash"
        # saves attention out+LSE only and measured best. int8 forward
        # projections (per-token x per-channel scales, exact bf16 backward)
        # ride the v5e MXU's native 2x int8 rate for 55.6 -> 59.9% MFU with a
        # loss trajectory identical to bf16 (mean |gap| 1.3e-4 over 60 fresh-
        # data steps — PERF.md round-4 A/B).
        cfg = TransformerConfig(
            vocab_size=32000, hidden_size=2304, n_layers=10, n_heads=18,
            n_kv_heads=6, ffn_hidden_size=6912, max_seq_len=2048,
            dtype="bfloat16",
            remat_policy=os.environ.get("DSTPU_REMAT_POLICY", "flash"),
            fused_ce=os.environ.get("DSTPU_FUSED_CE", "0") == "1",
            matmul_precision=os.environ.get("DSTPU_MATMUL_PRECISION", "int8"),
        )
        bsz, seq, steps, warmup = int(os.environ.get("DSTPU_BENCH_BSZ", 6)), 2048, 10, 4
    else:  # smoke-test path for CPU dev boxes
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
            max_seq_len=256, dtype="float32",
        )
        # batch scales with the (possibly virtual) device count so the DP
        # micro-batch stays >=1 when XLA_FLAGS fakes a multi-device mesh
        bsz, seq, steps, warmup = max(4, len(jax.devices())), 128, 3, 1

    params = init_params(cfg, jax.random.key(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=make_loss_fn(cfg),
        model_parameters=params,
        config={
            "train_batch_size": bsz,
            "bf16": {"enabled": on_tpu},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": 3 if on_tpu else 0},
            "steps_per_print": 10**9,
        },
    )
    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, size=(bsz, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks}

    for _ in range(warmup):
        float(engine.train_batch(batch=batch))  # sync each warmup step
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    loss = float(loss)  # device sync before stopping the clock
    dt = time.perf_counter() - t0

    tokens_per_step = bsz * seq
    tok_s = tokens_per_step * steps / dt
    achieved = tok_s * flops_per_token(cfg, seq)
    peak = peak_flops(platform)
    mfu = achieved / peak

    size = "767M" if on_tpu else "tiny"
    out = {
        "metric": f"llama-{size} zero3 train MFU ({platform}, {tok_s:.0f} tok/s, loss={loss:.3f})",
        "value": round(mfu * 100, 2),
        "unit": "% MFU",
        "vs_baseline": round(mfu / 0.40, 3),
    }
    if streamed_7b is not None:
        out["streamed_7b"] = streamed_7b
        out["v5e64_projection"] = v5e64_projection()
    if os.environ.get("DSTPU_BENCH_SKIP_OVERLAP_AB", "0") != "1":
        try:
            out["overlap_ab"] = bench_overlap_ab(cfg, seq)
        except Exception as e:  # the headline metric must survive
            out["overlap_ab"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if os.environ.get("DSTPU_BENCH_SKIP_CP", "0") != "1":
        try:
            out["long_context_cp"] = bench_long_context_cp()
        except Exception as e:  # the headline metric must survive
            out["long_context_cp"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if os.environ.get("DSTPU_BENCH_SPLASH", "0") == "1":
        try:
            out["splash_ab"] = bench_splash_ab()
        except Exception as e:  # the headline metric must survive
            out["splash_ab"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    if on_tpu and os.environ.get("DSTPU_BENCH_SKIP_SERVING", "0") != "1":
        # free the training engine's HBM residency (params + fp32 Adam state
        # ~12.7 GB) before the serving engine allocates its KV pool
        del engine, params
        import gc

        gc.collect()
        try:
            out["serving_v2"] = bench_serving(cfg)
        except Exception as e:  # the headline metric must survive
            out["serving_v2"] = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps(out))


def bench_serving(train_cfg):
    """FastGen-analogue serving throughput (BASELINE.md row 3): the v2
    paged-KV continuous-batching engine serving 32 concurrent sequences on
    the same 767M shape — split-phase prefill (no per-step host sync) +
    one fused 64-token decode round (PERF.md 'serving roofline'). Reports
    generated tok/s including prefill time, plus the decode round's
    in-round rate against its weight-read roofline."""
    import dataclasses
    import gc

    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import init_params, num_params
    from deepspeed_tpu.parallel.topology import reset_topology

    reset_topology()
    gc.collect()
    cfg = dataclasses.replace(train_cfg, remat=False, matmul_precision="default")
    params = init_params(cfg, jax.random.key(0))
    rc = RaggedInferenceEngineConfig.from_dict({
        "dtype": "bfloat16", "decode_steps": 64,
        # tuned for THIS workload by `dstpu_bench --tune-serving` (PERF.md
        # round-5 serving sweep): 256x4 prompt-chunk grid (979.8 vs 812.2
        # for the hand-picked 512x2) and a block table sized to the
        # workload's <=576-token contexts (B=5 x 128 — the decode gather
        # reads the whole table, so over-provisioned slots are pure wasted
        # HBM traffic). An operator serving longer contexts raises
        # max_blocks_per_seq/max_context and re-tunes.
        "prompt_chunk": 256, "max_prompt_chunks": 4,
        "kv_cache": {"block_size": 128, "num_blocks": 512, "max_blocks_per_seq": 5},
        "state_manager": {"max_tracked_sequences": 64, "max_ragged_batch_size": 1024,
                          "max_ragged_sequence_count": 32, "max_context": 640},
    })
    from deepspeed_tpu.inference.v2.engine_v2 import serving_benchmark

    eng = InferenceEngineV2(cfg, params, rc)
    # the CANONICAL workload, shared with the autotuner's serving
    # experiments (engine_v2.serving_benchmark) so tuned configs are
    # validated against the same measurement the bench reports
    best_rate = serving_benchmark(eng, n_seq=32, max_new=64, repeats=2)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, size=(int(l),)).astype(np.int32)
               for l in rng.integers(64, 512, size=32)]
    # decode-only roofline check: one warm fused round
    for uid, p in enumerate(prompts):
        eng.scheduler.submit(100 + uid, p[:256])
    from deepspeed_tpu.inference.v2.engine_v2 import _materialize_rows
    held = {}
    while eng.scheduler.has_pending():
        held.update(eng._step_device())
    for uid, tok in _materialize_rows(held, want_tokens=True).items():
        eng.scheduler.feedback(uid, int(tok))
    eng.decode_round(64)  # warm
    t0 = time.perf_counter()
    eng.decode_round(64)
    rt = time.perf_counter() - t0
    in_round = 32 * 64 / rt
    # weight-read roofline: every decode step reads all params once
    wb = num_params(eng.params) * 2  # bf16 bytes
    roof = 32 / (wb / 692e9)  # tok/s at the measured ~692 GB/s HBM stream rate
    return {
        "concurrent_seqs": 32,
        "gen_tok_s": round(best_rate, 1),
        "decode_steps": 64,
        "decode_in_round_tok_s": round(in_round, 0),
        "decode_roofline_tok_s": round(roof, 0),
        "decode_roofline_pct": round(100 * in_round / roof, 1),
    }


def bench_spec_ab(spec_k=None, cfg=None, params=None, seed=0):
    """Speculative-decoding A/B (riding ``--serving-load`` via the
    DSTPU_SPEC_K env knob): two identical serving stacks run the same
    decode-heavy closed workload — all requests submitted up front, short
    prompts, long greedy generations — once with spec off and once with
    draft-and-verify at K=DSTPU_SPEC_K. Output streams are bit-identical
    by construction (the verify step accepts only exact target matches),
    so the A/B isolates pure wall-clock: decode tok/s, TPOT, and the
    acceptance telemetry that explains the speedup.

    The workload is acceptance-FRIENDLY by design (small vocab + motif
    prompts, the regime where greedy decode revisits its own n-grams):
    spec decode's win is proportional to the drafter's hit rate, and this
    benchmark measures the machinery's ceiling, not a claim about
    arbitrary workloads — the adaptive controller exists for the others.
    Knobs: DSTPU_SPEC_K (draft length, 0 skips the A/B), DSTPU_SPEC_N
    (requests), DSTPU_SPEC_MAX_NEW (tokens per request)."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.serving.driver import ServingDriver
    from deepspeed_tpu.serving.request import SamplingParams

    spec_k = int(spec_k if spec_k is not None else os.environ.get("DSTPU_SPEC_K", 0))
    n_requests = int(os.environ.get("DSTPU_SPEC_N", 2))
    max_new = int(os.environ.get("DSTPU_SPEC_MAX_NEW", 64))
    if cfg is None:
        # vocab 64: greedy decode on a random tiny model re-enters short
        # cycles, which the prompt-lookup drafter predicts — the
        # high-acceptance end of the spectrum (a code-completion analogue).
        # hidden 384 x 4 layers: big enough that per-program weight traffic
        # dominates (the memory-bound regime spec decode targets); default
        # concurrency 2 = the low-batch latency case where verify's
        # per-sweep amortization is largest (measured 1.65x at acceptance
        # ~0.84; 8 concurrent streams already amortize the sweep 8 ways and
        # drop the A/B to ~1.2x)
        cfg = TransformerConfig(
            vocab_size=64, hidden_size=384, n_layers=4, n_heads=8,
            max_seq_len=1024, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))

    rng = np.random.default_rng(seed)
    motif = rng.integers(0, cfg.vocab_size, size=(6,)).astype(np.int32)
    prompts = []
    for _ in range(n_requests):
        tail = rng.integers(0, cfg.vocab_size, size=(int(rng.integers(4, 10)),))
        prompts.append(np.concatenate([np.tile(motif, 2), tail]).astype(np.int32))

    def run(k):
        rc = RaggedInferenceEngineConfig.from_dict({
            "dtype": cfg.dtype, "spec_k": k,
            "kv_cache": {"block_size": 16, "num_blocks": 384,
                         "max_blocks_per_seq": 16},
            "state_manager": {"max_tracked_sequences": 64,
                              "max_ragged_batch_size": 96,
                              "max_ragged_sequence_count": 16,
                              "max_context": 256},
        })
        engine = InferenceEngineV2(cfg, params, rc)
        driver = ServingDriver(engine, max_queue=n_requests + 1).start()
        # warm the compiled shapes (prefill grid + decode + verify) so the
        # measured pass is steady-state
        warm = driver.submit(prompts[0], params=SamplingParams(
            max_new_tokens=max(8, min(24, max_new)), ignore_eos=True))
        warm.wait(300)
        t0 = time.perf_counter()
        reqs = [driver.submit(p, params=SamplingParams(
            max_new_tokens=max_new, ignore_eos=True)) for p in prompts]
        for r in reqs:
            r.wait(600)
        wall = time.perf_counter() - t0
        health = driver.health()
        driver.shutdown(drain=True, timeout=60)
        toks = sum(len(r.generated) for r in reqs if r.state == "finished")
        tpots = [r.tpot_s for r in reqs if r.tpot_s is not None]
        return {
            "tok_s": toks / wall if wall > 0 else 0.0,
            "tpot_mean_s": float(np.mean(tpots)) if tpots else None,
            "outputs": [list(r.generated) for r in reqs],
            "spec": health["spec"],
        }

    base = run(0)
    spec = run(spec_k)
    if base["outputs"] != spec["outputs"]:
        raise RuntimeError("spec A/B output mismatch: verify rounds must be "
                           "bit-identical to plain decode")
    return {
        "spec_k": spec_k,
        "n_requests": n_requests,
        "max_new": max_new,
        "baseline_tok_s": round(base["tok_s"], 1),
        "spec_tok_s": round(spec["tok_s"], 1),
        "speedup": round(spec["tok_s"] / base["tok_s"], 3) if base["tok_s"] else None,
        "baseline_tpot_s": (round(base["tpot_mean_s"], 5)
                            if base["tpot_mean_s"] is not None else None),
        "spec_tpot_s": (round(spec["tpot_mean_s"], 5)
                        if spec["tpot_mean_s"] is not None else None),
        "acceptance_rate": round(spec["spec"]["acceptance_rate"], 3),
        "draft_tokens": spec["spec"]["draft_tokens"],
        "accepted_tokens": spec["spec"]["accepted_tokens"],
        "verify_rounds": spec["spec"]["rounds"],
        "outputs_bit_identical": True,
    }


def bench_kv_dtype_ab(cfg=None, params=None, seed=0):
    """Int8-KV A/B (riding ``--serving-load`` via the DSTPU_KV_DTYPE=int8
    env knob): two identical serving stacks sized from the SAME KV byte
    budget — once with bf16 payload blocks, once with int8 payloads +
    per-vector fp32 scale planes (``kv_cache_dtype: int8``). The budget is
    held fixed, so the int8 stack admits ~2x the blocks (2d/(d+4) of the
    head dim); the report carries the realized block counts, decode tok/s,
    and an output-closeness check: per-token agreement between the two
    greedy streams must stay above 0.8 (a broken dequant produces garbage
    and trips it; genuine int8 rounding on these tiny models measures at
    or near 1.0). Knobs: DSTPU_KV_DTYPE (int8 enables), DSTPU_KV_N
    (requests), DSTPU_KV_MAX_NEW (tokens per request)."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.kv_pool import blocks_for_budget, bytes_per_block
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.serving.driver import ServingDriver
    from deepspeed_tpu.serving.request import SamplingParams

    n_requests = int(os.environ.get("DSTPU_KV_N", 4))
    max_new = int(os.environ.get("DSTPU_KV_MAX_NEW", 48))
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=256, hidden_size=256, n_layers=2, n_heads=4,
            n_kv_heads=2, max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=(int(rng.integers(8, 24)),)).astype(np.int32)
               for _ in range(n_requests)]
    # the shared budget: what a 256-block bf16 pool costs at this shape
    block_size = 16
    per_bf16 = bytes_per_block(block_size, cfg.kv_heads, cfg.head_dim,
                               cfg.n_layers, "bf16")
    budget = (256 + 1) * per_bf16

    def run(kv_dtype):
        nb = blocks_for_budget(budget, block_size, cfg.kv_heads, cfg.head_dim,
                               cfg.n_layers, kv_dtype)
        rc = RaggedInferenceEngineConfig.from_dict({
            "dtype": cfg.dtype,
            "kv_cache": {"block_size": block_size, "num_blocks": nb,
                         "max_blocks_per_seq": 16, "kv_cache_dtype": kv_dtype},
            "state_manager": {"max_tracked_sequences": 64,
                              "max_ragged_batch_size": 96,
                              "max_ragged_sequence_count": 16,
                              "max_context": 256},
        })
        engine = InferenceEngineV2(cfg, params, rc)
        driver = ServingDriver(engine, max_queue=n_requests + 1).start()
        warm = driver.submit(prompts[0], params=SamplingParams(
            max_new_tokens=8, ignore_eos=True))
        warm.wait(300)
        t0 = time.perf_counter()
        reqs = [driver.submit(p, params=SamplingParams(
            max_new_tokens=max_new, ignore_eos=True)) for p in prompts]
        for r in reqs:
            r.wait(600)
        wall = time.perf_counter() - t0
        info = engine.kv_pool_info()
        driver.shutdown(drain=True, timeout=60)
        toks = sum(len(r.generated) for r in reqs if r.state == "finished")
        return {
            "num_blocks": nb,
            "kv_pool_bytes": info["kv_pool_bytes"],
            "tok_s": toks / wall if wall > 0 else 0.0,
            "outputs": [list(r.generated) for r in reqs],
        }

    base = run("bf16")
    quant = run("int8")
    agree = [
        float(np.mean([a == b for a, b in zip(x, y)])) if x and y else 0.0
        for x, y in zip(base["outputs"], quant["outputs"])
    ]
    agreement = float(np.mean(agree)) if agree else 0.0
    if agreement < 0.8:
        raise RuntimeError(
            f"int8-KV A/B output agreement {agreement:.2f} < 0.8: dequant is "
            "broken, not merely rounding"
        )
    return {
        "budget_bytes": budget,
        "bf16_blocks": base["num_blocks"],
        "int8_blocks": quant["num_blocks"],
        "capacity_multiplier": round(quant["num_blocks"] / base["num_blocks"], 3),
        "bf16_tok_s": round(base["tok_s"], 1),
        "int8_tok_s": round(quant["tok_s"], 1),
        "output_agreement": round(agreement, 4),
        "outputs_identical": base["outputs"] == quant["outputs"],
    }


def bench_host_tier_ab(cfg=None, params=None, seed=0):
    """Tiered-KV A/B (riding ``--serving-load`` via the
    DSTPU_KV_HOST_TIER_BYTES env knob): the SAME hot-prefix workload served
    twice under a KV pool deliberately sized to evict — once with the host
    tier off (an evicted prefix re-prefills) and once with it on (the
    evicted prefix spills to the host store and re-imports through the
    double-buffered chunked scatter). The sequence is: seed a shared
    30-block system prompt, then per revisit round flood with long unique
    prompts until the trie fully evicts it and revisit it; the report
    compares revisit TTFT across the two runs. The per-step token budget
    (96) makes the win legible on CPU: a cold revisit needs 6 prefill
    steps, a readmitted one covers the hot blocks from host memory (two
    16-block scatter windows) and prefills only the truly-cold tail in one.
    Token streams must be BIT-identical tier on vs off (the tier moves
    bytes, never changes them) — any divergence raises. Knobs:
    DSTPU_KV_HOST_TIER_BYTES (>0 enables), DSTPU_HOST_TIER_FLOODS,
    DSTPU_HOST_TIER_REVISITS."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.serving.driver import ServingDriver
    from deepspeed_tpu.serving.request import SamplingParams

    tier_bytes = int(os.environ.get("DSTPU_KV_HOST_TIER_BYTES", 1 << 26))
    # floods PER revisit round: 3 x 35 blocks overflows the 96-block pool
    n_floods = int(os.environ.get("DSTPU_HOST_TIER_FLOODS", 3))
    n_revisits = int(os.environ.get("DSTPU_HOST_TIER_REVISITS", 4))
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
            max_seq_len=1024, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(seed)
    block_size = 16
    hot = rng.integers(0, cfg.vocab_size, size=(480,)).astype(np.int32)  # 30 blocks
    tails = [rng.integers(0, cfg.vocab_size, size=(24,)).astype(np.int32)
             for _ in range(n_revisits + 1)]
    floods = [rng.integers(0, cfg.vocab_size, size=(560,)).astype(np.int32)
              for _ in range(n_floods * n_revisits)]

    def run(htb):
        rc = RaggedInferenceEngineConfig.from_dict({
            "dtype": cfg.dtype,
            # 96-block pool vs a flood round of n_floods x 35 blocks: every
            # round overflows the pool, so the trie MUST fully evict the
            # 30-block hot prefix before each revisit
            "kv_cache": {"block_size": block_size, "num_blocks": 96,
                         "max_blocks_per_seq": 40, "prefix_cache": True,
                         "host_tier_bytes": htb, "host_tier_chunk_blocks": 16},
            "state_manager": {"max_tracked_sequences": 32,
                              "max_ragged_batch_size": 96,
                              "max_ragged_sequence_count": 8,
                              "max_context": 768},
        })
        engine = InferenceEngineV2(cfg, params, rc)
        driver = ServingDriver(engine, max_queue=64).start()
        outputs = []

        def go(prompt, max_new=8):
            r = driver.submit(prompt, params=SamplingParams(
                max_new_tokens=max_new, ignore_eos=True))
            r.wait(300)
            outputs.append(list(r.generated))
            return r

        go(np.concatenate([hot, tails[0]]))  # seed the hot prefix (+ warmup)
        revisit_ttfts = []
        fi = iter(floods)
        for t in tails[1:]:
            for _ in range(n_floods):  # evict it (tier on: spill it) ...
                go(next(fi))
            r = go(np.concatenate([hot, t]))  # ... then revisit it
            if r.ttft_s is not None:
                revisit_ttfts.append(r.ttft_s)
        tier = engine.host_tier
        stats = dict(tier.stats()) if tier is not None else None
        driver.shutdown(drain=True, timeout=60)
        return {
            "ttft_revisit_mean_s": (float(np.mean(revisit_ttfts))
                                    if revisit_ttfts else None),
            "outputs": outputs,
            "tier": stats,
        }

    base = run(0)
    tiered = run(tier_bytes)
    if base["outputs"] != tiered["outputs"]:
        raise RuntimeError(
            "host-tier A/B streams diverged: the tier must be bit-invisible "
            "(spill/readmit moves bytes, never changes them)"
        )
    st = tiered["tier"] or {}
    if not st.get("spills") or not st.get("readmits"):
        raise RuntimeError(
            f"host-tier A/B measured nothing: spills={st.get('spills')} "
            f"readmits={st.get('readmits')} — the pool never evicted the hot "
            "prefix, resize the workload"
        )
    off_t, on_t = base["ttft_revisit_mean_s"], tiered["ttft_revisit_mean_s"]
    return {
        "tier_bytes": tier_bytes,
        "ttft_revisit_off_s": round(off_t, 4) if off_t is not None else None,
        "ttft_revisit_on_s": round(on_t, 4) if on_t is not None else None,
        "ttft_speedup": (round(off_t / on_t, 3)
                         if off_t and on_t else None),
        "spills": int(st.get("spills", 0)),
        "readmits": int(st.get("readmits", 0)),
        "host_tier_hits": int(st.get("hits", 0)),
        "host_bytes_peak": int(st.get("bytes", 0)),
        "outputs_bit_identical": True,
    }


def bench_kv_transport_ab(cfg=None, params=None, seed=0):
    """KV-transport A/B (riding ``--serving-load`` via the
    DSTPU_KV_TRANSPORT env knob): the SAME disaggregated revisit workload
    — 1 prefill worker handing off to 1 decode replica, every prompt
    sharing a hot multi-block prefix so revisit handoffs arrive with the
    prefix already trie-covered on the decode side — served twice: once
    over the baseline ``host`` wire (numpy bounce) and once over the
    requested transport. The ``device`` wire keeps exported blocks
    jax-resident (int8 scale planes riding along) and ships them as
    pipelined chunked windows, so the decode replica seeds the covered
    prefix and takes its first decode step while tail windows are still
    in flight. Reports the two numbers the wire owns: per-handoff latency
    (mean/p95 from the router histogram) and time-to-first-decode-token
    on the revisit rounds, plus bytes moved per handoff. Token streams
    must be BIT-identical across transports (the wire moves bytes, never
    changes them) — any divergence raises. Knobs: DSTPU_KV_TRANSPORT
    (``device``/``in_process`` enables), DSTPU_KVT_N (revisit rounds),
    DSTPU_KVT_MAX_NEW, DSTPU_KVT_KV_DTYPE (bf16|int8)."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.serving.cluster import Router
    from deepspeed_tpu.serving.cluster.handoff import KV_TRANSPORTS
    from deepspeed_tpu.serving.request import SamplingParams

    transport = os.environ.get("DSTPU_KV_TRANSPORT", "device")
    if transport not in KV_TRANSPORTS:
        raise ValueError(
            f"DSTPU_KV_TRANSPORT={transport!r}: choose from {KV_TRANSPORTS}")
    n_revisits = int(os.environ.get("DSTPU_KVT_N", 6))
    max_new = int(os.environ.get("DSTPU_KVT_MAX_NEW", 8))
    kv_dtype = os.environ.get("DSTPU_KVT_KV_DTYPE", "bf16")
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
            max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(seed)
    block_size = 16
    # 4-block hot prefix + 2-block unique tail = 6 blocks per handoff;
    # chunk width 2 → three pipelined windows per export on the device wire
    hot = rng.integers(0, cfg.vocab_size, size=(64,)).astype(np.int32)
    tails = [rng.integers(0, cfg.vocab_size, size=(24,)).astype(np.int32)
             for _ in range(n_revisits + 1)]
    rc_dict = {
        "dtype": cfg.dtype,
        "kv_cache": {"block_size": block_size, "num_blocks": 96,
                     "max_blocks_per_seq": 12, "prefix_cache": True,
                     "kv_cache_dtype": kv_dtype,
                     "host_tier_chunk_blocks": 2},
        "state_manager": {"max_tracked_sequences": 16,
                          "max_ragged_batch_size": 96,
                          "max_ragged_sequence_count": 8,
                          "max_context": 256},
    }

    def run(wire):
        engines = [
            InferenceEngineV2(cfg, params,
                              RaggedInferenceEngineConfig.from_dict(rc_dict))
            for _ in range(2)
        ]
        router = Router(engines=engines, num_prefill_workers=1,
                        kv_transport=wire, max_queue=16).start()
        outputs, revisit_ttfts = [], []
        try:
            def go(prompt):
                r = router.submit(prompt, params=SamplingParams(
                    max_new_tokens=max_new, ignore_eos=True))
                r.wait(300)
                outputs.append(list(r.generated))
                return r

            # seed round: compiles both engines' step shapes AND leaves the
            # hot prefix trie-covered on the decode replica, so every
            # measured revisit handoff exercises the covered-prefix seed +
            # pipelined-tail path
            go(np.concatenate([hot, tails[0]]))
            for t in tails[1:]:
                r = go(np.concatenate([hot, t]))
                if r.ttft_s is not None:
                    revisit_ttfts.append(r.ttft_s)
            kt = router.health()["kv_transport"]
            cell = kt["per_transport"].get(wire, {})
            # remote wire only: per-endpoint socket-level accounting
            # (payload bytes + framing tax, credit stalls) from the
            # exporters' KVEndpoint stats
            wire_stats = {}
            for ep in kt.get("endpoints", {}).values():
                for k in ("wire_bytes_sent", "frames_sent", "credit_stalls",
                          "served"):
                    wire_stats[k] = wire_stats.get(k, 0) + int(ep.get(k, 0))
        finally:
            router.shutdown(drain=True, timeout=60)
        handoffs = max(1.0, cell.get("handoffs", 0.0))
        return {
            "outputs": outputs,
            "ttft_revisit_mean_s": (float(np.mean(revisit_ttfts))
                                    if revisit_ttfts else None),
            "handoff_mean_s": kt["latency_mean_s"],
            "handoff_p95_s": kt["latency_p95_s"],
            "bytes_per_handoff": cell.get("bytes", 0.0) / handoffs,
            "windows_per_handoff": cell.get("chunks", 0.0) / handoffs,
            "handoffs": int(cell.get("handoffs", 0.0)),
            "wire_stats": wire_stats,
        }

    base = run("host")
    arm = run(transport)
    if base["outputs"] != arm["outputs"]:
        raise RuntimeError(
            f"kv-transport A/B streams diverged (host vs {transport}): the "
            "wire must be bit-invisible — it moves KV bytes, never changes "
            "them"
        )
    if not arm["handoffs"]:
        raise RuntimeError(
            "kv-transport A/B measured nothing: no handoffs reached the "
            f"{transport!r} wire — is the prefill worker routing?"
        )
    off_t, on_t = base["ttft_revisit_mean_s"], arm["ttft_revisit_mean_s"]
    out = {
        "transport": transport,
        "kv_dtype": kv_dtype,
        "handoffs_per_arm": arm["handoffs"],
        "handoff_host_mean_s": round(base["handoff_mean_s"], 6),
        "handoff_host_p95_s": round(base["handoff_p95_s"], 6),
        f"handoff_{transport}_mean_s": round(arm["handoff_mean_s"], 6),
        f"handoff_{transport}_p95_s": round(arm["handoff_p95_s"], 6),
        "handoff_speedup": (round(base["handoff_mean_s"]
                                  / arm["handoff_mean_s"], 3)
                            if arm["handoff_mean_s"] else None),
        "bytes_per_handoff_host": int(base["bytes_per_handoff"]),
        f"bytes_per_handoff_{transport}": int(arm["bytes_per_handoff"]),
        f"windows_per_handoff_{transport}": round(
            arm["windows_per_handoff"], 2),
        "ttft_revisit_host_s": round(off_t, 4) if off_t is not None else None,
        f"ttft_revisit_{transport}_s": (round(on_t, 4)
                                        if on_t is not None else None),
        "ttft_speedup": (round(off_t / on_t, 3) if off_t and on_t else None),
        "outputs_bit_identical": True,
    }
    if transport == "remote" and arm["wire_stats"]:
        ws = arm["wire_stats"]
        payload = arm["bytes_per_handoff"] * arm["handoffs"]
        out.update({
            # socket-level bytes vs exported payload bytes: >1 is framing
            # tax (headers + plane records), <1 means trie-covered prefix
            # blocks never crossed the wire (the FETCH starts past them)
            "wire_bytes_per_handoff": int(
                ws["wire_bytes_sent"] / max(1, ws["served"])),
            "wire_vs_payload_ratio": (round(
                ws["wire_bytes_sent"] / payload, 4) if payload else None),
            "wire_frames_per_handoff": round(
                ws["frames_sent"] / max(1, ws["served"]), 2),
            "wire_credit_stalls": ws["credit_stalls"],
        })
    return out


def bench_comm_quant_ab(cfg=None, params=None, seed=0):
    """Quantized-collectives A/B (riding ``--serving-load`` via the
    DSTPU_COMM_QUANT=int8 env knob): the SAME TP-decode workload served
    twice — full-width MODEL_AXIS psums, then int8-inside-the-collective
    (``comm_quant: int8``) — on a ``data x model=2`` slice of the available
    devices. Reports decode tok/s for both runs and the per-wire trace-time
    byte accounting (quantized vs replaced full-width bytes and the derived
    reduction ratio — the number the /metrics gauges export). Output gate:
    the first generated token must agree for ≥75% of requests (a broken
    (de)quant path mangles every logit and flips essentially all of them;
    genuine int8 rounding flips only knife-edge argmax ties, which on a
    trained model are rare and on these random-init models still spare the
    first token). Knobs: DSTPU_COMM_QUANT (int8 enables), DSTPU_CQ_N
    (requests), DSTPU_CQ_MAX_NEW (tokens per request)."""
    from deepspeed_tpu.comm.quantized import reset_wire_stats, wire_stats
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.parallel.topology import (
        Topology, reset_topology, set_topology,
    )

    ndev = len(jax.devices())
    if ndev < 2 or ndev % 2:
        return {"skipped": f"needs an even device count >= 2, have {ndev}"}
    tp = 2
    n_requests = int(os.environ.get("DSTPU_CQ_N", 4))
    max_new = int(os.environ.get("DSTPU_CQ_MAX_NEW", 32))
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=256, hidden_size=256, n_layers=2, n_heads=4,
            n_kv_heads=2, max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=(int(rng.integers(8, 24)),)).astype(np.int32)
               for _ in range(n_requests)]

    def run(mode):
        reset_topology()
        set_topology(Topology(data=ndev // tp, model=tp))
        try:
            reset_wire_stats()
            rc = RaggedInferenceEngineConfig.from_dict({
                "dtype": cfg.dtype, "tp_size": tp, "comm_quant": mode,
                "kv_cache": {"block_size": 16, "num_blocks": 128,
                             "max_blocks_per_seq": 16},
                "state_manager": {"max_tracked_sequences": 64,
                                  "max_ragged_batch_size": 96,
                                  "max_ragged_sequence_count": 16,
                                  "max_context": 256},
            })
            engine = InferenceEngineV2(cfg, params, rc)
            engine.generate(prompts[:1], max_new_tokens=8)  # compile warmup
            t0 = time.perf_counter()
            outs = engine.generate(prompts, max_new_tokens=max_new)
            wall = time.perf_counter() - t0
            toks = sum(len(o) - len(p) for o, p in zip(outs, prompts))
            return {
                "tok_s": toks / wall if wall > 0 else 0.0,
                "outputs": [np.asarray(o).tolist() for o in outs],
                "wires": wire_stats(),
            }
        finally:
            reset_topology()

    base = run("none")
    quant = run("int8")
    firsts = [
        x[len(p)] == y[len(p)]
        for p, x, y in zip(prompts, base["outputs"], quant["outputs"])
        if len(x) > len(p) and len(y) > len(p)
    ]
    first_tok_agreement = float(np.mean(firsts)) if firsts else 0.0
    if first_tok_agreement < 0.75:
        raise RuntimeError(
            f"comm-quant A/B first-token agreement {first_tok_agreement:.2f} "
            "< 0.75: the quantized collective path is broken, not merely "
            "rounding"
        )
    agree = [
        float(np.mean([a == b for a, b in zip(x[len(p):], y[len(p):])]))
        for p, x, y in zip(prompts, base["outputs"], quant["outputs"])
    ]
    return {
        "tp": tp,
        "none_tok_s": round(base["tok_s"], 1),
        "int8_tok_s": round(quant["tok_s"], 1),
        "first_token_agreement": round(first_tok_agreement, 4),
        "token_agreement": round(float(np.mean(agree)) if agree else 0.0, 4),
        "wires": {
            tag: {
                "sites": w["sites"],
                "wire_bytes_int8": w["wire_bytes_int8"],
                "wire_bytes_fp": w["wire_bytes_fp"],
                "reduction": round(w["reduction"], 3),
            }
            for tag, w in quant["wires"].items()
        },
    }


def bench_comm_overlap_ab(cfg=None, params=None, seed=0):
    """Tile-granular overlap A/B (riding ``--serving-load`` via the
    DSTPU_COMM_OVERLAP=tiled env knob): the SAME TP-decode workload served
    twice — monolithic row-parallel psums, then per-tile collective rings
    (``comm_overlap: tiled``, T3-style) — on a ``data x model=2`` slice.
    Reports decode tok/s for both runs and the per-wire tile counts from
    the trace-time registry (how many independent collective programs each
    wire decomposed into — the structural lever the latency-hiding
    scheduler overlaps). Output gate: tiling is pure transport, so the
    tiled token streams must be BIT-IDENTICAL to the monolithic run — any
    divergence is a bug, not rounding. Composes with the int8 wire: set
    DSTPU_COMM_QUANT=int8 too and both arms run quantized, isolating the
    overlap delta. Knobs: DSTPU_COMM_OVERLAP (tiled enables),
    DSTPU_CO_TILES (tile count, default 4), DSTPU_CO_N (requests),
    DSTPU_CO_MAX_NEW (tokens per request)."""
    from deepspeed_tpu.comm.quantized import reset_wire_stats, wire_stats
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.parallel.topology import (
        Topology, reset_topology, set_topology,
    )

    ndev = len(jax.devices())
    if ndev < 2 or ndev % 2:
        return {"skipped": f"needs an even device count >= 2, have {ndev}"}
    tp = 2
    tiles = int(os.environ.get("DSTPU_CO_TILES", 4))
    comm_quant = os.environ.get("DSTPU_COMM_QUANT", "") or "none"
    n_requests = int(os.environ.get("DSTPU_CO_N", 4))
    max_new = int(os.environ.get("DSTPU_CO_MAX_NEW", 32))
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=256, hidden_size=256, n_layers=2, n_heads=4,
            n_kv_heads=2, max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size,
                            size=(int(rng.integers(8, 24)),)).astype(np.int32)
               for _ in range(n_requests)]

    def run(mode):
        reset_topology()
        set_topology(Topology(data=ndev // tp, model=tp))
        try:
            reset_wire_stats()
            rc = RaggedInferenceEngineConfig.from_dict({
                "dtype": cfg.dtype, "tp_size": tp, "comm_quant": comm_quant,
                "comm_overlap": mode, "tp_overlap_tiles": tiles,
                "kv_cache": {"block_size": 16, "num_blocks": 128,
                             "max_blocks_per_seq": 16},
                "state_manager": {"max_tracked_sequences": 64,
                                  "max_ragged_batch_size": 96,
                                  "max_ragged_sequence_count": 16,
                                  "max_context": 256},
            })
            engine = InferenceEngineV2(cfg, params, rc)
            engine.generate(prompts[:1], max_new_tokens=8)  # compile warmup
            t0 = time.perf_counter()
            outs = engine.generate(prompts, max_new_tokens=max_new)
            wall = time.perf_counter() - t0
            toks = sum(len(o) - len(p) for o, p in zip(outs, prompts))
            return {
                "tok_s": toks / wall if wall > 0 else 0.0,
                "outputs": [np.asarray(o).tolist() for o in outs],
                "wires": wire_stats(),
            }
        finally:
            reset_topology()

    base = run("none")
    tiled = run("tiled")
    if base["outputs"] != tiled["outputs"]:
        raise RuntimeError(
            "comm-overlap A/B output mismatch: tiled decode must be "
            "bit-identical to the monolithic wire (pure transport); "
            "divergence is a ring bug, not rounding"
        )
    return {
        "tp": tp,
        "comm_quant": comm_quant,
        "tp_overlap_tiles": tiles,
        "none_tok_s": round(base["tok_s"], 1),
        "tiled_tok_s": round(tiled["tok_s"], 1),
        "outputs_identical": True,
        "wire_tiles": {
            tag: w.get("tiles", 1) for tag, w in tiled["wires"].items()
        },
    }


def bench_disagg_replicas(n_replicas=2, cfg=None, params=None, seed=0):
    """Multi-replica serving A/B (``DSTPU_SERVE_REPLICAS=N`` rider on
    --serving-load): the same saturating workload — every request submitted
    up front, so the engines, not the arrival process, are the bottleneck —
    against (a) the single-engine ServingDriver and (b) a Router with N
    colocated decode replicas at EQUAL per-replica settings (same pool,
    same batch budget each). Reports aggregate decode goodput ratio and the
    per-replica utilization balance (min/max decode tokens — placement
    should keep it near 1)."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.serving.cluster import Router
    from deepspeed_tpu.serving.driver import ServingDriver
    from deepspeed_tpu.serving.request import SamplingParams

    n_replicas = int(n_replicas)
    n_requests = int(os.environ.get("DSTPU_SERVE_N", 24)) * 2
    max_new = int(os.environ.get("DSTPU_SERVE_MAX_NEW", 12)) * 2
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
            max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rc_dict = {
        "dtype": cfg.dtype,
        "kv_cache": {"block_size": 16, "num_blocks": 384,
                     "max_blocks_per_seq": 16},
        "state_manager": {"max_tracked_sequences": 64,
                          "max_ragged_batch_size": 96,
                          "max_ragged_sequence_count": 16,
                          "max_context": 256},
    }
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size, size=(int(l),)).astype(np.int32)
               for l in rng.integers(8, 32, size=n_requests)]

    def run(front):
        # warm pass = the full workload, unmeasured: every replica compiles
        # its step shapes (a single warm request would leave the OTHER
        # replicas compiling inside the measured window)
        warm = [front.submit(p, params=SamplingParams(max_new_tokens=max_new,
                                                      ignore_eos=True))
                for p in prompts]
        for r in warm:
            r.wait(300)
        t0 = time.perf_counter()
        reqs = [front.submit(p, params=SamplingParams(max_new_tokens=max_new,
                                                      ignore_eos=True))
                for p in prompts]
        for r in reqs:
            r.wait(300)
        wall = time.perf_counter() - t0
        done = [r for r in reqs if r.state == "finished"]
        return sum(len(r.generated) for r in done) / wall, len(done)

    single = ServingDriver(
        InferenceEngineV2(cfg, params,
                          RaggedInferenceEngineConfig.from_dict(rc_dict)),
        max_queue=n_requests + 1, kv_headroom=0.05,
    ).start()
    single_tok_s, single_done = run(single)
    single.shutdown(drain=True, timeout=60)

    engines = [
        InferenceEngineV2(cfg, params,
                          RaggedInferenceEngineConfig.from_dict(rc_dict))
        for _ in range(n_replicas)
    ]
    router = Router(engines=engines, num_prefill_workers=0,
                    max_queue=n_requests + 1, kv_headroom=0.05).start()
    multi_tok_s, multi_done = run(router)
    health = router.health()
    per_replica = {name: int(st["decode_tokens_total"])
                   for name, st in health["replicas"].items()}
    router.shutdown(drain=True, timeout=60)
    decode_counts = [v for v in per_replica.values()] or [0]
    balance = (min(decode_counts) / max(decode_counts)
               if max(decode_counts) else 0.0)
    return {
        "n_decode_replicas": n_replicas,
        "n_requests": n_requests,
        "max_new": max_new,
        "single_goodput_tok_s": round(single_tok_s, 1),
        "multi_goodput_tok_s": round(multi_tok_s, 1),
        "disagg_goodput_ratio": round(multi_tok_s / single_tok_s, 2)
        if single_tok_s else None,
        "completed": [single_done, multi_done],
        "replica_decode_tokens": per_replica,
        "utilization_balance": round(balance, 3),
    }


def parse_load_trace(spec):
    """``DSTPU_SERVE_LOAD_TRACE`` — a piecewise-Poisson arrival trace as
    ``"rate:dur,rate:dur,..."`` (requests/s : seconds). Bursty open-loop
    load is where the elastic control plane earns its keep; a single flat
    rate never exercises scale-up or the shed ladder."""
    segments = []
    for part in str(spec).split(","):
        rate, _, dur = part.strip().partition(":")
        rate, dur = float(rate), float(dur)
        if rate <= 0 or dur <= 0:
            raise ValueError(
                f"load trace segment {part!r}: rate and duration must be "
                "positive (format 'rate:dur,rate:dur')")
        segments.append((rate, dur))
    if not segments:
        raise ValueError("empty load trace")
    return segments


def bench_elastic_burst(trace, cfg=None, params=None, seed=0):
    """Elastic-serving burst benchmark (``DSTPU_SERVE_LOAD_TRACE`` rider
    on --serving-load): drive an elastic Router — 1 decode replica + 1
    warm spare, QoS tiers assigned round-robin, the shed ladder armed —
    with the piecewise-Poisson trace, and report what the control plane
    did: per-tier completion/shed/goodput/TTFT, preempt/resume counts,
    and scale-up/down decisions. The interesting number under burst is
    the interactive tier's p99 TTFT staying near its steady-state while
    the batch tier sheds first."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.serving import ElasticServingConfig, WarmSparePool
    from deepspeed_tpu.serving.cluster import Router
    from deepspeed_tpu.serving.driver import RequestRejected
    from deepspeed_tpu.serving.request import QOS_TIERS, SamplingParams

    segments = parse_load_trace(trace)
    max_new = int(os.environ.get("DSTPU_SERVE_MAX_NEW", 12))
    max_queue = int(os.environ.get("DSTPU_SERVE_QUEUE", 16))
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
            max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rc_dict = {
        "dtype": cfg.dtype,
        "kv_cache": {"block_size": 16, "num_blocks": 128,
                     "max_blocks_per_seq": 16},
        "state_manager": {"max_tracked_sequences": 32,
                          "max_ragged_batch_size": 96,
                          "max_ragged_sequence_count": 8,
                          "max_context": 256},
    }

    def mk():
        return InferenceEngineV2(cfg, params,
                                 RaggedInferenceEngineConfig.from_dict(rc_dict))

    ecfg = ElasticServingConfig(
        min_decode_replicas=1, max_decode_replicas=2,
        control_interval_s=0.05, scale_up_after=2, scale_down_after=40,
    )
    # the spare pre-traces the step programs at spawn: scale-up inside the
    # burst is wiring, not compiling (assert_warm_replicas pins it below)
    pool = WarmSparePool(factory=mk, count=1, warm_kw={"decode_steps": 1})
    router = Router(engines=[mk()], num_prefill_workers=0, elastic=ecfg,
                    spare_pool=pool, max_queue=max_queue,
                    kv_headroom=0.05).start()

    rng = np.random.default_rng(seed)
    tiers = sorted(QOS_TIERS, key=QOS_TIERS.get)  # interactive first
    reqs, shed = [], {t: 0 for t in tiers}
    warm = router.submit(
        rng.integers(0, cfg.vocab_size, size=(8,)).astype(np.int32),
        params=SamplingParams(max_new_tokens=2, ignore_eos=True))
    warm.wait(300)
    t0 = time.perf_counter()
    i = 0
    for rate, dur in segments:
        seg_end = time.perf_counter() + dur
        while time.perf_counter() < seg_end:
            time.sleep(float(rng.exponential(1.0 / rate)))
            tier = tiers[i % len(tiers)]
            i += 1
            prompt = rng.integers(
                0, cfg.vocab_size, size=(int(rng.integers(8, 32)),)
            ).astype(np.int32)
            try:
                reqs.append((tier, router.submit(
                    prompt,
                    params=SamplingParams(max_new_tokens=max_new,
                                          ignore_eos=True, qos=tier))))
            except RequestRejected:
                shed[tier] += 1
    for _, r in reqs:
        r.wait(300)
    wall = time.perf_counter() - t0
    new_traces = router.assert_warm_replicas()  # raises on a burst compile
    snap = router.metrics.snapshot()
    health = router.health()
    router.shutdown(drain=True, timeout=60)

    def pct(vals, q):
        return (round(float(np.percentile(np.asarray(vals), q)), 4)
                if vals else None)

    per_tier = {}
    for tier in tiers:
        mine = [r for t, r in reqs if t == tier]
        done = [r for r in mine if r.state == "finished"]
        ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
        per_tier[tier] = {
            "submitted": len(mine),
            "completed": len(done),
            "shed": shed[tier],
            "preempted": sum(r.preemptions for r in mine),
            "goodput_tok_s": round(
                sum(len(r.generated) for r in done) / wall, 1),
            "ttft_p50_s": pct(ttfts, 50),
            "ttft_p99_s": pct(ttfts, 99),
        }
    return {
        "trace": [list(s) for s in segments],
        "max_new": max_new,
        "max_queue": max_queue,
        "tiers": per_tier,
        "preempted_total": int(snap.get("requests_preempted_total", 0)),
        "resumed_total": int(snap.get("requests_resumed_total", 0)),
        "shed_total": int(snap.get("requests_shed_total", 0)),
        "scale_up_total": int(snap.get("scale_up_total", 0)),
        "scale_down_total": int(snap.get("scale_down_total", 0)),
        "decode_replicas_final": health["elastic"]["decode_replicas"],
        "warm_replicas_asserted": int(new_traces),
    }


def bench_serving_load(
    n_requests=None, rate_rps=None, max_new=None, slo_e2e_s=None,
    cfg=None, params=None, seed=0,
):
    """Serving-LOAD benchmark (``python bench.py --serving-load``): drive the
    full serving stack — ServingDriver admission/streaming over the v2
    engine — with Poisson arrivals (open-loop, the serving-systems standard:
    closed-loop clients hide queueing delay) and report the request-level
    numbers an operator actually SLOs on: TTFT, TPOT, e2e latency
    (p50/p95), and goodput (generated tok/s counting only requests that
    finished within the SLO). Runs on CPU with a tiny model by default;
    knobs via env: DSTPU_SERVE_N, DSTPU_SERVE_RATE, DSTPU_SERVE_MAX_NEW,
    DSTPU_SERVE_SLO_S.

    Prefix-caching knobs: DSTPU_SERVE_PREFIX_FRAC (fraction of requests
    that share a common system-prompt prefix, default 0 — set 0.8 to model
    a chat workload) and DSTPU_SERVE_PREFIX_CACHE (1 on / 0 off, default
    1). With a shared prefix the report splits TTFT by hit vs cold requests
    and adds the cache's hit-rate, so the cache's win is measured on the
    requests it actually serves."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.serving.driver import RequestRejected, ServingDriver
    from deepspeed_tpu.serving.request import SamplingParams

    n_requests = int(n_requests or os.environ.get("DSTPU_SERVE_N", 24))
    rate_rps = float(rate_rps or os.environ.get("DSTPU_SERVE_RATE", 16.0))
    max_new = int(max_new or os.environ.get("DSTPU_SERVE_MAX_NEW", 12))
    slo = slo_e2e_s or os.environ.get("DSTPU_SERVE_SLO_S")
    slo = float(slo) if slo is not None else None
    prefix_frac = float(os.environ.get("DSTPU_SERVE_PREFIX_FRAC", 0.0))
    prefix_cache = os.environ.get("DSTPU_SERVE_PREFIX_CACHE", "1") != "0"

    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
            max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    # per-step token budget 96: a cold system-prompt request needs 2-3
    # prefill steps, a cache hit needs one — TTFT then measures the steps
    # the cache actually removes (per-step overhead dominates tiny-model
    # prefill, so a within-step token discount alone would be invisible)
    rc = RaggedInferenceEngineConfig.from_dict({
        "dtype": cfg.dtype,
        "kv_cache": {"block_size": 16, "num_blocks": 384, "max_blocks_per_seq": 16,
                     "prefix_cache": prefix_cache},
        "state_manager": {"max_tracked_sequences": 64, "max_ragged_batch_size": 96,
                          "max_ragged_sequence_count": 16, "max_context": 256},
    })
    engine = InferenceEngineV2(cfg, params, rc)
    driver = ServingDriver(engine, max_queue=n_requests, kv_headroom=0.05)
    driver.start()

    rng = np.random.default_rng(seed)
    # a shared system prompt: 10 full blocks, so every sharing request hits
    # the same cached prefix; its unique tail still forces a real prefill
    sys_prompt = rng.integers(0, cfg.vocab_size, size=(160,)).astype(np.int32)
    shares = rng.random(n_requests) < prefix_frac
    prompts = []
    for i, l in enumerate(rng.integers(8, 32, size=n_requests)):
        tail = rng.integers(0, cfg.vocab_size, size=(int(l),)).astype(np.int32)
        prompts.append(np.concatenate([sys_prompt, tail]) if shares[i] else tail)
    # warm the compiled step shapes so the measured run isn't compile-bound;
    # the warm request also primes the cache with the system prompt (the
    # steady-state a live server reaches after one cold request)
    warm_tail = rng.integers(0, cfg.vocab_size, size=(8,)).astype(np.int32)
    warm_prompt = (np.concatenate([sys_prompt, warm_tail]) if prefix_frac > 0
                   else warm_tail)
    warm = driver.submit(warm_prompt, params=SamplingParams(max_new_tokens=4, ignore_eos=True))
    warm.wait(120)

    gaps = rng.exponential(1.0 / rate_rps, size=n_requests)
    reqs, rejected = [], 0
    req_shares = []
    t0 = time.perf_counter()
    for i, (prompt, gap) in enumerate(zip(prompts, gaps)):
        time.sleep(float(gap))
        try:
            reqs.append(driver.submit(
                prompt, params=SamplingParams(max_new_tokens=max_new, ignore_eos=True)
            ))
            req_shares.append(bool(shares[i]))
        except RequestRejected:
            rejected += 1
    for r in reqs:
        r.wait(300)
    wall = time.perf_counter() - t0
    cache = engine.prefix_cache
    cache_stats = cache.stats() if cache is not None else None
    driver.shutdown(drain=True, timeout=60)

    done = [r for r in reqs if r.state == "finished"]
    good = [r for r in done if slo is None or (r.e2e_s is not None and r.e2e_s <= slo)]

    def pct(vals, q):
        if not vals:
            return None
        return round(float(np.percentile(np.asarray(vals), q)), 4)

    ttfts = [r.ttft_s for r in done if r.ttft_s is not None]
    tpots = [r.tpot_s for r in done if r.tpot_s is not None]
    e2es = [r.e2e_s for r in done if r.e2e_s is not None]

    # hit-vs-cold TTFT split: "hit" = the request shared the system prefix
    # (with the cache on, its prefill skipped the shared blocks)
    hit_ttfts = [r.ttft_s for r, s in zip(reqs, req_shares)
                 if s and r.state == "finished" and r.ttft_s is not None]
    cold_ttfts = [r.ttft_s for r, s in zip(reqs, req_shares)
                  if not s and r.state == "finished" and r.ttft_s is not None]
    prefix_report = {}
    if prefix_frac > 0:
        prefix_report = {
            "prefix_frac": prefix_frac,
            "prefix_cache": prefix_cache,
            "ttft_hit_mean_s": (round(float(np.mean(hit_ttfts)), 4)
                                if hit_ttfts else None),
            "ttft_cold_mean_s": (round(float(np.mean(cold_ttfts)), 4)
                                 if cold_ttfts else None),
            "prefix_hit_rate": (round(cache_stats["hit_rate"], 3)
                                if cache_stats else 0.0),
            "prefix_hit_tokens": (int(cache_stats["hit_tokens"])
                                  if cache_stats else 0),
            "prefix_cached_blocks": (int(cache_stats["cached_blocks"])
                                     if cache_stats else 0),
            "prefix_evictions": (int(cache_stats["evictions"])
                                 if cache_stats else 0),
        }
    # spec decode A/B rider: DSTPU_SPEC_K>0 appends a draft-and-verify
    # vs plain-decode comparison on a decode-heavy workload
    spec_report = {}
    spec_k_env = int(os.environ.get("DSTPU_SPEC_K", 0))
    if spec_k_env > 0:
        spec_report = {"spec": bench_spec_ab(spec_k=spec_k_env, seed=seed)}
    # int8-KV A/B rider: DSTPU_KV_DTYPE=int8 appends a fixed-byte-budget
    # capacity + throughput + output-closeness comparison vs bf16 pools
    kv_report = {}
    if os.environ.get("DSTPU_KV_DTYPE", "") == "int8":
        kv_report = {"kv_int8": bench_kv_dtype_ab(seed=seed)}
    # tiered-KV host-store rider: DSTPU_KV_HOST_TIER_BYTES>0 appends an
    # evict→spill→readmit revisit-TTFT comparison vs plain re-prefill
    # under an eviction-forcing pool (streams must stay bit-identical)
    ht_report = {}
    if int(os.environ.get("DSTPU_KV_HOST_TIER_BYTES", "0") or 0) > 0:
        ht_report = {"kv_host_tier": bench_host_tier_ab(seed=seed)}
    # KV-transport A/B rider: DSTPU_KV_TRANSPORT=device|in_process appends
    # a disagg revisit-workload comparison vs the host numpy wire —
    # per-handoff latency, bytes/windows per handoff, revisit TTFT
    # (streams must stay bit-identical across transports)
    kvt_report = {}
    if os.environ.get("DSTPU_KV_TRANSPORT", ""):
        kvt_report = {"kv_transport": bench_kv_transport_ab(seed=seed)}
    # quantized-collectives A/B rider: DSTPU_COMM_QUANT=int8 appends a
    # TP-decode tok/s + per-wire byte-reduction comparison vs full width
    cq_report = {}
    if os.environ.get("DSTPU_COMM_QUANT", "") == "int8":
        cq_report = {"comm_quant_int8": bench_comm_quant_ab(seed=seed)}
    # tile-granular overlap A/B rider: DSTPU_COMM_OVERLAP=tiled appends a
    # TP-decode tok/s comparison (bit-identical outputs enforced) plus the
    # per-wire tile counts; composes with DSTPU_COMM_QUANT=int8
    co_report = {}
    if os.environ.get("DSTPU_COMM_OVERLAP", "") == "tiled":
        co_report = {"comm_overlap_tiled": bench_comm_overlap_ab(seed=seed)}
    # multi-replica rider: DSTPU_SERVE_REPLICAS=N (>=2) appends a Router
    # scale-out A/B — aggregate decode goodput vs the single driver at
    # equal per-replica settings, plus per-replica utilization balance
    disagg_report = {}
    n_repl = int(os.environ.get("DSTPU_SERVE_REPLICAS", "0") or 0)
    if n_repl >= 2:
        disagg_report = {"disagg": bench_disagg_replicas(
            n_replicas=n_repl, cfg=cfg, params=params, seed=seed)}
    # elastic burst rider: DSTPU_SERVE_LOAD_TRACE="rate:dur,rate:dur"
    # appends a piecewise-Poisson burst against the elastic Router —
    # per-tier goodput/TTFT, shed and preempt counts, scaling decisions
    elastic_report = {}
    load_trace = os.environ.get("DSTPU_SERVE_LOAD_TRACE", "")
    if load_trace:
        elastic_report = {"elastic_burst": bench_elastic_burst(
            load_trace, cfg=cfg, params=params, seed=seed)}
    # tracing-overhead rider: DSTPU_TRACE_AB=1 appends a tracing-on vs
    # tracing-off decode tok/s comparison and asserts the <=2% gate
    trace_report = {}
    if os.environ.get("DSTPU_TRACE_AB", "") == "1":
        trace_report = {"trace_overhead": bench_trace_overhead_ab(
            cfg=cfg, params=params, seed=seed)}
    # chaos rider: DSTPU_CHAOS=1 appends a fault-free vs faulted A/B on a
    # 2-replica router — recovery latency, goodput retention, and a
    # zero-divergence assertion on every recovered stream
    chaos_report = {}
    if os.environ.get("DSTPU_CHAOS", "") == "1":
        chaos_report = {"chaos": bench_chaos_ab(
            cfg=cfg, params=params, seed=seed)}
    return {
        "mode": "serving_load",
        "n_requests": n_requests,
        "offered_rps": rate_rps,
        "completed": len(done),
        "rejected": rejected,
        "timed_out": sum(1 for r in reqs if r.state == "timed_out"),
        "failed": sum(1 for r in reqs if r.state == "failed"),
        "ttft_p50_s": pct(ttfts, 50), "ttft_p95_s": pct(ttfts, 95),
        "tpot_p50_s": pct(tpots, 50), "tpot_p95_s": pct(tpots, 95),
        "e2e_p50_s": pct(e2es, 50), "e2e_p95_s": pct(e2es, 95),
        "slo_e2e_s": slo,
        "goodput_tok_s": round(sum(len(r.generated) for r in good) / wall, 1),
        "throughput_tok_s": round(sum(len(r.generated) for r in done) / wall, 1),
        **prefix_report,
        **spec_report,
        **kv_report,
        **ht_report,
        **kvt_report,
        **cq_report,
        **co_report,
        **disagg_report,
        **elastic_report,
        **trace_report,
        **chaos_report,
    }


def bench_chaos_ab(cfg=None, params=None, seed=0):
    """Chaos A/B (``python bench.py --chaos`` or riding ``--serving-load``
    via DSTPU_CHAOS=1): the SAME workload served by a 2-replica resilient
    Router twice — arm A fault-free, arm B under a deterministic fault
    schedule (a replica worker killed mid-stream plus one faulted
    handoff/checkpoint import). Reports the numbers an operator SLOs a
    failure on: recovery latency (injected fault -> each stream re-queued
    on a survivor, from the control-plane event log), goodput retention
    (faulted tok/s over fault-free tok/s), and recovery-route counts —
    and ASSERTS zero divergence: every recovered stream must be
    bit-identical to its fault-free twin (sampling keys are
    (seed, uid, position)-addressed, so a replica death must never change
    a single token). Knobs: DSTPU_CHAOS_N (requests), DSTPU_CHAOS_MAX_NEW
    (tokens per request), DSTPU_CHAOS_CRASH_NTH (worker-pass arrival that
    dies; later = deeper mid-stream)."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.observability.events import get_event_log
    from deepspeed_tpu.serving import Router
    from deepspeed_tpu.serving.request import SamplingParams
    from deepspeed_tpu.serving.resilience import (
        FaultSpec, ResilienceConfig, inject)

    n_requests = int(os.environ.get("DSTPU_CHAOS_N", 8))
    max_new = int(os.environ.get("DSTPU_CHAOS_MAX_NEW", 24))
    crash_nth = int(os.environ.get("DSTPU_CHAOS_CRASH_NTH", 12))
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
            max_seq_len=512, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rc_dict = {
        "dtype": cfg.dtype,
        "kv_cache": {"block_size": 16, "num_blocks": 192,
                     "max_blocks_per_seq": 16},
        "state_manager": {"max_tracked_sequences": 32,
                          "max_ragged_batch_size": 96,
                          "max_ragged_sequence_count": 8,
                          "max_context": 256},
    }
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size, size=(int(l),)).astype(np.int32)
               for l in rng.integers(8, 24, size=n_requests)]
    rcfg = ResilienceConfig(hung_step_s=5.0, probe_backoff_s=0.05,
                            retry_backoff_s=0.005)

    def run(schedule):
        engines = [
            InferenceEngineV2(cfg, params,
                              RaggedInferenceEngineConfig.from_dict(rc_dict))
            for _ in range(2)
        ]
        router = Router(engines=engines, num_prefill_workers=0,
                        max_queue=n_requests + 1, kv_headroom=0.05,
                        resilience=rcfg).start()
        try:
            warm = router.submit(prompts[0], params=SamplingParams(
                max_new_tokens=2, ignore_eos=True))
            warm.wait(300)
            with inject(*schedule) as inj:
                t0 = time.perf_counter()
                reqs = [router.submit(p, params=SamplingParams(
                    max_new_tokens=max_new, ignore_eos=True))
                    for p in prompts]
                for r in reqs:
                    r.wait(600)
                wall = time.perf_counter() - t0
            health = router.health()
        finally:
            router.shutdown(drain=True, timeout=60)
        done = [r for r in reqs if r.state == "finished"]
        return {
            "streams": [list(r.generated) for r in reqs],
            "completed": len(done),
            "tok_s": sum(len(r.generated) for r in done) / wall,
            "resilience": health["resilience"],
            "fired": inj.fired(),
        }

    base = run(())
    faulted = run((
        FaultSpec("worker.crash", nth=crash_nth, replica="d0"),
        FaultSpec("handoff.import", nth=1),
    ))

    divergent = sum(
        1 for a, b in zip(base["streams"], faulted["streams"]) if a != b)
    if divergent:
        raise AssertionError(
            f"chaos A/B: {divergent}/{n_requests} streams diverged after "
            "recovery — bit-identity is the contract, not a best effort")
    # recovery latency off the control-plane journal: injected-fault fire
    # time -> each request_recovered event it caused
    fired_ts = [f["t"] for f in faulted["fired"]]
    lat = []
    if fired_ts:
        t_fault = min(fired_ts)
        lat = sorted(e["t"] - t_fault
                     for e in get_event_log().recent()
                     if e.get("kind") == "request_recovered"
                     and e["t"] >= t_fault)
    res = faulted["resilience"]
    return {
        "n_requests": n_requests,
        "max_new": max_new,
        "faults_fired": [{k: f[k] for k in ("site", "replica", "nth")}
                         for f in faulted["fired"]],
        "completed": [base["completed"], faulted["completed"]],
        "divergent_streams": divergent,
        "recoveries": res["recoveries"],
        "recovery_checkpoints": res["recovery_checkpoints"],
        "recovery_replays": res["recovery_replays"],
        "quarantines": res["quarantines"],
        "handoff_retries": res["handoff_retries"],
        "recovery_latency_first_s": round(lat[0], 4) if lat else None,
        "recovery_latency_last_s": round(lat[-1], 4) if lat else None,
        "baseline_tok_s": round(base["tok_s"], 1),
        "faulted_tok_s": round(faulted["tok_s"], 1),
        "goodput_retention": (round(faulted["tok_s"] / base["tok_s"], 3)
                              if base["tok_s"] else None),
    }


def bench_trace_overhead_ab(cfg=None, params=None, seed=0, max_pct=None):
    """Tracing-overhead A/B (``python bench.py --trace-overhead`` or riding
    ``--serving-load`` via DSTPU_TRACE_AB=1): decode tok/s with the span
    tracer fully on — request trees, engine dispatch/device_wait hooks,
    the /debug/trace retention machinery — must stay within 2% of tracing
    off.  One serving stack serves both arms (no compile variance);
    trials alternate off/on so clock drift hits both equally, and each
    arm reports its best trial.  The gate ASSERTS: blowing past
    DSTPU_TRACE_AB_PCT (default 2.0) is a regression in the no-op path
    or a hot-loop span leak, not noise to wave off.
    Knobs: DSTPU_TRACE_N (requests/trial), DSTPU_TRACE_MAX_NEW,
    DSTPU_TRACE_TRIALS (per arm)."""
    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params
    from deepspeed_tpu.observability import NULL_TRACER, SpanTracer, set_tracer
    from deepspeed_tpu.serving.driver import ServingDriver
    from deepspeed_tpu.serving.request import SamplingParams

    # many SHORT trials, best-of per arm: scheduler/cgroup stalls only ever
    # slow a trial down, so the per-arm maximum converges on the machine's
    # true rate much faster than the mean of a few long trials does
    n_requests = int(os.environ.get("DSTPU_TRACE_N", 4))
    max_new = int(os.environ.get("DSTPU_TRACE_MAX_NEW", 32))
    trials = int(os.environ.get("DSTPU_TRACE_TRIALS", 10))
    max_pct = float(max_pct if max_pct is not None
                    else os.environ.get("DSTPU_TRACE_AB_PCT", 2.0))
    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=256, hidden_size=256, n_layers=2, n_heads=4,
            max_seq_len=1024, dtype="float32",
        )
        params = init_params(cfg, jax.random.key(0))
    rc = RaggedInferenceEngineConfig.from_dict({
        "dtype": cfg.dtype,
        "kv_cache": {"block_size": 16, "num_blocks": 384,
                     "max_blocks_per_seq": 16},
        "state_manager": {"max_tracked_sequences": 64,
                          "max_ragged_batch_size": 96,
                          "max_ragged_sequence_count": 16,
                          "max_context": 256},
    })
    engine = InferenceEngineV2(cfg, params, rc)
    driver = ServingDriver(engine, max_queue=n_requests + 1).start()

    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, cfg.vocab_size, size=(int(l),)).astype(np.int32)
               for l in rng.integers(4, 12, size=n_requests)]

    def trial():
        # clock on process CPU time, not wall: cgroup throttling and noisy
        # neighbours stall the wall clock but never accrue CPU, so tok per
        # CPU-second isolates the work tracing itself adds (the wall-clock
        # variance on a shared box dwarfs a 2% gate; CPU time does not)
        c0 = time.process_time()
        reqs = [driver.submit(p, params=SamplingParams(
            max_new_tokens=max_new, ignore_eos=True)) for p in prompts]
        for r in reqs:
            r.wait(600)
        cpu = time.process_time() - c0
        toks = sum(len(r.generated) for r in reqs if r.state == "finished")
        assert toks == n_requests * max_new, "trial did not finish cleanly"
        return toks / cpu

    try:
        set_tracer(NULL_TRACER)
        trial()  # warm the compiled shapes outside both arms
        pairs = []
        for _ in range(trials):
            set_tracer(NULL_TRACER)
            a = trial()
            set_tracer(SpanTracer())
            b = trial()
            pairs.append((a, b))
    finally:
        set_tracer(NULL_TRACER)
        driver.shutdown(drain=True, timeout=60)

    # residual CPU-time noise (GC, allocator) is still one-sided, so judge
    # the median ratio of the 3 calmest back-to-back pairs
    calm = sorted(pairs, key=lambda p: p[0] + p[1], reverse=True)[:3]
    ratios = sorted(b / a for a, b in calm)
    overhead_pct = (1.0 - ratios[len(ratios) // 2]) * 100.0
    off_best, on_best = calm[0]
    if overhead_pct > max_pct:
        raise AssertionError(
            f"tracing overhead {overhead_pct:.2f}% exceeds the {max_pct}% "
            f"gate (off {off_best:.1f} tok/s vs on {on_best:.1f} tok/s)")
    return {
        "n_requests": n_requests,
        "max_new": max_new,
        "trials_per_arm": trials,
        "off_tok_s": round(off_best, 1),
        "on_tok_s": round(on_best, 1),
        "overhead_pct": round(overhead_pct, 3),
        "gate_pct": max_pct,
        "within_gate": True,
    }


if __name__ == "__main__":
    import sys

    if "--serving-load" in sys.argv[1:]:
        print(json.dumps(bench_serving_load()))
    elif "--trace-overhead" in sys.argv[1:]:
        print(json.dumps(bench_trace_overhead_ab()))
    elif "--chaos" in sys.argv[1:]:
        print(json.dumps(bench_chaos_ab()))
    else:
        main()
