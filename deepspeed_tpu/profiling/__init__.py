"""Profiling (reference deepspeed/profiling/): jaxpr/XLA-cost-model flops
profiler."""

from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    analyze_fn,
    jaxpr_flops_by_primitive,
    num_to_string,
)

__all__ = ["FlopsProfiler", "analyze_fn", "jaxpr_flops_by_primitive", "num_to_string"]
