"""FLOPS profiler — jaxpr/XLA cost analysis instead of module hooks.

Analogue of the reference ``profiling/flops_profiler/profiler.py:30``
(``FlopsProfiler``): the reference monkey-patches ``torch.nn.functional`` to
count MACs per module; on TPU the compiler already knows — XLA's
``cost_analysis()`` gives whole-program flops/bytes, and walking the jaxpr
gives the per-primitive breakdown (the "module depth" of a functional
program). The reference's printed-profile surface (total flops/params/
duration, top items, optional file output) is preserved.
"""

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import log_dist, logger


def _dot_flops(eqn) -> float:
    """2*M*N*K flops for a dot_general from its shapes."""
    a, b = eqn.invars[0].aval, eqn.invars[1].aval
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    batch = 1.0
    for d in lb:
        batch *= a.shape[d]
    k = 1.0
    for d in lc:
        k *= a.shape[d]
    m = 1.0
    for i, d in enumerate(a.shape):
        if i not in lc and i not in lb:
            m *= d
    n = 1.0
    for i, d in enumerate(b.shape):
        if i not in rc and i not in rb:
            n *= d
    return 2.0 * batch * m * n * k


def jaxpr_flops_by_primitive(jaxpr, scale: float = 1.0) -> Dict[str, float]:
    """Recursively aggregate matmul flops + op counts per primitive. Scans
    multiply their body by the trip count; inner jaxprs (pjit/remat/custom
    vjp) recurse at the same scale."""
    out: Dict[str, float] = {}

    def add(name, val):
        out[name] = out.get(name, 0.0) + val

    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim == "dot_general":
            add("dot_general", _dot_flops(eqn) * scale)
            continue
        if prim == "scan":
            inner = jaxpr_flops_by_primitive(
                eqn.params["jaxpr"].jaxpr, scale * eqn.params["length"]
            )
            for k, v in inner.items():
                add(k, v)
            continue
        sub = None
        for key in ("jaxpr", "call_jaxpr"):
            if key in eqn.params:
                sub = eqn.params[key]
                break
        if sub is not None:
            sub_jaxpr = sub.jaxpr if hasattr(sub, "jaxpr") else sub
            for k, v in jaxpr_flops_by_primitive(sub_jaxpr, scale).items():
                add(k, v)
            continue
        if prim == "while":
            # trip count is dynamic: count ONE body iteration (a lower bound)
            # and surface the loop marker so readers know it's per-iteration
            body = eqn.params.get("body_jaxpr")
            if body is not None:
                for k, v in jaxpr_flops_by_primitive(body.jaxpr, scale).items():
                    add(k if k.startswith("#") else f"{k}(per while iter)", v)
            add("#while", scale)
            continue
        if prim == "cond":
            # one branch executes: take the max (upper bound), not the sum
            branch_costs = [
                jaxpr_flops_by_primitive(br.jaxpr, scale)
                for br in eqn.params.get("branches", ())
            ]
            keys = {k for bc in branch_costs for k in bc}
            for k in keys:
                add(k, max(bc.get(k, 0.0) for bc in branch_costs))
            continue
        # non-matmul primitive: count invocations (elementwise/collective mix)
        add(f"#{prim}", scale)
    return out


def analyze_fn(fn: Callable, *args, **kwargs) -> Dict[str, Any]:
    """Lower ``fn`` and return {'flops', 'bytes_accessed', 'optimal_seconds',
    'by_primitive'} — flops/bytes from XLA's own cost model, breakdown from
    the jaxpr."""
    lowered = jax.jit(fn).lower(*args, **kwargs)
    cost = lowered.compile().cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # pre-0.5 jax: one dict per device
        cost = cost[0] if cost else {}
    jaxpr = jax.make_jaxpr(fn)(*args, **kwargs)
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "optimal_seconds": float(cost.get("optimal_seconds", 0.0)),
        "by_primitive": jaxpr_flops_by_primitive(jaxpr.jaxpr),
    }


def num_to_string(num: float, precision: int = 2) -> str:
    if num >= 1e12:
        return f"{num / 1e12:.{precision}f} T"
    if num >= 1e9:
        return f"{num / 1e9:.{precision}f} G"
    if num >= 1e6:
        return f"{num / 1e6:.{precision}f} M"
    if num >= 1e3:
        return f"{num / 1e3:.{precision}f} K"
    return f"{num:.{precision}f}"


class FlopsProfiler:
    """Reference-API profiler over a jax step function.

    Typical flow (mirrors profiler.py usage):
        prof = FlopsProfiler()
        prof.start_profile()
        out = step_fn(*args)            # one profiled execution
        prof.stop_profile(step_fn, *args)
        prof.print_model_profile()
        prof.end_profile()
    The engine drives this automatically at ``flops_profiler.profile_step``.
    """

    def __init__(self, model: Optional[Callable] = None, ds_engine=None):
        self.model = model
        self.ds_engine = ds_engine
        self.started = False
        self._t0 = 0.0
        self._duration = 0.0
        self._analysis: Dict[str, Any] = {}
        self._n_params = 0

    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.perf_counter()

    def stop_profile(self, fn: Optional[Callable] = None, *args, **kwargs):
        if not self.started:
            return
        self._duration = time.perf_counter() - self._t0
        if fn is not None:
            self._analysis = analyze_fn(fn, *args, **kwargs)

    def reset_profile(self):
        self._analysis = {}
        self._duration = 0.0

    def end_profile(self):
        self.started = False
        self.reset_profile()

    # -- reference getters --------------------------------------------------
    def get_total_flops(self, as_string: bool = False):
        f = self._analysis.get("flops", 0.0)
        return num_to_string(f) + "FLOPS" if as_string else f

    def get_total_macs(self, as_string: bool = False):
        m = self._analysis.get("flops", 0.0) / 2.0
        return num_to_string(m) + "MACs" if as_string else m

    def get_total_duration(self, as_string: bool = False):
        return f"{self._duration * 1e3:.2f} ms" if as_string else self._duration

    def set_total_params(self, params: Any):
        from deepspeed_tpu.models import num_params

        self._n_params = num_params(params)

    def get_total_params(self, as_string: bool = False):
        return num_to_string(self._n_params) if as_string else self._n_params

    def print_model_profile(
        self,
        profile_step: int = 1,
        module_depth: int = -1,
        top_modules: int = 1,
        detailed: bool = True,
        output_file: Optional[str] = None,
    ):
        lines = [
            "-" * 60,
            f"DeepSpeed-TPU Flops Profiler (step {profile_step})",
            "-" * 60,
            f"params:               {self.get_total_params(True)}",
            f"fwd+bwd+step flops:   {self.get_total_flops(True)}",
            f"bytes accessed:       {num_to_string(self._analysis.get('bytes_accessed', 0))}B",
            f"measured duration:    {self.get_total_duration(True)}",
        ]
        dur = self._duration
        if dur > 0 and self._analysis.get("flops"):
            lines.append(f"achieved:             {num_to_string(self._analysis['flops'] / dur)}FLOPS/s")
        if detailed and self._analysis.get("by_primitive"):
            lines.append("matmul flops by primitive / op counts:")
            items = sorted(
                self._analysis["by_primitive"].items(), key=lambda kv: -kv[1]
            )[: max(top_modules, 1)]
            for k, v in items:
                if k.startswith("#"):
                    lines.append(f"  {k:<28} x{int(v)}")
                else:
                    lines.append(f"  {k:<28} {num_to_string(v)}FLOPS")
        lines.append("-" * 60)
        text = "\n".join(lines)
        if output_file:
            if jax.process_index() == 0:  # one writer on shared filesystems
                with open(output_file, "w") as f:
                    f.write(text + "\n")
        else:
            log_dist(text, ranks=[0])
        return text
