"""Compression transforms: functional analogues of the reference's
``compression/basic_layer.py`` mixins (``LinearLayer_Compress`` — weight/
activation quantization, sparse/row/head pruning).

The torch reference mutates module forwards; here each technique is a pure
leaf transform applied to matched parameters (QAT fake-quant during
training, masks for pruning), selected by path patterns like the reference's
``different_groups`` ``modules`` lists.
"""

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def match_leaves(params: Any, patterns: Sequence[str]) -> List[Tuple[tuple, Any]]:
    """(path, leaf) pairs matching any pattern by FULL path segments
    ('layer_1' does not match 'layer_10'; '*' matches everything — the
    reference's catch-all group)."""
    from deepspeed_tpu.utils.pytree import path_str, segments_match

    out = []
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        name = path_str(path)
        if any(segments_match(name, p) for p in patterns):
            out.append((path, leaf))
    return out


# norms/biases/embedding gathers are not matmul weights: the reference scopes
# techniques to Linear modules; the catch-all '*' group must not QAT-distort
# normalization scales (they are also what stacked [L, h] leaves mostly are)
NON_WEIGHT_PATTERNS = ("norm", "bias", "ln_", "layernorm", "embed", "pos_embed")


def _is_weight_leaf(name: str, leaf) -> bool:
    if getattr(leaf, "ndim", 0) < 2:
        return False
    last = name.rsplit("/", 1)[-1]
    return not any(p in last for p in NON_WEIGHT_PATTERNS)


def _apply_to_matched(params, patterns, leaf_fn, weights_only: bool = True):
    from deepspeed_tpu.utils.pytree import path_str

    matched_paths = {tuple(p) for p, _ in match_leaves(params, patterns)}

    def visit(path, leaf):
        if tuple(path) not in matched_paths:
            return leaf
        if weights_only and not _is_weight_leaf(path_str(path), leaf):
            return leaf
        return leaf_fn(leaf)

    return jax.tree_util.tree_map_with_path(visit, params)


# ---------------------------------------------------------------------------
# quantization (QAT fake-quant)
# ---------------------------------------------------------------------------
def fake_quantize(x: jax.Array, bits: int, symmetric: bool = True) -> jax.Array:
    """Quantize-dequantize at ``bits`` (reference LinearLayer_Compress weight
    quantization forward): straight-through in backward (the round is wrapped
    in a stop-gradient identity). Leading dims beyond the last two (stacked
    layers / experts) get their OWN scales — one global absmax across a
    [L, in, out] stack would let one hot layer crush the others' precision."""
    levels = 2.0 ** (bits - 1) - 1 if symmetric else 2.0**bits - 1
    xf = x.astype(jnp.float32)
    reduce_axes = tuple(range(max(xf.ndim - 2, 0), xf.ndim))  # last two dims
    if symmetric:
        scale = jnp.max(jnp.abs(xf), axis=reduce_axes, keepdims=True) / levels
        scale = jnp.maximum(scale, 1e-12)
        q = jnp.round(xf / scale)
        deq = jnp.clip(q, -levels, levels) * scale
    else:
        lo = jnp.min(xf, axis=reduce_axes, keepdims=True)
        hi = jnp.max(xf, axis=reduce_axes, keepdims=True)
        scale = jnp.maximum((hi - lo) / levels, 1e-12)
        q = jnp.round((xf - lo) / scale)
        deq = jnp.clip(q, 0, levels) * scale + lo
    # straight-through estimator: forward sees deq, backward sees identity
    return (xf + jax.lax.stop_gradient(deq - xf)).astype(x.dtype)


def quantize_weights(params, patterns: Sequence[str], bits: int, symmetric: bool = True):
    return _apply_to_matched(params, patterns, lambda w: fake_quantize(w, bits, symmetric))


def quantize_activation(x: jax.Array, bits: int, range_calibration: str = "dynamic") -> jax.Array:
    """Reference QuantAct (basic_layer.py:17): activation fake-quant."""
    return fake_quantize(x, bits, symmetric=True)


# ---------------------------------------------------------------------------
# pruning masks
# ---------------------------------------------------------------------------
def sparse_mask(w: jax.Array, dense_ratio: float, method: str = "l1") -> jax.Array:
    """Unstructured magnitude mask keeping the top ``dense_ratio`` fraction
    per matrix (reference sparse_pruning l1/topk); stacked leading dims each
    threshold independently."""
    lead = w.shape[:-2] if w.ndim > 2 else ()
    a = jnp.abs(w.astype(jnp.float32)).reshape(lead + (-1,))
    k = max(int(a.shape[-1] * dense_ratio), 1)
    thresh = jnp.sort(a, axis=-1)[..., -k][..., None]
    return (a >= thresh).reshape(w.shape).astype(w.dtype)


def row_mask(w: jax.Array, dense_ratio: float) -> jax.Array:
    """Structured row mask by L2 norm over the last two dims ([.., in, out]:
    prune OUTPUT features — reference nn.Linear rows). Leading dims (stacked
    layers) each get their own mask."""
    norms = jnp.linalg.norm(w.astype(jnp.float32), axis=-2)  # [.., out]
    k = max(int(norms.shape[-1] * dense_ratio), 1)
    thresh = jnp.sort(norms, axis=-1)[..., -k][..., None]
    keep = (norms >= thresh).astype(w.dtype)  # [.., out]
    return jnp.broadcast_to(keep[..., None, :], w.shape)


def head_mask(w: jax.Array, num_heads: int, dense_ratio: float) -> jax.Array:
    """Attention-head mask: [.., in, H*d] weights pruned per head by L2 norm
    (reference head_pruning on the attention output projection); stacked
    leading dims get independent per-layer masks."""
    in_dim, out_dim = w.shape[-2], w.shape[-1]
    if out_dim % num_heads != 0:
        raise ValueError(f"out dim {out_dim} not divisible by heads {num_heads}")
    d = out_dim // num_heads
    lead = w.shape[:-2]
    per_head = jnp.linalg.norm(
        w.astype(jnp.float32).reshape(lead + (in_dim, num_heads, d)), axis=(-3, -1)
    )  # [.., H]
    k = max(int(num_heads * dense_ratio), 1)
    thresh = jnp.sort(per_head, axis=-1)[..., -k][..., None]
    keep = (per_head >= thresh).astype(w.dtype)  # [.., H]
    keep = jnp.repeat(keep, d, axis=-1)  # [.., H*d]
    return jnp.broadcast_to(keep[..., None, :], w.shape)


def prune_weights(params, patterns, dense_ratio, method: str = "sparse", num_heads: int = 0):
    def leaf_fn(w):
        if getattr(w, "ndim", 0) < 2:
            return w
        if method == "sparse":
            return w * sparse_mask(w, dense_ratio)
        if method == "row":
            return w * row_mask(w, dense_ratio)
        if method == "head":
            return w * head_mask(w, num_heads, dense_ratio)
        raise ValueError(f"unknown pruning method {method!r}")

    return _apply_to_matched(params, patterns, leaf_fn)


def sparsity(params, patterns=("*",)) -> float:
    """Realized zero fraction over matched leaves."""
    total, zeros = 0, 0
    for _, leaf in match_leaves(params, patterns):
        if getattr(leaf, "ndim", 0) >= 2:
            total += leaf.size
            zeros += int(jnp.sum(leaf == 0))
    return zeros / max(total, 1)


# ---------------------------------------------------------------------------
# layer reduction (depth distillation prep)
# ---------------------------------------------------------------------------
def reduce_layers(params: Dict[str, Any], keep_layers: Sequence[int], layers_key: str = "layers"):
    """Reference layer_reduction: keep only the listed layer indices of the
    stacked [L, ...] layer pytree (student initialization from teacher
    depths)."""
    idx = jnp.asarray(list(keep_layers), jnp.int32)
    out = dict(params)
    out[layers_key] = jax.tree.map(lambda l: l[idx], params[layers_key])
    return out
