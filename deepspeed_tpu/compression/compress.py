"""Compression entry points (reference ``compression/compress.py``):
``init_compression`` builds the per-step param transform from the
``compression_training`` config section; ``redundancy_clean`` materializes
the masks permanently (the reference's post-training cleanup)."""

from typing import Any, Callable, Dict, Tuple

from deepspeed_tpu.compression.scheduler import CompressionScheduler
from deepspeed_tpu.compression.transforms import (
    prune_weights,
    quantize_weights,
    reduce_layers,
)
from deepspeed_tpu.utils.logging import log_dist


def init_compression(
    params: Any, deepspeed_config: Dict[str, Any], teacher_model=None, mpu=None
) -> Tuple[Any, CompressionScheduler, Callable[[Any, int], Any]]:
    """Returns (params, scheduler, compress_fn) where
    ``compress_fn(params, step)`` applies the techniques active at ``step``
    (call it on the params fed to the loss — QAT fake-quant + masks are pure
    transforms, safe under jit). Layer reduction applies immediately, like
    the reference's student init."""
    ccfg = deepspeed_config.get("compression_training", {}) or {}
    sched = CompressionScheduler.from_config(ccfg)

    aq = sched.techniques.get("activation_quantization")
    if aq is not None and aq.enabled:
        log_dist(
            "activation_quantization: wrap activations with "
            "compression.quantize_activation(x, sched.techniques"
            "['activation_quantization'].bits_at(step)) — a functional loss "
            "cannot be rewritten in place (reference QuantAct swap)",
            ranks=[0],
        )

    lr_cfg = ccfg.get("layer_reduction", {}) or {}
    if lr_cfg.get("enabled"):
        keep = lr_cfg.get("teacher_layer", lr_cfg.get("keep_layers"))
        if not keep:
            raise ValueError("layer_reduction requires 'teacher_layer' (kept layer indices)")
        params = reduce_layers(params, keep)
        log_dist(f"layer_reduction: kept layers {list(keep)}", ranks=[0])

    def compress_fn(p: Any, step=None, final: bool = False) -> Any:
        """Apply active techniques. ``final=True`` (or step=None) applies
        every ENABLED technique at its fully-ramped state — the bake path."""
        if final or step is None:
            active = {n: t for n, t in sched.techniques.items() if t.enabled}
            bits_step = None
        else:
            active = sched.active_techniques(step)
            bits_step = step
        wq = active.get("weight_quantization")
        if wq:
            p = quantize_weights(p, wq.patterns, wq.bits_at(bits_step))
        sp = active.get("sparse_pruning")
        if sp:
            p = prune_weights(p, sp.patterns, sp.dense_ratio, method="sparse")
        rp = active.get("row_pruning")
        if rp:
            p = prune_weights(p, rp.patterns, rp.dense_ratio, method="row")
        hp = active.get("head_pruning")
        if hp:
            p = prune_weights(p, hp.patterns, hp.dense_ratio, method="head", num_heads=hp.num_heads)
        return p

    return params, sched, compress_fn


def redundancy_clean(params: Any, deepspeed_config: Dict[str, Any], mpu=None) -> Any:
    """Bake the final masks into TRAINED weights (reference redundancy_clean
    — the torch version also re-dims modules; functional params keep their
    shapes, zeros carry the pruning). ``params`` are post-training: layer
    reduction already happened at init and is NOT re-applied; quantization
    bakes at target bits, pruning at its final masks regardless of schedule
    windows."""
    cfg = dict(deepspeed_config)
    ccfg = dict(cfg.get("compression_training", {}) or {})
    ccfg.pop("layer_reduction", None)  # applied once, at init
    cfg["compression_training"] = ccfg
    _, _, compress_fn = init_compression(params, cfg)
    return compress_fn(params, final=True)
