"""Compression suite (reference deepspeed/compression/, 2.4k LoC)."""

from deepspeed_tpu.compression.compress import init_compression, redundancy_clean
from deepspeed_tpu.compression.scheduler import CompressionScheduler, TechniqueSchedule
from deepspeed_tpu.compression.transforms import (
    fake_quantize,
    head_mask,
    prune_weights,
    quantize_activation,
    quantize_weights,
    reduce_layers,
    row_mask,
    sparse_mask,
    sparsity,
)

__all__ = [
    "CompressionScheduler",
    "TechniqueSchedule",
    "fake_quantize",
    "head_mask",
    "init_compression",
    "prune_weights",
    "quantize_activation",
    "quantize_weights",
    "redundancy_clean",
    "reduce_layers",
    "row_mask",
    "sparse_mask",
    "sparsity",
]
