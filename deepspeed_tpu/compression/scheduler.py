"""Compression scheduler (reference ``compression/scheduler.py``): each
technique activates at its ``schedule_offset`` (and optionally ends at
``schedule_offset_end``); weight-quantization bits can ramp down in stages
(the MoQ-style start→target halving)."""

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class TechniqueSchedule:
    enabled: bool = False
    schedule_offset: int = 0
    schedule_offset_end: Optional[int] = None
    # weight quantization extras
    start_bits: int = 8
    target_bits: int = 8
    quantize_period: int = 0  # steps between bit halvings (0 = jump to target)
    # pruning extras
    dense_ratio: float = 1.0
    num_heads: int = 0
    patterns: tuple = ("*",)

    def active(self, step: int) -> bool:
        if not self.enabled or step < self.schedule_offset:
            return False
        if self.schedule_offset_end is not None and step > self.schedule_offset_end:
            return False
        return True

    def bits_at(self, step: Optional[int]) -> int:
        """MoQ-style halving from start_bits toward target_bits every
        quantize_period steps after activation (reference quantize.py).
        ``step=None`` = fully ramped (export/bake time)."""
        if step is None:
            return self.target_bits
        if not self.active(step) or self.quantize_period <= 0:
            return self.target_bits if self.active(step) else self.start_bits
        halvings = (step - self.schedule_offset) // self.quantize_period
        bits = self.start_bits
        for _ in range(halvings):
            if bits <= self.target_bits:
                break
            bits = max(bits // 2, self.target_bits)
        return bits


class CompressionScheduler:
    """Holds per-technique schedules and answers 'what applies at step N'."""

    def __init__(self, techniques: Dict[str, TechniqueSchedule]):
        self.techniques = techniques

    @classmethod
    def from_config(cls, compression_cfg: Dict[str, Any]) -> "CompressionScheduler":
        techs = {}
        for name in (
            "weight_quantization",
            "activation_quantization",
            "sparse_pruning",
            "row_pruning",
            "head_pruning",
        ):
            section = compression_cfg.get(name, {}) or {}
            shared = section.get("shared_parameters", {}) or {}
            groups = section.get("different_groups", {}) or {}
            params: Dict[str, Any] = {
                "enabled": shared.get("enabled", False),
                "schedule_offset": shared.get("schedule_offset", 0),
                "schedule_offset_end": shared.get("schedule_offset_end"),
            }
            # first group supplies technique knobs (reference groups each
            # carry their own params; one group covers the common case)
            if groups:
                g = next(iter(groups.values()))
                gp = g.get("params", {})
                params["start_bits"] = gp.get("start_bits", 8)
                params["target_bits"] = gp.get("target_bits", gp.get("bits", 8))
                params["dense_ratio"] = gp.get("dense_ratio", 1.0)
                params["num_heads"] = gp.get("num_heads", 0)
                params["patterns"] = tuple(g.get("modules", ["*"]))
                params["quantize_period"] = shared.get("quantize_period", 0)
            tech = TechniqueSchedule(**params)
            if name == "head_pruning" and tech.enabled and tech.num_heads <= 0:
                raise ValueError(
                    "head_pruning requires 'num_heads' in its group params "
                    "(fail at config parse, not mid-training)"
                )
            techs[name] = tech
        return cls(techs)

    def active_techniques(self, step: int):
        return {n: t for n, t in self.techniques.items() if t.active(step)}
