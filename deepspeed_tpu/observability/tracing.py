"""Monotonic-clock span tracer with per-request trees and a global ring.

Span model
----------

A :class:`Span` is a ``[t0, t1)`` interval on ``time.monotonic()`` with a
``span_id``/``parent_id`` pair, a ``track`` (timeline row: engine name or
``"request:<uid>"``), and an open ``args`` dict for payload (batch sizes,
drained-token counts, block counts, ...).

Spans live in one of two places:

* **request trees** — keyed by request uid; a single rooted tree covering
  queued -> admission/placement -> prefill -> handoff -> decode rounds ->
  finish (plus preempt/resume phases when the elastic planner fires).
  Trees move to a bounded completed-trace ring at ``end_trace``, subject
  to the capture policy (``all`` | ``slow``).
* **the engine ring** — spans with no request key (engine step rounds,
  dispatch vs device-wait brackets, host-tier readmits) in one bounded
  ``deque``; these render as per-engine timeline rows in the export.

Thread safety: one lock guards id allocation and every container
mutation; ``end()`` only stores into an already-published span and needs
no lock.

Disabled path: :data:`NULL_TRACER` is installed by default.  Every method
returns the shared :data:`_NULL_SPAN` singleton (its own no-op context
manager), so ``with get_tracer().span(...):`` costs no allocation when
tracing is off — callers only guard *args construction* behind
``tracer.enabled``.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = [
    "NULL_TRACER",
    "NullTracer",
    "Span",
    "SpanTracer",
    "TraceContext",
    "begin_request_trace",
    "configure_tracing",
    "finish_request_trace",
    "get_tracer",
    "mark_admitted",
    "mark_first_token",
    "mark_preempted",
    "mark_resumed",
    "set_tracer",
]


class Span:
    """One timed interval.  ``t1 is None`` while the span is open."""

    __slots__ = ("span_id", "parent_id", "name", "track", "t0", "t1", "args")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 track: str, t0: float, args: Optional[dict] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.track = track
        self.t0 = t0
        self.t1: Optional[float] = None
        self.args = args

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def to_dict(self) -> dict:
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "track": self.track,
            "t0": self.t0,
            "t1": self.t1,
            "args": dict(self.args) if self.args else {},
        }

    def __repr__(self):  # pragma: no cover - debug aid
        dur = self.duration_s
        return (f"Span({self.name!r}, id={self.span_id}, "
                f"parent={self.parent_id}, "
                f"dur={'open' if dur is None else f'{dur * 1e3:.3f}ms'})")


class _SpanHandle:
    """Context manager returned by ``SpanTracer.span``."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "SpanTracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, exc_type, exc, tb):
        self._tracer.end(self.span)
        return False


class _NullSpan:
    """Shared do-nothing span: its own context manager, ends are no-ops."""

    __slots__ = ()

    span_id = -1
    parent_id = None
    name = ""
    track = ""
    t0 = 0.0
    t1 = 0.0
    args = None
    span = None  # mirror _SpanHandle.span for uniform `with ... as sp:` use
    duration_s = 0.0

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_SPAN = _NullSpan()
# `with null.span(...) as sp:` must hand back the same singleton
_NullSpan.span = _NULL_SPAN


class NullTracer:
    """Tracing-off singleton: every method is a constant-return no-op."""

    enabled = False

    def now(self) -> float:
        return 0.0

    def begin_trace(self, key, name, t0=None, args=None):
        return _NULL_SPAN

    def start(self, key, name, parent=None, t0=None, track=None, args=None):
        return _NULL_SPAN

    def end(self, span, t1=None, args=None):
        pass

    def complete(self, name, t0, t1=None, key=None, parent=None, track=None,
                 args=None):
        return _NULL_SPAN

    def instant(self, name, key=None, track=None, t=None, args=None):
        return _NULL_SPAN

    def span(self, name, key=None, parent=None, track=None, args=None):
        return _NULL_SPAN

    def end_trace(self, key, slow_hint=False, meta=None):
        return False

    def trace(self, key):
        return None

    def recent(self):
        return []

    def ring_spans(self):
        return []

    def stats(self):
        return {"enabled": False}


NULL_TRACER = NullTracer()


class SpanTracer:
    """Thread-safe bounded span tracer (see module docstring)."""

    enabled = True

    #: reservoir size for the slow-capture latency percentile
    RESERVOIR = 256
    #: keep everything until the reservoir has this many samples
    WARMUP = 32

    def __init__(self, max_events: int = 65536, capture: str = "all",
                 slow_quantile: float = 0.90):
        if capture not in ("all", "slow"):
            raise ValueError(f"capture must be 'all' or 'slow', got {capture!r}")
        if max_events < 256:
            max_events = 256
        self.max_events = int(max_events)
        self.capture = capture
        self.slow_quantile = float(slow_quantile)
        self._lock = threading.Lock()
        self._next_id = 1
        # uid -> list[Span]; first span is the root
        self._active: Dict[Any, List[Span]] = {}
        # completed request traces: list of dicts, bounded by total span budget
        self._done: deque = deque()
        self._done_events = 0
        # global engine/control spans (no request key)
        self._ring: deque = deque(maxlen=self.max_events)
        self._e2e_samples: deque = deque(maxlen=self.RESERVOIR)
        self.dropped_traces = 0
        self.dropped_spans = 0

    # ---- clock ----------------------------------------------------------

    def now(self) -> float:
        return time.monotonic()

    # ---- span lifecycle -------------------------------------------------

    def begin_trace(self, key, name: str, t0: Optional[float] = None,
                    args: Optional[dict] = None) -> Span:
        """Open a new request tree rooted at ``name``.

        Re-beginning an existing key discards the stale tree (a uid can
        only be live once; stale trees would otherwise leak forever).
        """
        t0 = self.now() if t0 is None else t0
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            root = Span(sid, None, name, f"request:{key}", t0, args)
            self._active[key] = [root]
        return root

    def start(self, key, name: str, parent: Optional[Span] = None,
              t0: Optional[float] = None, track: Optional[str] = None,
              args: Optional[dict] = None) -> Span:
        """Open a span.  ``key=None`` targets the global engine ring."""
        t0 = self.now() if t0 is None else t0
        pid = parent.span_id if parent is not None else None
        with self._lock:
            sid = self._next_id
            self._next_id += 1
            if key is not None:
                tree = self._active.get(key)
                if tree is None:
                    # late span for an unknown/finished request: drop
                    self.dropped_spans += 1
                    return Span(sid, pid, name, track or f"request:{key}",
                                t0, args)
                if pid is None:
                    pid = tree[0].span_id
                sp = Span(sid, pid, name, track or tree[0].track, t0, args)
                if len(tree) < self.max_events:
                    tree.append(sp)
                else:
                    self.dropped_spans += 1
                return sp
            sp = Span(sid, pid, name, track or "engine", t0, args)
            self._ring.append(sp)
            return sp

    def end(self, span: Span, t1: Optional[float] = None,
            args: Optional[dict] = None) -> Span:
        span.t1 = self.now() if t1 is None else t1
        if args:
            if span.args is None:
                span.args = dict(args)
            else:
                span.args.update(args)
        return span

    def complete(self, name: str, t0: float, t1: Optional[float] = None,
                 key=None, parent: Optional[Span] = None,
                 track: Optional[str] = None,
                 args: Optional[dict] = None) -> Span:
        """Record an already-timed ``[t0, t1]`` span in one call."""
        sp = self.start(key, name, parent=parent, t0=t0, track=track, args=args)
        sp.t1 = self.now() if t1 is None else t1
        return sp

    def instant(self, name: str, key=None, track: Optional[str] = None,
                t: Optional[float] = None, args: Optional[dict] = None) -> Span:
        """Zero-duration marker (renders as a Perfetto instant event)."""
        t = self.now() if t is None else t
        sp = self.start(key, name, t0=t, track=track, args=args)
        sp.t1 = t
        return sp

    def span(self, name: str, key=None, parent: Optional[Span] = None,
             track: Optional[str] = None,
             args: Optional[dict] = None) -> _SpanHandle:
        """``with tracer.span("round.fused", args={...}) as sp:``"""
        return _SpanHandle(self, self.start(key, name, parent=parent,
                                            track=track, args=args))

    # ---- trace completion / retention ----------------------------------

    def end_trace(self, key, slow_hint: bool = False,
                  meta: Optional[dict] = None) -> bool:
        """Close a request tree; returns True iff the tree was retained."""
        with self._lock:
            tree = self._active.pop(key, None)
            if tree is None:
                return False
            root = tree[0]
            e2e = None if root.t1 is None else root.t1 - root.t0
            keep = self._should_keep_locked(e2e, slow_hint)
            if e2e is not None:
                self._e2e_samples.append(e2e)
            if not keep:
                self.dropped_traces += 1
                return False
            self._done.append({
                "key": key,
                "root": root.name,
                "e2e_s": e2e,
                "slow": bool(slow_hint),
                "meta": dict(meta) if meta else {},
                "spans": tree,
            })
            self._done_events += len(tree)
            while self._done_events > self.max_events and len(self._done) > 1:
                old = self._done.popleft()
                self._done_events -= len(old["spans"])
                self.dropped_traces += 1
            return True

    def _should_keep_locked(self, e2e: Optional[float], slow_hint: bool) -> bool:
        if self.capture == "all" or slow_hint:
            return True
        if len(self._e2e_samples) < self.WARMUP:
            return True  # warmup: no stable percentile yet
        if e2e is None:
            return True  # never finished cleanly — that IS interesting
        ordered = sorted(self._e2e_samples)
        idx = min(len(ordered) - 1,
                  int(self.slow_quantile * (len(ordered) - 1)))
        return e2e >= ordered[idx]

    # ---- read side ------------------------------------------------------

    def trace(self, key) -> Optional[dict]:
        """A single request tree (completed preferred, else in-flight)."""
        with self._lock:
            for rec in reversed(self._done):
                if rec["key"] == key:
                    return {**rec, "spans": list(rec["spans"]),
                            "complete": True}
            tree = self._active.get(key)
            if tree is not None:
                root = tree[0]
                return {"key": key, "root": root.name, "e2e_s": None,
                        "slow": False, "meta": {}, "spans": list(tree),
                        "complete": False}
        return None

    def traces(self) -> List[dict]:
        """All retained completed traces, oldest first (spans included)."""
        with self._lock:
            return [{**rec, "spans": list(rec["spans"]), "complete": True}
                    for rec in self._done]

    def recent(self) -> List[dict]:
        """Span-free summaries of retained traces, newest first."""
        with self._lock:
            return [{"key": rec["key"], "root": rec["root"],
                     "e2e_s": rec["e2e_s"], "slow": rec["slow"],
                     "meta": dict(rec["meta"]), "spans": len(rec["spans"])}
                    for rec in reversed(self._done)]

    def active_keys(self) -> List[Any]:
        with self._lock:
            return list(self._active.keys())

    def ring_spans(self) -> List[Span]:
        with self._lock:
            return list(self._ring)

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "capture": self.capture,
                "max_events": self.max_events,
                "active_traces": len(self._active),
                "completed_traces": len(self._done),
                "completed_spans": self._done_events,
                "ring_spans": len(self._ring),
                "dropped_traces": self.dropped_traces,
                "dropped_spans": self.dropped_spans,
            }


# ---- module-level singleton --------------------------------------------

_TRACER: Any = NULL_TRACER


def get_tracer():
    return _TRACER


def set_tracer(tracer):
    global _TRACER
    _TRACER = tracer
    return tracer


def configure_tracing(enabled: bool = True, max_events: int = 65536,
                      capture: str = "all"):
    """Install the global tracer (SpanTracer when enabled, else the null)."""
    if enabled:
        return set_tracer(SpanTracer(max_events=max_events, capture=capture))
    return set_tracer(NULL_TRACER)


# ---- per-request trace context -----------------------------------------


class TraceContext:
    """Carried on ``Request.trace``: the root span plus the current
    lifecycle *phase* span (queued | prefill | decode | preempted), so
    round/handoff spans can parent onto the phase they occurred in."""

    __slots__ = ("uid", "tracer", "root", "phase", "t_first")

    def __init__(self, uid, tracer, root: Span, phase: Span):
        self.uid = uid
        self.tracer = tracer
        self.root = root
        self.phase = phase
        # first-token stamp, recorded at the prefill->decode switch so the
        # ServingMetrics.observe_trace bridge reads latencies off the SPAN
        # endpoints rather than re-deriving them from the Request
        self.t_first: Optional[float] = None

    def _switch_phase(self, name: str, t: Optional[float] = None,
                      args: Optional[dict] = None) -> Span:
        tr = self.tracer
        t = tr.now() if t is None else t
        tr.end(self.phase, t1=t, args=args)
        self.phase = tr.start(self.uid, name, parent=self.root, t0=t)
        return self.phase


def begin_request_trace(tracer, req, extra: Optional[dict] = None):
    """Root a new trace at ``req.t_submit`` and attach it to the request."""
    if not tracer.enabled:
        return None
    p = req.params
    args = {
        "uid": req.uid,
        "tenant": p.tenant,
        "qos": p.qos,
        "prompt_tokens": len(req.prompt_tokens),
        "max_new_tokens": p.max_new_tokens,
    }
    if getattr(p, "trace_id", None):
        args["trace_id"] = p.trace_id
    if extra:
        args.update(extra)
    root = tracer.begin_trace(req.uid, "request", t0=req.t_submit, args=args)
    phase = tracer.start(req.uid, "queued", parent=root, t0=req.t_submit)
    ctx = TraceContext(req.uid, tracer, root, phase)
    req.trace = ctx
    return ctx


def mark_admitted(req, core: Optional[str] = None):
    """queued -> prefill, stamped at ``req.t_admitted``."""
    ctx = req.trace
    if ctx is None:
        return
    args = {"core": core} if core else None
    ctx._switch_phase("prefill", t=req.t_admitted, args=args)


def mark_first_token(req):
    """prefill -> decode, stamped at ``req.t_first_token``."""
    ctx = req.trace
    if ctx is None:
        return
    ctx.t_first = req.t_first_token
    ctx._switch_phase("decode", t=req.t_first_token)


def mark_preempted(req, reason: str = "preempted"):
    """decode -> preempted (elastic planner took the replica)."""
    ctx = req.trace
    if ctx is None:
        return
    ctx._switch_phase("preempted", args={"reason": reason})


def mark_resumed(req, core: Optional[str] = None):
    """preempted -> decode on the resuming replica."""
    ctx = req.trace
    if ctx is None:
        return
    args = {"core": core} if core else None
    ctx._switch_phase("decode", args=args)


def finish_request_trace(req, reason: Optional[str] = None):
    """Close phase + root at ``req.t_finish`` and run retention policy."""
    ctx = req.trace
    if ctx is None:
        return False
    tr = ctx.tracer
    t = req.t_finish if req.t_finish is not None else tr.now()
    tr.end(ctx.phase, t1=t)
    reason = reason or getattr(req, "finish_reason", None) or "unknown"
    tr.end(ctx.root, t1=t, args={
        "finish_reason": reason,
        "tokens": len(req.generated),
        "preemptions": getattr(req, "preemptions", 0),
    })
    slow_hint = (reason not in ("stop", "max_tokens", "eos")
                 or getattr(req, "preemptions", 0) > 0)
    meta = {"finish_reason": reason, "tenant": req.params.tenant,
            "qos": req.params.qos, "tokens": len(req.generated)}
    kept = tr.end_trace(req.uid, slow_hint=slow_hint, meta=meta)
    req.trace = None
    return kept
