"""``dstpu trace`` — pull a timeline from a serving endpoint.

    dstpu trace dump --url http://127.0.0.1:8000 --out dstpu.trace.json
    dstpu trace dump --uid 3 --out req3.trace.json

The output validates against the Chrome-trace schema and opens in
https://ui.perfetto.dev or ``chrome://tracing``.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request

from deepspeed_tpu.observability.export import validate_chrome_trace

__all__ = ["trace_main"]


def _fetch_json(url: str, timeout: float):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def trace_main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dstpu trace",
        description="dump request/engine timelines from a serving endpoint")
    sub = ap.add_subparsers(dest="cmd")
    dump = sub.add_parser("dump", help="fetch a Chrome-trace JSON timeline")
    dump.add_argument("--url", default="http://127.0.0.1:8000",
                      help="serving endpoint base URL")
    dump.add_argument("--uid", type=int, default=None,
                      help="dump one request's span tree (default: everything)")
    dump.add_argument("--out", default="dstpu.trace.json",
                      help="output path (open in Perfetto)")
    dump.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    if args.cmd is None:
        ap.print_help()
        return 2

    base = args.url.rstrip("/")
    if args.uid is not None:
        url = f"{base}/debug/trace?uid={args.uid}"
    else:
        url = f"{base}/debug/trace?format=chrome"
    try:
        doc = _fetch_json(url, args.timeout)
    except urllib.error.HTTPError as e:
        print(f"trace dump: {url} -> HTTP {e.code} {e.reason}", file=sys.stderr)
        return 1
    except (urllib.error.URLError, OSError, ValueError) as e:
        print(f"trace dump: {url} -> {e}", file=sys.stderr)
        return 1

    errs = validate_chrome_trace(doc)
    if errs:
        print("trace dump: endpoint returned an invalid Chrome-trace "
              "document:", file=sys.stderr)
        for e in errs[:10]:
            print(f"  - {e}", file=sys.stderr)
        return 1

    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"))
    n = len(doc.get("traceEvents", []))
    print(f"wrote {args.out}: {n} events (open in https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(trace_main(sys.argv[1:]))
