"""Control-plane event log: a tiny always-on bounded journal.

Events are discrete control-plane facts — shed-ladder level changes,
preempt/resume, replica scale up/down, engine failures, handoff errors —
as opposed to spans, which are intervals.  The log is cheap enough to
leave on even when span tracing is off, and the exporter renders events
as Perfetto instant events on a dedicated "control" track.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import List, Optional

__all__ = ["Event", "EventLog", "get_event_log", "log_event"]


class Event:
    __slots__ = ("t", "kind", "fields")

    def __init__(self, t: float, kind: str, fields: dict):
        self.t = t            # time.monotonic() — same clock as spans
        self.kind = kind
        self.fields = fields

    def to_dict(self) -> dict:
        return {"t": self.t, "kind": self.kind, **self.fields}

    def __repr__(self):  # pragma: no cover - debug aid
        return f"Event({self.kind!r}, t={self.t:.6f}, {self.fields})"


class EventLog:
    """Thread-safe bounded event journal."""

    def __init__(self, maxlen: int = 512):
        self._events: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.total = 0
        # events silently evicted by the bounded deque; a nonzero value
        # on /debug/events means the journal wrapped and incident
        # timelines may be missing their oldest entries
        self.dropped = 0

    def emit(self, kind: str, **fields) -> Event:
        ev = Event(time.monotonic(), kind, fields)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)
            self.total += 1
        return ev

    def stats(self) -> dict:
        with self._lock:
            return {
                "total": self.total,
                "retained": len(self._events),
                "dropped": self.dropped,
            }

    def recent(self, n: Optional[int] = None) -> List[dict]:
        """Newest-first event dicts (all retained when ``n`` is None)."""
        with self._lock:
            evs = list(self._events)
        evs.reverse()
        if n is not None:
            evs = evs[:n]
        return [e.to_dict() for e in evs]

    def events(self) -> List[Event]:
        """Retained events oldest-first (for the timeline exporter)."""
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)


_LOG = EventLog()


def get_event_log() -> EventLog:
    return _LOG


def log_event(kind: str, **fields) -> Event:
    """Emit on the global control-plane log."""
    return _LOG.emit(kind, **fields)
