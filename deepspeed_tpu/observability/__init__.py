"""Request tracing, Perfetto export, and the control-plane event log.

This package is dependency-free (stdlib only).  The tracer is a global
singleton selected by :func:`configure_tracing`; when tracing is off the
singleton is a :class:`NullTracer` whose methods all return one shared
no-op span, so the disabled path costs an attribute check and zero
allocations per call.
"""

from deepspeed_tpu.observability.events import (
    Event,
    EventLog,
    get_event_log,
    log_event,
)
from deepspeed_tpu.observability.export import (
    to_chrome_trace,
    trace_to_chrome,
    validate_chrome_trace,
    write_trace,
)
from deepspeed_tpu.observability.tracing import (
    NULL_TRACER,
    NullTracer,
    Span,
    SpanTracer,
    TraceContext,
    begin_request_trace,
    configure_tracing,
    finish_request_trace,
    get_tracer,
    mark_admitted,
    mark_first_token,
    mark_preempted,
    mark_resumed,
    set_tracer,
)

__all__ = [
    "Event",
    "EventLog",
    "NULL_TRACER",
    "NullTracer",
    "Span",
    "SpanTracer",
    "TraceContext",
    "begin_request_trace",
    "configure_tracing",
    "finish_request_trace",
    "get_event_log",
    "get_tracer",
    "log_event",
    "mark_admitted",
    "mark_first_token",
    "mark_preempted",
    "mark_resumed",
    "set_tracer",
    "to_chrome_trace",
    "trace_to_chrome",
    "validate_chrome_trace",
    "write_trace",
]
