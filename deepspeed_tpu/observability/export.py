"""Chrome-trace / Perfetto JSON export for spans and control events.

The output is the Trace Event Format that ``chrome://tracing`` and
https://ui.perfetto.dev both open: ``{"traceEvents": [...],
"displayTimeUnit": "ms"}`` with complete ("X") events in microseconds.

Track layout:

* pid 1 ``requests`` — one tid per request uid; the span tree nests by
  timestamp containment (Perfetto stacks same-tid X events).
* pid 2 ``engines`` — one tid per engine/track name (step rounds,
  dispatch vs device_wait brackets, host-tier readmits).
* pid 3 ``control`` — instant events from the control-plane event log.
"""

from __future__ import annotations

import json
import math
from typing import Iterable, List, Optional

__all__ = [
    "to_chrome_trace",
    "trace_to_chrome",
    "validate_chrome_trace",
    "write_trace",
]

_PID_REQUESTS = 1
_PID_ENGINES = 2
_PID_CONTROL = 3


def _us(t: float) -> float:
    return round(t * 1e6, 3)


def _span_event(span, pid: int, tid: int, now: float) -> dict:
    t1 = span.t1 if span.t1 is not None else now
    ev = {
        "name": span.name,
        "ph": "X",
        "ts": _us(span.t0),
        "dur": max(0.0, _us(t1) - _us(span.t0)),
        "pid": pid,
        "tid": tid,
        "args": dict(span.args) if span.args else {},
    }
    ev["args"]["span_id"] = span.span_id
    if span.parent_id is not None:
        ev["args"]["parent_id"] = span.parent_id
    if span.t1 is None:
        ev["args"]["open"] = True
    return ev


def _meta(pid: int, name: str, tid: Optional[int] = None,
          tname: Optional[str] = None) -> List[dict]:
    out = [{"name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": name}}]
    if tid is not None:
        out.append({"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                    "args": {"name": tname or str(tid)}})
    return out


def _req_tid(key) -> int:
    try:
        return int(key)
    except (TypeError, ValueError):
        return abs(hash(key)) % (1 << 30)


def to_chrome_trace(traces: Optional[Iterable[dict]] = None,
                    ring: Optional[Iterable] = None,
                    events: Optional[Iterable] = None,
                    tracer=None, event_log=None, now: Optional[float] = None) -> dict:
    """Build one timeline document.

    Either pass explicit ``traces`` (dicts from ``SpanTracer.trace[s]``),
    ``ring`` (engine Spans), and ``events`` (control Events) — or pass a
    ``tracer``/``event_log`` and everything retained is exported.
    """
    if tracer is not None:
        traces = tracer.traces() if traces is None else traces
        ring = tracer.ring_spans() if ring is None else ring
    if event_log is not None and events is None:
        events = event_log.events()
    traces = list(traces or [])
    ring = list(ring or [])
    events = list(events or [])

    if now is None:
        now = 0.0
        for tr in traces:
            for sp in tr["spans"]:
                now = max(now, sp.t0, sp.t1 or 0.0)
        for sp in ring:
            now = max(now, sp.t0, sp.t1 or 0.0)
        for ev in events:
            now = max(now, ev.t)

    out: List[dict] = []
    out += _meta(_PID_REQUESTS, "requests")
    for tr in traces:
        tid = _req_tid(tr["key"])
        out += _meta(_PID_REQUESTS, "requests", tid, f"request {tr['key']}")[1:]
        for sp in tr["spans"]:
            out.append(_span_event(sp, _PID_REQUESTS, tid, now))

    if ring:
        out += _meta(_PID_ENGINES, "engines")
        track_tids = {}
        for sp in ring:
            tid = track_tids.get(sp.track)
            if tid is None:
                tid = len(track_tids) + 1
                track_tids[sp.track] = tid
                out += _meta(_PID_ENGINES, "engines", tid, sp.track)[1:]
            out.append(_span_event(sp, _PID_ENGINES, tid, now))

    if events:
        out += _meta(_PID_CONTROL, "control", 1, "events")
        for ev in events:
            out.append({
                "name": ev.kind,
                "ph": "i",
                "s": "g",
                "ts": _us(ev.t),
                "pid": _PID_CONTROL,
                "tid": 1,
                "args": dict(ev.fields),
            })

    return {"traceEvents": out, "displayTimeUnit": "ms"}


def trace_to_chrome(trace: dict, now: Optional[float] = None) -> dict:
    """A single request tree as its own Chrome-trace document."""
    return to_chrome_trace(traces=[trace], now=now)


def validate_chrome_trace(doc) -> List[str]:
    """Schema check; returns a list of problems (empty == valid)."""
    errs: List[str] = []
    if not isinstance(doc, dict):
        return [f"document is {type(doc).__name__}, expected dict"]
    evs = doc.get("traceEvents")
    if not isinstance(evs, list):
        return ["missing/invalid traceEvents list"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            errs.append(f"event {i}: not a dict")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E"):
            errs.append(f"event {i}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errs.append(f"event {i}: missing name")
        if "pid" not in ev:
            errs.append(f"event {i}: missing pid")
        if ph == "M":
            continue
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or not math.isfinite(ts):
            errs.append(f"event {i}: non-finite ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if (not isinstance(dur, (int, float)) or not math.isfinite(dur)
                    or dur < 0):
                errs.append(f"event {i}: bad dur {dur!r}")
        if len(errs) > 20:
            errs.append("... (truncated)")
            break
    return errs


def write_trace(path: str, doc: dict) -> str:
    """Validate and write a ``.trace.json`` Perfetto can open."""
    errs = validate_chrome_trace(doc)
    if errs:
        raise ValueError("invalid Chrome-trace document: " + "; ".join(errs[:5]))
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, separators=(",", ":"))
    return path
