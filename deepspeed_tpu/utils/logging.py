"""Rank-aware logging utilities.

TPU-native analogue of the reference's ``deepspeed/utils/logging.py``
(``logger``, ``log_dist``, ``log_dist_once``). Rank filtering uses the JAX
process index instead of torch.distributed ranks.
"""

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class _LoggerFactory:
    @staticmethod
    def create_logger(name=None, level=logging.INFO):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] %(message)s"
        )
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = _LoggerFactory.create_logger(
    name="DeepSpeedTPU", level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info"), logging.INFO)
)


def _process_index():
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log on listed process ranks only (rank -1 or None == all).

    Mirrors the reference ``log_dist`` semantics (utils/logging.py).
    """
    should_log = ranks is None or len(ranks) == 0 or -1 in ranks
    if not should_log:
        should_log = _process_index() in set(ranks)
    if should_log:
        logger.log(level, f"[Rank {_process_index()}] {message}")


_logged_once = set()


def log_dist_once(message, ranks=None, level=logging.INFO):
    key = (message, tuple(ranks) if ranks else None, level)
    if key not in _logged_once:
        _logged_once.add(key)
        log_dist(message, ranks=ranks, level=level)


@functools.lru_cache(None)
def warning_once(message):
    logger.warning(message)


def print_rank_0(message):
    if _process_index() == 0:
        print(message, flush=True)
