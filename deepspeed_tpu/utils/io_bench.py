"""dstpu_io: AIO engine micro-benchmark (reference ``bin/ds_io`` +
``csrc/aio`` benchmark harness, and ``bin/ds_nvme_tune`` parameter sweep).

Measures sustained read/write bandwidth of the native AIO engine against a
target directory across (block_size, queue_depth, intra_op_parallelism)
configurations; ``--tune`` sweeps and reports the best."""

import argparse
import json
import os
import tempfile
import time

import numpy as np

from deepspeed_tpu.utils.logging import logger


def _bench_one(path, size_mb, block_size, parallelism, read):
    from deepspeed_tpu.ops.aio import AioHandle

    h = AioHandle(block_size=block_size, intra_op_parallelism=parallelism)
    buf = h.new_cpu_locked_tensor(size_mb * (1 << 20) // 4, np.float32)
    buf[:] = 1.0
    if read:
        h.sync_pwrite(buf, path)  # seed the file
        # drop the freshly-written pages so the read measures the DEVICE,
        # not the page cache (--tune would otherwise recommend AIO params
        # from cache-bound numbers)
        try:
            fd = os.open(path, os.O_RDONLY)
            os.fsync(fd)
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
            os.close(fd)
        except (OSError, AttributeError):
            logger.warning("could not drop page cache; read bandwidth may be cache-bound")
    t0 = time.perf_counter()
    if read:
        h.sync_pread(buf, path)
    else:
        h.sync_pwrite(buf, path)
    dt = time.perf_counter() - t0
    h.free_cpu_locked_tensor(buf)
    return size_mb / 1024 / dt  # GB/s


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dstpu_io", description=__doc__)
    p.add_argument("--path", default=None, help="target dir (default: tmp)")
    p.add_argument("--size_mb", type=int, default=256)
    p.add_argument("--block_size", type=int, default=1 << 20)
    p.add_argument("--parallelism", type=int, default=4)
    p.add_argument("--read", action="store_true", help="bench reads (default writes)")
    p.add_argument("--tune", action="store_true", help="sweep block/parallelism")
    args = p.parse_args(argv)

    target_dir = args.path or tempfile.gettempdir()
    path = os.path.join(target_dir, "dstpu_io_bench.bin")
    try:
        if args.tune:
            best = None
            for bs in (256 << 10, 1 << 20, 4 << 20, 16 << 20):
                for par in (1, 2, 4, 8):
                    gbs = _bench_one(path, args.size_mb, bs, par, args.read)
                    row = {"block_size": bs, "parallelism": par, "GB_per_s": round(gbs, 3)}
                    print(json.dumps(row))
                    if best is None or gbs > best["GB_per_s"]:
                        best = row
            print(json.dumps({"best": best}))
        else:
            gbs = _bench_one(path, args.size_mb, args.block_size, args.parallelism, args.read)
            print(json.dumps({
                "op": "read" if args.read else "write",
                "size_mb": args.size_mb,
                "block_size": args.block_size,
                "parallelism": args.parallelism,
                "GB_per_s": round(gbs, 3),
            }))
    finally:
        if os.path.exists(path):
            os.remove(path)
    return 0
