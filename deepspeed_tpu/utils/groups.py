"""Parallel-group query API.

Mirror of the reference ``deepspeed/utils/groups.py`` query surface
(``_get_data_parallel_world_size`` etc., groups.py:57-759). On TPU the
"groups" are named mesh axes of the global :class:`Topology`; the rank-list
algebra (``_get_expert_parallel_ranks`` groups.py:315) is subsumed by the
mesh's coordinate system.
"""

from deepspeed_tpu.parallel.topology import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPE_AXIS,
    SEQUENCE_AXIS,
    get_topology,
)

_mesh_device = None


def initialize(ep_size=1, mpu=None):
    """Reference groups.initialize — EP groups are created lazily from the
    mesh's expert axis; nothing to materialize here."""


# ---- world sizes ----
def get_data_parallel_world_size():
    return get_topology().dp_world_size


def get_model_parallel_world_size():
    return get_topology().model_parallel_size


get_tensor_model_parallel_world_size = get_model_parallel_world_size


def get_pipe_parallel_world_size():
    return get_topology().pipe_parallel_size


def get_sequence_parallel_world_size():
    return get_topology().sequence_parallel_size


def get_expert_parallel_world_size(group_name=None):
    return get_topology().expert_parallel_size


def get_expert_data_parallel_world_size(group_name=None):
    return get_topology().data_parallel_size


def get_world_size():
    return get_topology().world_size


# ---- group handles: axis names stand in for torch process groups ----
def get_data_parallel_group():
    """The non-expert data-parallel axes. When MiCS/hpZ factorized data into
    (data, zero), the dp group spans BOTH — a collective over this handle
    must cover the same world get_data_parallel_world_size() reports."""
    from deepspeed_tpu.parallel.topology import ZERO_AXIS

    if get_topology().zero_shard_size > 1:
        return (DATA_AXIS, ZERO_AXIS)
    return DATA_AXIS


def get_model_parallel_group():
    return MODEL_AXIS


get_tensor_model_parallel_group = get_model_parallel_group


def get_pipe_parallel_group():
    return PIPE_AXIS


def get_sequence_parallel_group():
    return SEQUENCE_AXIS


def get_expert_parallel_group(group_name=None):
    return EXPERT_AXIS


def get_expert_data_parallel_group(group_name=None):
    return DATA_AXIS


def get_zero_param_intra_parallel_group():
    """hpZ/MiCS shard-group axis (reference groups.py:702
    _create_zero_param_parallel_group): the ``zero`` mesh axis when the
    topology was built with a shard group, else the plain data axis."""
    from deepspeed_tpu.parallel.topology import ZERO_AXIS

    return ZERO_AXIS if get_topology().zero_shard_size > 1 else DATA_AXIS


def get_zero_param_intra_parallel_group_world_size():
    return get_topology().zero_shard_size


# ---- in-trace ranks (valid inside shard_map) ----
def get_data_parallel_rank():
    from jax import lax

    group = get_data_parallel_group()
    if isinstance(group, tuple):
        return lax.axis_index(group)  # combined (data, zero) rank
    return lax.axis_index(group)


def get_model_parallel_rank():
    from jax import lax

    return lax.axis_index(MODEL_AXIS)


def get_sequence_parallel_rank():
    from jax import lax

    return lax.axis_index(SEQUENCE_AXIS)


def get_expert_parallel_rank(group_name=None):
    from jax import lax

    return lax.axis_index(EXPERT_AXIS)
