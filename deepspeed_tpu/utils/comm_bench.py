"""dstpu_bench: collective micro-benchmark (reference ``bin/ds_bench`` →
benchmarks/communication sweep: all_reduce/all_gather/all_to_all/
reduce_scatter across message sizes, reporting algbw/busbw).

Runs on whatever mesh is available (real chips, or the virtual CPU mesh via
--cpu_devices N for plumbing checks). Bus bandwidth uses the standard
ring-collective byte multipliers."""

import argparse
import json
import time

import numpy as np


def _bus_factor(op, w):
    # bytes actually moved per rank vs message size (ring algorithms)
    return {
        "all_reduce": 2 * (w - 1) / w,
        "all_gather": (w - 1) / w,
        "reduce_scatter": (w - 1) / w,
        "all_to_all": (w - 1) / w,
    }[op]


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dstpu_bench", description=__doc__)
    p.add_argument("--op", default="all_reduce",
                   choices=["all_reduce", "all_gather", "reduce_scatter", "all_to_all"])
    p.add_argument("--minsize", type=int, default=1 << 20, help="bytes")
    p.add_argument("--maxsize", type=int, default=1 << 28, help="bytes")
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument("--cpu_devices", type=int, default=0,
                   help="force an N-device virtual CPU mesh (plumbing checks)")
    args = p.parse_args(argv)

    if args.cpu_devices:
        # pre-0.5 jax has no jax_num_cpu_devices option: the XLA flag (set
        # before jax initializes its backend) covers both generations
        import os

        if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={args.cpu_devices}"
            ).strip()
    import jax

    if args.cpu_devices:
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", args.cpu_devices)
        except AttributeError:
            pass  # XLA_FLAGS fallback above
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    devs = jax.devices()
    w = len(devs)
    if w < 2:
        print(json.dumps({"error": f"need >=2 devices for collectives, have {w}"}))
        return 1
    mesh = jax.sharding.Mesh(devs, ("x",))

    def collective(x):
        if args.op == "all_reduce":
            return jax.lax.psum(x, "x")
        if args.op == "all_gather":
            return jax.lax.all_gather(x, "x", tiled=True)
        if args.op == "reduce_scatter":
            return jax.lax.psum_scatter(x, "x", tiled=True)
        return jax.lax.all_to_all(x.reshape(w, -1), "x", 0, 0, tiled=False).reshape(-1)

    size = args.minsize
    while size <= args.maxsize:
        n = max(size // 4 // w * w, w * w)  # fp32 elements, divisible shapes
        fn = jax.jit(jax.shard_map(
            collective, mesh=mesh, in_specs=P("x"), out_specs=P("x") if args.op in ("all_reduce",) else P(),
            check_vma=False,
        ))
        # x is the GLOBAL array under shard_map(in_specs=P("x")): each rank's
        # collective message is n/w elements — size the global input so the
        # PER-RANK message matches the sweep size. Build it PRE-SHARDED: an
        # unsharded global array would materialize entirely on device 0 and
        # OOM at large sweep sizes on large meshes.
        n_global = n * w
        # build only the per-device shards (n*4 bytes each): neither host nor
        # any device ever holds the global array
        sharding = jax.sharding.NamedSharding(mesh, P("x"))
        x = jax.make_array_from_callback(
            (n_global,), sharding,
            lambda idx: np.ones((n,), np.float32),
        )
        try:
            out = fn(x)
            jax.block_until_ready(out)
            for _ in range(args.warmup):
                jax.block_until_ready(fn(x))
            t0 = time.perf_counter()
            for _ in range(args.iters):
                out = fn(x)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / args.iters
            nbytes = n * 4  # per-rank message bytes
            algbw = nbytes / dt / 1e9
            print(json.dumps({
                "op": args.op, "size_bytes": nbytes, "time_us": round(dt * 1e6, 1),
                "algbw_GBps": round(algbw, 3),
                "busbw_GBps": round(algbw * _bus_factor(args.op, w), 3),
            }))
        except Exception as e:  # shape/op unsupported at this size
            print(json.dumps({"op": args.op, "size_bytes": size, "error": str(e)[:200]}))
        size *= 4
    return 0
