"""Wall-clock and throughput timers.

TPU-native analogue of the reference ``deepspeed/utils/timer.py``
(``SynchronizedWallClockTimer`` utils/timer.py:44, ``ThroughputTimer`` :200).
Instead of CUDA events we synchronize by blocking on outstanding XLA async
dispatch (``jax.block_until_ready`` on a trivial computation) — on TPU all
dispatched work is ordered, so a barrier on a fresh op drains the queue.
"""

import time

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
BACKWARD_INNER_MICRO_TIMER = "bwd_inner_microstep"
BACKWARD_INNER_GLOBAL_TIMER = "bwd_inner"
BACKWARD_REDUCE_MICRO_TIMER = "bwd_allreduce_microstep"
BACKWARD_REDUCE_GLOBAL_TIMER = "bwd_allreduce"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


# Cached jitted barrier computation. Caching the RESULT array would be
# wrong — block_until_ready on an already-ready array returns immediately
# without draining the dispatch queue; what we cache is the compiled
# function, and each sync blocks on a FRESH invocation, which on TPU is
# ordered after all previously dispatched work.
_SYNC_FN = None


def device_synchronize(tree=None):
    """Drain outstanding async dispatch (the shared sync-barrier helper).

    With ``tree`` given, blocks until those specific arrays are ready
    (cheaper than a full barrier; used by the engine-step device_wait
    hooks). With no argument, dispatches a cached trivial computation and
    blocks on it, which orders after the whole queue.
    """
    global _SYNC_FN
    try:
        import jax
    except ImportError:
        return  # CPU-only / jax-less environment: nothing to drain
    try:
        if tree is not None:
            jax.block_until_ready(tree)
            return
        if _SYNC_FN is None:
            import jax.numpy as jnp

            _SYNC_FN = jax.jit(lambda: jnp.zeros(()))
        jax.block_until_ready(_SYNC_FN())
    except RuntimeError:
        # backend not initialized (e.g. forked worker before first use);
        # a timer barrier is best-effort, never fatal
        pass


# legacy alias (pre-existing internal call sites)
_device_synchronize = device_synchronize


class SynchronizedWallClockTimer:
    """Group of named timers, each synchronizing the device before reading the clock."""

    class Timer:
        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.start_time = time.time()
            self.elapsed_records = []

        def start(self):
            if self.started_:
                raise RuntimeError(f"{self.name_} timer has already been started")
            _device_synchronize()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False, record=True):
            if not self.started_:
                raise RuntimeError("timer is not started")
            _device_synchronize()
            elapsed = time.time() - self.start_time
            if record:
                self.elapsed_records.append(elapsed)
            self.started_ = False
            return elapsed

        def _get_elapsed_msec(self):
            return sum(self.elapsed_records) * 1000.0

        def reset(self):
            self.started_ = False
            self.elapsed_records = []

        def elapsed(self, reset=True):
            started = self.started_
            if started:
                self.stop()
            elapsed = self._get_elapsed_msec()
            if reset:
                self.reset()
            if started:
                self.start()
            return elapsed

        def mean(self):
            if not self.elapsed_records:
                return 0.0
            return sum(self.elapsed_records) / len(self.elapsed_records) * 1000.0

    def __init__(self):
        self.timers = {}

    def get_timers(self):
        return self.timers

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    @staticmethod
    def memory_usage():
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0)
            peak = stats.get("peak_bytes_in_use", 0)
            return f"DeviceMem in-use: {in_use / 2**30:.2f} GB | peak: {peak / 2**30:.2f} GB"
        except Exception:
            return "DeviceMem stats unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        if normalizer <= 0.0:
            raise ValueError(f"normalizer must be positive, got {normalizer}")
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) / normalizer
                string += f" | {name}: {elapsed_time:.2f}"
        log_dist(string, ranks=ranks or [0])


class NoopTimer:
    class Timer:
        def start(self):
            ...

        def reset(self):
            ...

        def stop(self, **kwargs):
            ...

        def elapsed(self, **kwargs):
            return 0

        def mean(self):
            return 0

    def __init__(self):
        self.timer = self.Timer()

    def __call__(self, name):
        return self.timer

    def get_timers(self):
        return {}

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        ...


class ThroughputTimer:
    """Samples/sec + TFLOPs estimator (reference utils/timer.py:200)."""

    def __init__(self, config, batch_size, start_step=2, steps_per_output=None, monitor_memory=False, logging_fn=None):
        self.config = config
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = batch_size or 1
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self._window_start = 0
        self._window_steps = 0
        self._timed_steps = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist

    @property
    def enabled(self):
        return getattr(self.config, "enabled", True)

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = True

    def start(self):
        """Per-step device synchronization would drain the XLA async-dispatch
        pipeline and serialize the optimizer/epilogue tail against the next
        step's forward (measured ~25% step-time loss on v5e). The reference
        can afford CUDA-event timing per step (utils/timer.py:32) because
        events don't stall the stream; the TPU equivalent is to sync only at
        reporting boundaries and attribute the window's wall time to the
        steps inside it."""
        if not self.enabled:
            return
        self.started = True
        if self.global_step_count >= self.start_step and self._window_start == 0:
            _device_synchronize()
            self._window_start = time.time()
            self._window_steps = 0

    def stop(self, global_step=False, report_speed=True):
        if not self.enabled or not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if not global_step:
            # micro-steps never close a window (or sync): only gradient
            # boundaries count toward throughput, matching the reference's
            # per-global-step accounting
            return
        self.global_step_count += 1
        if self._window_start > 0:
            self._window_steps += 1
            boundary = not self.steps_per_output or self.global_step_count % self.steps_per_output == 0
            if not boundary:
                return
            _device_synchronize()
            self.end_time = time.time()
            duration = self.end_time - self._window_start
            self.total_elapsed_time += duration
            self._timed_steps += self._window_steps
            self.step_elapsed_time = duration / max(self._window_steps, 1)
            self._window_start = 0
            if global_step and report_speed and self.steps_per_output:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, RunningAvgSamplesPerSec="
                    f"{self.avg_samples_per_sec():.2f}, CurrSamplesPerSec="
                    f"{self.batch_size / self.step_elapsed_time if self.step_elapsed_time else 0:.2f}"
                )

    def avg_samples_per_sec(self):
        if self._timed_steps > 0 and self.total_elapsed_time > 0:
            avg_time_per_step = self.total_elapsed_time / self._timed_steps
            return self.batch_size / avg_time_per_step
        return float("-inf")


def trim_mean(data, trim_percent):
    """Compute the trimmed mean of a list of numbers (reference utils/timer.py tail)."""
    if not 0.0 <= trim_percent <= 1.0:
        raise ValueError(f"trim_percent must be in [0, 1], got {trim_percent}")
    n = len(data)
    if n == 0:
        return 0
    data = sorted(data)
    trim_count = int(trim_percent * n)
    trimmed = data[trim_count : n - trim_count] or data
    return sum(trimmed) / len(trimmed)
