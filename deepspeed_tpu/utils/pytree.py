"""Shared pytree-path helpers (used by AutoTP classification and the
compression matchers)."""

from typing import Sequence


def path_str(path) -> str:
    """'/'-joined, lowercased render of a tree_flatten_with_path key path."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:
            parts.append(str(p))
    return "/".join(parts).lower()


def segments_match(name: str, pattern: str) -> bool:
    """Pattern matches when its '/'- or '.'-separated segments appear as a
    CONTIGUOUS run of full segments in ``name`` — 'layer_1' matches
    'layers/layer_1/w' but not 'layers/layer_10/w' (bare substring matching
    silently over-matches numbered modules)."""
    if pattern == "*":
        return True
    nsegs = name.lower().split("/")
    psegs = pattern.lower().replace(".", "/").split("/")
    n, m = len(nsegs), len(psegs)
    return any(nsegs[i : i + m] == psegs for i in range(n - m + 1))
