"""Memory observability (reference ``runtime/utils.py:776 see_memory_usage``
+ ``memory_breakdown`` config): device HBM stats from the JAX client, host
RSS from the OS — logged rank-0, forceable."""

import resource
from typing import Dict

import jax

from deepspeed_tpu.utils.logging import log_dist

_GB = 2**30
_MB = 2**20


def memory_status(device=None) -> Dict[str, float]:
    """Device + host memory snapshot in bytes. Keys mirror the reference's
    MA/CA (allocated/reserved) naming where a TPU equivalent exists."""
    dev = device or jax.devices()[0]
    stats = {}
    try:
        s = dev.memory_stats() or {}
        stats["bytes_in_use"] = s.get("bytes_in_use", 0)
        stats["peak_bytes_in_use"] = s.get("peak_bytes_in_use", 0)
        stats["bytes_limit"] = s.get("bytes_limit", 0)
        stats["largest_free_block_bytes"] = s.get("largest_free_block_bytes", 0)
    except Exception:
        pass
    stats["host_rss_bytes"] = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    return stats


def see_memory_usage(message: str, force: bool = False, ranks=(0,)):
    """Reference see_memory_usage: one formatted line of device/host memory.
    Cheap (no device sync beyond the stats query); gate call sites with
    ``force`` or the ``memory_breakdown`` config like the reference does."""
    if not force:
        return
    s = memory_status()
    parts = [message]
    if s.get("bytes_limit"):
        parts.append(
            f"HBM {s['bytes_in_use'] / _GB:.2f}GB used "
            f"(peak {s['peak_bytes_in_use'] / _GB:.2f}GB / limit {s['bytes_limit'] / _GB:.2f}GB)"
        )
    parts.append(f"host RSS {s['host_rss_bytes'] / _GB:.2f}GB")
    log_dist(" | ".join(parts), ranks=list(ranks))
    return s


def params_memory_breakdown(tree) -> Dict[str, int]:
    """Bytes per top-level pytree key (what the reference's per-module
    breakdown gives for model state)."""
    import numpy as np

    out = {}
    if isinstance(tree, dict):
        items = tree.items()
    else:
        items = [("params", tree)]
    for k, sub in items:
        out[str(k)] = sum(
            int(np.prod(p.shape)) * p.dtype.itemsize
            for p in jax.tree_util.tree_leaves(sub)
            if hasattr(p, "shape")
        )
    return out
