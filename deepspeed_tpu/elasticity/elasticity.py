"""Elastic training: batch-size/chip-count co-design + resume planning.

Analogue of the reference ``elasticity/elasticity.py`` (``compute_elastic_config``
:233, candidate enumeration :27-126) and the elastic agent's role
(``elastic_agent.py:32``): pick a global batch size with MANY compatible
accelerator counts so the job can scale up/down without changing convergence
behavior (batch = micro × gas × dp_world must stay fixed), and on a
membership change emit the new (micro, gas) decomposition — recovery itself
is universal-checkpoint resume (checkpoint/engine.py), which reshards state
to the new topology.

Math mirrors the reference v0.1/v0.2 algorithms; "GPUs" become chips.
"""

from dataclasses import dataclass, field
from math import lcm
from typing import List, Optional, Tuple


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError, ValueError):
    """Invalid elasticity config section. Also a ValueError so generic
    config-validation callers (and the serving bridge) can catch it
    without importing this package."""


@dataclass
class ElasticityConfig:
    """The ``elasticity`` config section (reference elasticity/config.py)."""

    enabled: bool = False
    max_train_batch_size: int = 2000
    micro_batch_sizes: List[int] = field(default_factory=lambda: [2, 4, 6])
    min_gpus: int = 1
    max_gpus: int = 10000
    min_time: int = 0
    version: float = 0.1
    prefer_larger_batch: bool = True
    model_parallel_size: int = 1
    num_gpus_per_node: int = 1

    def __post_init__(self):
        if self.min_gpus < 1:
            raise ElasticityConfigError(
                f"min_gpus must be >= 1, got {self.min_gpus}"
            )
        if self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"max_gpus ({self.max_gpus}) must be >= min_gpus ({self.min_gpus})"
            )
        if not self.micro_batch_sizes:
            raise ElasticityConfigError("micro_batch_sizes must be non-empty")
        if any(int(mb) < 1 for mb in self.micro_batch_sizes):
            raise ElasticityConfigError(
                f"micro_batch_sizes must all be >= 1, got {self.micro_batch_sizes}"
            )
        if self.max_train_batch_size < min(self.micro_batch_sizes):
            raise ElasticityConfigError(
                f"max_train_batch_size ({self.max_train_batch_size}) is below "
                f"the smallest micro batch ({min(self.micro_batch_sizes)})"
            )
        if self.model_parallel_size < 1 or self.num_gpus_per_node < 1:
            raise ElasticityConfigError(
                "model_parallel_size and num_gpus_per_node must be >= 1"
            )

    @classmethod
    def from_dict(cls, d: dict) -> "ElasticityConfig":
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})


def get_candidate_batch_sizes(base_list: List[int], max_acceptable: int) -> List[int]:
    """Largest multiple of each base ≤ max (reference :27)."""
    candidates = set()
    for base in base_list:
        if base <= max_acceptable:
            candidates.add(base * (max_acceptable // base))
    return sorted(candidates)


def get_valid_gpus(batch_size: int, micro_batches: List[int], min_gpus: int, max_gpus: int) -> List[int]:
    """Chip counts g where some micro-batch evenly decomposes batch_size
    (reference :45): batch % (micro * g) == 0."""
    valid = []
    for g in range(min_gpus, max_gpus + 1):
        if any(batch_size % (mb * g) == 0 for mb in micro_batches):
            valid.append(g)
    return valid


def get_best_candidates(
    candidate_batch_sizes: List[int],
    micro_batches: List[int],
    min_gpus: int,
    max_gpus: int,
    prefer_larger: bool,
) -> Tuple[int, List[int]]:
    """Candidate with the most compatible chip counts; ties → batch-size
    preference (reference :63)."""
    max_valid = -1
    best_batch, best_gpus = 0, []
    for batch in candidate_batch_sizes:
        valid = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus)
        better_tie = prefer_larger and batch > best_batch
        if len(valid) > max_valid or (len(valid) == max_valid and better_tie):
            max_valid = len(valid)
            best_batch, best_gpus = batch, valid
    return best_batch, best_gpus


def _get_compatible_gpus_v01(
    micro_batches: List[int],
    max_acceptable_batch_size: int,
    min_gpus: Optional[int] = None,
    max_gpus: Optional[int] = None,
    prefer_larger: bool = True,
) -> Tuple[int, List[int]]:
    """Reference v0.1 (:83): candidate bases are each micro batch and their
    LCM, scaled to the largest multiple under the cap."""
    min_gpus = min_gpus or 1
    max_gpus = max_gpus or max_acceptable_batch_size // min(micro_batches)
    if not all(mb <= max_acceptable_batch_size for mb in micro_batches):
        raise ValueError(
            "All micro batches must be <= max_acceptable_batch_size "
            f"({max_acceptable_batch_size})"
        )
    base_list = list(micro_batches) + [lcm(*micro_batches)]
    candidates = get_candidate_batch_sizes(base_list, max_acceptable_batch_size)
    return get_best_candidates(candidates, micro_batches, min_gpus, max_gpus, prefer_larger)


def _get_compatible_gpus_v02(
    micro_batches: List[int],
    max_acceptable_batch_size: int,
    current_num_gpus: int,
    min_gpus: Optional[int] = None,
    max_gpus: Optional[int] = None,
    prefer_larger: bool = True,
    num_gpus_per_node: int = 1,
    model_parallel_size: int = 1,
) -> Tuple[int, List[int], Optional[int]]:
    """Reference v0.2 (:126): the batch search runs at NODE granularity —
    candidates come from v0.1 over batch/dp_size_per_node with node counts,
    then scale back. Returns (batch, valid DP WORLD sizes, micro) — callers
    convert chips → dp world via model_parallel_size. If the current dp
    world is not elastic-compatible, falls back to the largest batch that
    decomposes on exactly that world (reference :172-186)."""
    if num_gpus_per_node % model_parallel_size:
        raise ElasticityError(
            f"num_gpus_per_node {num_gpus_per_node} must be divisible by "
            f"model_parallel_size {model_parallel_size}"
        )
    dp_size_per_node = num_gpus_per_node // model_parallel_size
    current_dp = current_num_gpus // model_parallel_size

    def get_microbatch(batch, dp_world):
        cands = [mb for mb in micro_batches if (batch // dp_world) % mb == 0]
        if not cands:
            return None
        return max(cands) if prefer_larger else min(cands)

    batch, valid_nodes = _get_compatible_gpus_v01(
        micro_batches,
        max_acceptable_batch_size // dp_size_per_node,
        max(int((min_gpus or 1) / num_gpus_per_node), 1),
        max(int((max_gpus or num_gpus_per_node) / num_gpus_per_node), 1),
        prefer_larger=prefer_larger,
    )
    batch = int(batch) * dp_size_per_node
    valid_dp = [n * dp_size_per_node for n in valid_nodes]
    if current_dp in valid_dp:
        return batch, valid_dp, get_microbatch(batch, current_dp)

    # current world not elastic-compatible: largest batch decomposing on it
    best_batch, best_micro = 0, None
    for mb in micro_batches:
        unit = mb * current_dp
        if unit <= max_acceptable_batch_size:
            cand = (max_acceptable_batch_size // unit) * unit
            if cand > best_batch or (cand == best_batch and prefer_larger):
                best_batch, best_micro = cand, mb
    if best_batch == 0:
        raise ElasticityError(
            f"no batch <= {max_acceptable_batch_size} decomposes on dp world {current_dp}"
        )
    return best_batch, [current_dp], best_micro


def compute_elastic_config(
    ds_config: dict,
    target_deepspeed_version: str = "",
    world_size: int = 0,
    return_microbatch: bool = False,
):
    """Reference compute_elastic_config (:233). Returns
    (final_batch_size, valid_gpus[, micro_batch]). Deterministic per config."""
    if "elasticity" not in ds_config:
        raise ElasticityConfigError("'elasticity' is missing from the config")
    ecfg = ElasticityConfig.from_dict(ds_config["elasticity"])
    if not ds_config["elasticity"].get("enabled", False):
        # reference semantics: missing/false 'enabled' refuses (the caller is
        # running an elastic job; a silently-inactive config would mislead)
        raise ElasticityConfigError("Elasticity is disabled")

    if ecfg.version >= 0.2:
        batch, valid, micro02 = _get_compatible_gpus_v02(
            ecfg.micro_batch_sizes,
            ecfg.max_train_batch_size,
            current_num_gpus=world_size or ecfg.num_gpus_per_node * ecfg.model_parallel_size,
            min_gpus=ecfg.min_gpus,
            max_gpus=ecfg.max_gpus,
            prefer_larger=ecfg.prefer_larger_batch,
            num_gpus_per_node=ecfg.num_gpus_per_node,
            model_parallel_size=ecfg.model_parallel_size,
        )
        dp_world = (world_size // ecfg.model_parallel_size) if world_size > 0 else 0
    else:
        batch, valid = _get_compatible_gpus_v01(
            ecfg.micro_batch_sizes,
            ecfg.max_train_batch_size,
            min_gpus=ecfg.min_gpus,
            max_gpus=ecfg.max_gpus,
            prefer_larger=ecfg.prefer_larger_batch,
        )
        micro02 = None
        dp_world = world_size

    if dp_world > 0 and dp_world not in valid:
        raise ElasticityError(
            f"dp world {dp_world} is not compatible with batch {batch} "
            f"(valid dp worlds: {valid[:16]}{'...' if len(valid) > 16 else ''})"
        )
    if not return_microbatch:
        return batch, valid
    if dp_world <= 0:
        raise ValueError("return_microbatch requires world_size")
    micro = micro02 if micro02 is not None else micro_batch_for_world(
        batch, ecfg.micro_batch_sizes, dp_world, ecfg.prefer_larger_batch
    )
    return batch, valid, micro


def micro_batch_for_world(
    batch: int, micro_batches: List[int], world_size: int, prefer_larger: bool = True
) -> int:
    """The micro-batch that decomposes ``batch`` on ``world_size`` chips."""
    compatible = [mb for mb in micro_batches if batch % (mb * world_size) == 0]
    if not compatible:
        raise ElasticityError(
            f"no configured micro batch decomposes batch {batch} over {world_size} chips"
        )
    return max(compatible) if prefer_larger else min(compatible)


def elastic_resume_plan(ds_config: dict, new_world_size: int) -> dict:
    """Membership change → the new training decomposition (the elastic
    agent's restart math, reference elastic_agent.py:32 + engine guard
    :680-690). ``new_world_size`` is total chips; the batch decomposes over
    the DATA-parallel world (chips / model_parallel_size). Apply the patch to
    the config and resume from the universal checkpoint."""
    batch, valid, micro = compute_elastic_config(
        ds_config, world_size=new_world_size, return_microbatch=True
    )
    mp = ds_config["elasticity"].get("model_parallel_size", 1)
    dp = new_world_size // mp
    gas = batch // (micro * dp)
    return {
        "train_batch_size": batch,
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
    }
