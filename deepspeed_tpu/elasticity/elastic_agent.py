"""Elastic agent: membership watch + automatic relaunch into UCP resume.

Reference analogue: ``DSElasticAgent(LocalElasticAgent)``
(``deepspeed/elasticity/elastic_agent.py:32``) — the reference plugs into
torch-elastic's rendezvous and restarts workers when membership changes.
The TPU-native form is a supervisor daemon: it derives the current world
size from a membership source (hostfile or a world-size file), solves the
new (train_batch, micro, gas) decomposition with the elasticity solver
(``elastic_resume_plan``), writes the patched config, and (re)launches the
training command. The relaunched run resumes from the latest checkpoint;
orbax reshard-on-load (the built-in universal checkpoint) absorbs the
world-size change, so training continues where it left off.

Membership sources:
  * ``hostfile`` — re-parsed every poll; world = sum of ``slots`` entries
    (the reference's rendezvous node set, file-driven).
  * ``world_file`` — a file holding one integer (operator- or
    orchestrator-driven; also what the integration test uses).

The launched command may contain the placeholders ``{config}`` (path of the
patched config JSON) and ``{world_size}``; the agent also exports
``DSTPU_ELASTIC_CONFIG`` / ``DSTPU_WORLD_SIZE`` / ``DSTPU_ELASTIC_RESTARTS``.
"""

import json
import os
import signal
import subprocess
import sys
import tempfile
import time
from typing import Callable, List, Optional

from deepspeed_tpu.elasticity.elasticity import ElasticityError, elastic_resume_plan
from deepspeed_tpu.utils.logging import logger


def _world_from_hostfile(path: str) -> int:
    world = 0
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            slots = 1
            for tok in parts[1:]:
                if tok.startswith("slots="):
                    slots = int(tok.split("=", 1)[1])
            world += slots
    return world


def _world_from_file(path: str) -> int:
    with open(path) as f:
        return int(f.read().strip())


class ElasticAgent:
    """Supervise a training command across membership changes.

    cmd:        argv list; ``{config}``/``{world_size}`` placeholders are
                substituted per launch.
    ds_config:  base config dict (must contain an ``elasticity`` section).
    """

    def __init__(
        self,
        cmd: List[str],
        ds_config: dict,
        hostfile: Optional[str] = None,
        world_file: Optional[str] = None,
        world_fn: Optional[Callable[[], int]] = None,
        poll_interval: float = 5.0,
        max_restarts: int = 100,
        workdir: Optional[str] = None,
    ):
        if sum(x is not None for x in (hostfile, world_file, world_fn)) != 1:
            raise ValueError("pass exactly one membership source: hostfile, world_file or world_fn")
        if "elasticity" not in ds_config:
            raise ElasticityError("config has no 'elasticity' section")
        self.cmd = list(cmd)
        self.ds_config = ds_config
        self.poll_interval = poll_interval
        self.max_restarts = max_restarts
        self.workdir = workdir or tempfile.mkdtemp(prefix="dstpu_elastic_")
        os.makedirs(self.workdir, exist_ok=True)
        if hostfile is not None:
            self._world_fn = lambda: _world_from_hostfile(hostfile)
        elif world_file is not None:
            self._world_fn = lambda: _world_from_file(world_file)
        else:
            self._world_fn = world_fn
        self.restarts = 0
        self.launches: List[dict] = []  # (world, plan) per launch — observability/tests

    # ------------------------------------------------------------------
    def _patched_config_path(self, world: int) -> str:
        plan = elastic_resume_plan(self.ds_config, world)
        cfg = dict(self.ds_config)
        cfg.update(plan)
        path = os.path.join(self.workdir, f"elastic_config_w{world}_r{self.restarts}.json")
        with open(path, "w") as f:
            json.dump(cfg, f, indent=2)
        self.launches.append({"world": world, "plan": plan, "config": path})
        return path

    def _launch(self, world: int) -> subprocess.Popen:
        cfg_path = self._patched_config_path(world)
        # literal replace, NOT str.format: training commands legitimately
        # contain braces (shell/awk/JSON) that format() would choke on
        argv = [
            a.replace("{config}", cfg_path).replace("{world_size}", str(world))
            for a in self.cmd
        ]
        env = dict(os.environ)
        env["DSTPU_ELASTIC_CONFIG"] = cfg_path
        env["DSTPU_WORLD_SIZE"] = str(world)
        env["DSTPU_ELASTIC_RESTARTS"] = str(self.restarts)
        plan = self.launches[-1]["plan"]
        logger.info(
            f"elastic agent: launching world={world} micro="
            f"{plan['train_micro_batch_size_per_gpu']} gas="
            f"{plan['gradient_accumulation_steps']} (restart {self.restarts})"
        )
        # new process group so a membership change can kill the whole tree
        # (reference launcher kills the proc tree on SIGTERM, launch.py:131)
        return subprocess.Popen(argv, env=env, start_new_session=True)

    @staticmethod
    def _terminate(proc: subprocess.Popen, grace: float = 10.0):
        if proc.poll() is not None:
            return
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            return
        deadline = time.time() + grace
        while time.time() < deadline:
            if proc.poll() is not None:
                return
            time.sleep(0.2)
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()

    def _poll_world(self, last: int) -> int:
        """Read membership, treating a transient failure (hostfile briefly
        missing or empty, world_file mid-rewrite → int('') ValueError) as
        'membership unchanged' — a failed poll must never take down a
        healthy run."""
        try:
            world = self._world_fn()
        except (OSError, ValueError) as e:
            logger.warning(f"elastic agent: membership poll failed ({e}); keeping world={last}")
            return last
        if world <= 0:
            # an empty-but-readable hostfile parses to 0 — that is a
            # mid-rewrite artifact, not a real zero-node cluster
            logger.warning(f"elastic agent: membership poll read world={world}; keeping world={last}")
            return last
        return world

    # ------------------------------------------------------------------
    def run(self) -> int:
        """Supervise until the training command exits 0 (done), a config
        becomes unsolvable, or max_restarts is exhausted. Returns the final
        exit code."""
        world = self._world_fn()
        proc = self._launch(world)
        try:
            while True:
                rc = proc.poll()
                if rc is not None:
                    if rc == 0:
                        logger.info("elastic agent: training completed")
                        return 0
                    # crashed worker: relaunch at the CURRENT membership
                    # (reference elastic agent restart-on-failure semantics)
                    self.restarts += 1
                    if self.restarts > self.max_restarts:
                        logger.error(f"elastic agent: giving up after {self.max_restarts} restarts")
                        return rc
                    world = self._poll_world(world)
                    logger.warning(f"elastic agent: worker exited rc={rc}; relaunching at world={world}")
                    proc = self._launch(world)
                    continue
                new_world = self._poll_world(world)
                if new_world != world:
                    # budget check BEFORE terminating: never kill a healthy
                    # run the agent is not allowed to replace
                    if self.restarts + 1 > self.max_restarts:
                        logger.error(
                            f"elastic agent: membership change {world} -> {new_world} ignored — "
                            f"restart budget ({self.max_restarts}) exhausted; current run continues"
                        )
                        world = new_world  # don't re-trigger every poll
                        time.sleep(self.poll_interval)
                        continue
                    logger.warning(
                        f"elastic agent: membership change {world} -> {new_world}; restarting into UCP resume"
                    )
                    self._terminate(proc)
                    self.restarts += 1
                    world = new_world
                    proc = self._launch(world)
                    continue
                time.sleep(self.poll_interval)
        finally:
            self._terminate(proc)
