"""dstpu_elastic: elastic-config explorer CLI (reference ``bin/ds_elastic``):
reads a config JSON with an ``elasticity`` section and prints the compatible
(batch size, chip count) schedule, optionally for a specific world size."""

import argparse
import json

from deepspeed_tpu.elasticity.elasticity import compute_elastic_config


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dstpu_elastic", description=__doc__)
    p.add_argument("-c", "--config", required=True, help="DeepSpeed config json")
    p.add_argument("-w", "--world-size", type=int, default=0,
                   help="report micro-batch/gas for this chip count")
    p.add_argument("--watch", action="store_true",
                   help="supervise CMD across membership changes (elastic agent)")
    p.add_argument("--hostfile", default=None, help="watch: membership from hostfile slots")
    p.add_argument("--world-file", default=None, help="watch: membership from an integer file")
    p.add_argument("--poll-interval", type=float, default=5.0)
    p.add_argument("--max-restarts", type=int, default=100)
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="watch: training command after '--' ({config}/{world_size} substituted)")
    args = p.parse_args(argv)

    with open(args.config) as f:
        ds_config = json.load(f)
    if args.watch:
        from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent

        cmd = args.cmd[1:] if args.cmd and args.cmd[0] == "--" else args.cmd
        if not cmd:
            p.error("--watch needs a training command after '--'")
        agent = ElasticAgent(
            cmd, ds_config,
            hostfile=args.hostfile, world_file=args.world_file,
            poll_interval=args.poll_interval, max_restarts=args.max_restarts,
        )
        return agent.run()
    if args.world_size:
        batch, valid, mbs = compute_elastic_config(
            ds_config, world_size=args.world_size, return_microbatch=True
        )
        print(json.dumps({
            "world_size": args.world_size,
            "final_batch_size": batch,
            "micro_batch_per_chip": mbs,
            "valid_chip_counts": valid,
        }, indent=2))
    else:
        batch, valid = compute_elastic_config(ds_config)
        print(json.dumps({
            "final_batch_size": batch,
            "valid_chip_counts": valid,
        }, indent=2))
    return 0
