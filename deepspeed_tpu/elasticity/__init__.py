"""Elastic training (reference deepspeed/elasticity/)."""

from deepspeed_tpu.elasticity.elastic_agent import ElasticAgent
from deepspeed_tpu.elasticity.elasticity import (
    ElasticityConfig,
    ElasticityConfigError,
    ElasticityError,
    compute_elastic_config,
    elastic_resume_plan,
    get_best_candidates,
    get_candidate_batch_sizes,
    get_valid_gpus,
    micro_batch_for_world,
)

__all__ = [
    "ElasticAgent",
    "ElasticityConfig",
    "ElasticityConfigError",
    "ElasticityError",
    "compute_elastic_config",
    "elastic_resume_plan",
    "get_best_candidates",
    "get_candidate_batch_sizes",
    "get_valid_gpus",
    "micro_batch_for_world",
]
