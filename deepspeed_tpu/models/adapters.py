"""User-model adapters: bring arbitrary flax modules / loss functions to the
engine's ``loss_fn(params, batch[, rng])`` contract.

The reference wraps user ``nn.Module``s directly (``deepspeed.initialize``
engine.py:202 takes the torch module); the TPU engine trains pure loss
functions over param pytrees, so foreign model types adapt here.
"""

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp


def _default_loss(outputs: jax.Array, batch: dict) -> jax.Array:
    """Heuristic loss when the user does not supply one:
    * [b, s, vocab] outputs + integer 'labels' → next-token cross-entropy
      (shifted like HF causal-LM heads; label -100 = HF ignore_index, and an
      optional 'loss_mask' in the batch also masks positions)
    * 'labels'/'y' same shape as outputs → MSE
    """
    labels = batch.get("labels", batch.get("y"))
    if labels is None:
        raise ValueError(
            "default loss needs 'labels' (or 'y') in the batch; pass loss=... for custom objectives"
        )
    labels = jnp.asarray(labels)
    if outputs.ndim == 3 and jnp.issubdtype(labels.dtype, jnp.integer):
        logits = outputs[:, :-1].astype(jnp.float32)
        targets = labels[:, 1:]
        mask = (targets != -100).astype(jnp.float32)
        if "loss_mask" in batch:
            mask = mask * jnp.asarray(batch["loss_mask"])[:, 1:].astype(jnp.float32)
        safe_targets = jnp.maximum(targets, 0)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, safe_targets[..., None], axis=-1)[..., 0]
        return jnp.sum(-ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(jnp.square(outputs.astype(jnp.float32) - labels.astype(jnp.float32)))


def flax_loss_fn(
    module: Any,
    loss: Optional[Callable[[jax.Array, dict], jax.Array]] = None,
    inputs_key: str = "inputs",
    train: Optional[bool] = None,
    mutable: bool = False,
):
    """Adapt a flax ``nn.Module`` to the engine contract.

    ``params = module.init(rng, example_inputs)['params']`` is what you pass
    to ``deepspeed_tpu.initialize(model_parameters=...)``; this wrapper is
    the ``model=`` argument.

    module:     a flax ``linen.Module`` instance
    loss:       ``loss(outputs, batch) -> scalar`` (default: causal-LM CE for
                [b, s, vocab] integer labels, MSE otherwise)
    inputs_key: batch key holding the module's positional input (falls back
                to 'input_ids' then 'x')
    train:      value passed to ``module.apply(..., train=...)`` when the
                module's __call__ accepts it (dropout etc.); None = omit
    mutable:    pass-through for modules with batch-norm-style state — the
                mutated collections are DISCARDED (the engine trains pure
                params), so only enable for modules where that is acceptable
    """
    loss = loss or _default_loss

    def _inputs(batch):
        for k in (inputs_key, "input_ids", "x"):
            if k in batch:
                return batch[k]
        raise KeyError(f"none of ({inputs_key!r}, 'input_ids', 'x') found in batch")

    def loss_fn(params, batch, rng=None):
        kwargs = {}
        if train is not None:
            kwargs["train"] = train
        if rng is not None:
            kwargs["rngs"] = {"dropout": rng}
        variables = {"params": params}
        if mutable:
            out = module.apply(variables, _inputs(batch), mutable=["batch_stats"], **kwargs)
            outputs = out[0]
        else:
            outputs = module.apply(variables, _inputs(batch), **kwargs)
        if isinstance(outputs, tuple):
            outputs = outputs[0]
        if hasattr(outputs, "logits"):  # HF-flax output dataclasses
            outputs = outputs.logits
        return loss(outputs, batch)

    return loss_fn
