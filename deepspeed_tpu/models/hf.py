"""HF checkpoint import: per-architecture loaders → the native model family.

Analogue of the reference checkpoint-shard loading + per-arch containers
(``module_inject/load_checkpoint.py``, ``module_inject/containers/``,
``inference/v2/model_implementations/{llama_v2,mistral,mixtral,qwen_v2,
qwen_v2_moe,falcon,phi,phi3}``): a HF causal-LM checkpoint directory becomes
a (:class:`TransformerConfig`, stacked-params pytree) pair that trains or
serves through ``deepspeed_tpu.initialize`` / ``init_inference`` unchanged.

Supported ``model_type``s: llama, mistral, qwen2, qwen2_moe, qwen3,
qwen3_moe (per-head q/k RMSNorm), mixtral, falcon, phi (incl. qk_layernorm),
phi3, gpt2, gpt_neo, opt, gemma, bloom, gptj, gpt_neox, internlm, stablelm
(incl. qk_layernorm), starcoder2, megatron_gpt (Megatron-LM GPT state-dict
naming, per-head-interleaved fused qkv), plus the bert/distilbert encoder
family (post-LN bidirectional stack + masked-LM head) and clip_text_model
(the stable-diffusion text tower; unet/vae are N/A here — diffusers is not
in the image) (scaled-RoPE checkpoints —
llama3/yarn/longrope/linear/dynamic — import via ``rope_scaling``;
sliding-window checkpoints — mistral/starcoder2/gpt_neo local — import via
``sliding_window``/``attn_layer_pattern``). Dispatch is by ``config.json``'s
``model_type`` (see
:data:`ARCH_LOADERS`); the inference engine factory additionally dispatches
on ``architectures[0]`` (engine_factory.py).

Weight-layout notes (why each mapping is what it is):
  * HF Linear stores ``[out, in]``; this model family uses JAX's ``[in,
    out]`` → transpose every projection.
  * Layers here are STACKED along a leading ``[n_layers, ...]`` dim (the
    ``lax.scan`` layout), so per-layer tensors stack after transposing.
  * RoPE: HF's ``rotate_half`` IS the half-split convention used by
    ``transformer._rope`` — weights map 1:1, no permutation needed. Phi's
    partial rotary maps to ``rope_frac``.
  * Falcon fuses q/k/v into ``query_key_value`` with a per-kv-group
    interleave under ``new_decoder_architecture`` — de-interleaved here.
  * ``torch`` is only used to read the checkpoint on host (CPU); arrays
    convert to numpy before entering JAX.
"""

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach()
    if hasattr(t, "float") and str(getattr(t, "dtype", "")).startswith("torch.bfloat16"):
        t = t.float()
    return np.asarray(t.cpu() if hasattr(t, "cpu") else t)


def _np_cast(a, dtype: str) -> np.ndarray:
    """Host-only dtype cast (ml_dtypes carries bf16 in numpy — no device
    round-trip for multi-GB checkpoints)."""
    import ml_dtypes

    a = _to_np(a)
    if a.dtype == np.dtype("V2") or str(a.dtype) == "bfloat16":
        a = a.view(ml_dtypes.bfloat16).astype(np.float32)
    target = {"bfloat16": ml_dtypes.bfloat16, "float32": np.float32, "float16": np.float16}[dtype]
    return a.astype(target)


def dataclass_replace(cfg, **kw):
    return dataclasses.replace(cfg, **kw)


def _load_state_dict(path: str) -> Dict[str, Any]:
    """Read all weights of a HF checkpoint dir (safetensors preferred,
    sharded or single-file; torch .bin fallback)."""
    index = os.path.join(path, "model.safetensors.index.json")
    single_st = os.path.join(path, "model.safetensors")
    torch_bin = os.path.join(path, "pytorch_model.bin")
    state: Dict[str, Any] = {}
    if os.path.isfile(index) or os.path.isfile(single_st):
        # framework="pt": the numpy backend cannot represent bf16 tensors;
        # torch (cpu) reads them and _to_np upcasts
        from safetensors import safe_open

        files = (
            sorted({os.path.join(path, s) for s in json.load(open(index))["weight_map"].values()})
            if os.path.isfile(index)
            else [single_st]
        )
        for shard in files:
            with safe_open(shard, framework="pt") as f:
                for k in f.keys():
                    state[k] = _to_np(f.get_tensor(k))
    elif os.path.isfile(torch_bin):
        import torch

        state = {k: _to_np(v) for k, v in torch.load(torch_bin, map_location="cpu", weights_only=True).items()}
    else:
        raise FileNotFoundError(f"no safetensors/bin checkpoint under {path}")
    return state


def _getter(hf_cfg) -> Callable:
    return (lambda k, d=None: hf_cfg.get(k, d)) if isinstance(hf_cfg, dict) else (
        lambda k, d=None: getattr(hf_cfg, k, d)
    )


# ---------------------------------------------------------------------------
# per-arch config translation
# ---------------------------------------------------------------------------
def _parse_rope_scaling(get):
    """HF rope_scaling → the canonical hashable config form (llama3 / yarn /
    longrope / linear / dynamic — transformer.rope_params implements the
    math). Unknown types still fail fast: silently building plain-theta RoPE
    would load without error and produce wrong logits."""
    from deepspeed_tpu.models.transformer import rope_scaling_from_hf

    return rope_scaling_from_hf(
        get("rope_scaling", None), get("original_max_position_embeddings", None)
    )


def _llama_like_config(get, **extra) -> TransformerConfig:
    base = dict(
        rope_scaling=_parse_rope_scaling(get),
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        n_kv_heads=get("num_key_value_heads", None),
        ffn_hidden_size=get("intermediate_size"),
        max_seq_len=get("max_position_embeddings", 2048),
        norm="rmsnorm",
        activation="swiglu",
        position="rope",
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )
    base.update(extra)
    return TransformerConfig(**base)


def config_from_hf(hf_cfg) -> TransformerConfig:
    """HF config (object or dict) → TransformerConfig; dispatches on
    ``model_type`` (llama when absent)."""
    get = _getter(hf_cfg)
    mt = get("model_type", "llama")
    if mt in ("llama", "mistral"):
        head_dim = get("head_dim", None)
        derived = get("hidden_size") // get("num_attention_heads")
        override = int(head_dim) if head_dim is not None and int(head_dim) != derived else None
        bias = bool(get("attention_bias", False))
        # mistral sliding_window (None on v0.2+ checkpoints → full attention)
        window = int(get("sliding_window", None) or 0) if mt == "mistral" else 0
        if window >= get("max_position_embeddings", 2048):
            window = 0  # window beyond the position range is full attention
        return _llama_like_config(
            get, head_dim_override=override, attn_qkv_bias=bias,
            attn_out_bias=bias, sliding_window=window,
        )
    if mt == "internlm":
        # InternLM is llama + biased attention projections (reference
        # module_inject/containers/internlm.py). `bias` covers q/k/v AND o
        # (HF InternLM passes one flag to all four Linears). internlm2's
        # fused-wqkv export is not supported.
        bias = bool(get("bias", True))
        return _llama_like_config(get, attn_qkv_bias=bias, attn_out_bias=bias)
    if mt == "qwen2":
        return _llama_like_config(get, attn_qkv_bias=True)
    if mt == "qwen3":
        # qwen2 minus qkv bias, plus per-head q/k RMSNorm and a decoupled
        # head_dim (always 128 regardless of hidden/heads)
        head_dim = get("head_dim", None)
        derived = get("hidden_size") // get("num_attention_heads")
        return _llama_like_config(
            get,
            qk_norm=True,
            head_dim_override=int(head_dim) if head_dim is not None and int(head_dim) != derived else None,
        )
    if mt == "qwen3_moe":
        sparse_step = get("decoder_sparse_step", 1)
        mlp_only = get("mlp_only_layers", []) or []
        if sparse_step != 1 or mlp_only:
            raise ValueError(
                f"qwen3_moe: decoder_sparse_step={sparse_step}, mlp_only_layers="
                f"{mlp_only} — only uniform MoE stacks are supported"
            )
        head_dim = get("head_dim", None)
        derived = get("hidden_size") // get("num_attention_heads")
        return _llama_like_config(
            get,
            qk_norm=True,
            head_dim_override=int(head_dim) if head_dim is not None and int(head_dim) != derived else None,
            ffn_hidden_size=get("moe_intermediate_size"),
            n_experts=get("num_experts"),
            moe_top_k=get("num_experts_per_tok"),
            moe_norm_topk_prob=bool(get("norm_topk_prob", True)),
            # drop-free (HF semantics) — same capacity stance as qwen2_moe
            moe_capacity_factor=float(get("num_experts")) / float(get("num_experts_per_tok")),
            moe_aux_loss_coef=float(get("router_aux_loss_coef", 0.001)),
        )
    if mt == "qwen2_moe":
        sparse_step = get("decoder_sparse_step", 1)
        mlp_only = get("mlp_only_layers", []) or []
        if sparse_step != 1 or mlp_only:
            # the scan layout wants uniform layers; mixed dense/MoE stacks
            # would need a per-layer dispatch — fail with the real reason
            raise ValueError(
                f"qwen2_moe: decoder_sparse_step={sparse_step}, mlp_only_layers="
                f"{mlp_only} — only uniform MoE stacks are supported"
            )
        logger.warning(
            "qwen2_moe import sets moe_capacity_factor=E/k (drop-free, HF "
            "semantics): the dense dispatch/combine einsums are O(tokens² · "
            "experts · hidden) at this bound — for long-sequence training "
            "lower capacity_factor (accepting drops) or expect high memory"
        )
        return _llama_like_config(
            get,
            attn_qkv_bias=True,
            ffn_hidden_size=get("moe_intermediate_size"),
            n_experts=get("num_experts"),
            moe_top_k=get("num_experts_per_tok"),
            moe_norm_topk_prob=bool(get("norm_topk_prob", False)),
            # HF qwen2-moe never drops tokens. capacity = ceil(t·k·cf/E), and
            # a token contributes at most ONE slot per expert, so cf = E/k
            # gives capacity = t — the minimal drop-free bound (all tokens on
            # one expert). Dense dispatch is still O(t·E·t) at this bound;
            # lower cf (accepting drops) for long-sequence training runs.
            moe_capacity_factor=float(get("num_experts")) / float(get("num_experts_per_tok")),
            moe_shared_expert_dim=get("shared_expert_intermediate_size", 0) or 0,
            moe_aux_loss_coef=float(get("router_aux_loss_coef", 0.001)),
        )
    if mt == "mixtral":
        return _llama_like_config(
            get,
            ffn_hidden_size=get("intermediate_size"),
            n_experts=get("num_local_experts"),
            moe_top_k=get("num_experts_per_tok"),
            # HF mixtral ALWAYS renormalizes the top-k routing weights
            moe_norm_topk_prob=True,
            # dropless (HF never drops): cf = E/k gives capacity = tokens,
            # the minimal drop-free bound — same stance as qwen2_moe above
            moe_capacity_factor=float(get("num_local_experts")) / float(get("num_experts_per_tok")),
            moe_aux_loss_coef=float(get("router_aux_loss_coef", 0.001)),
        )
    if mt == "stablelm":
        return TransformerConfig(
            qk_norm=bool(get("qk_layernorm", False)),
            qk_norm_kind="layernorm_per_head",
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            n_layers=get("num_hidden_layers"),
            n_heads=get("num_attention_heads"),
            n_kv_heads=get("num_key_value_heads", None),
            ffn_hidden_size=get("intermediate_size"),
            max_seq_len=get("max_position_embeddings", 4096),
            norm="layernorm",
            activation="swiglu",  # silu-gated MLP under LayerNorm
            position="rope",
            rope_theta=float(get("rope_theta", 10000.0)),
            rope_scaling=_parse_rope_scaling(get),
            rope_frac=float(get("partial_rotary_factor", 0.25)),
            norm_eps=float(get("layer_norm_eps", 1e-5)),
            tie_embeddings=bool(get("tie_word_embeddings", False)),
            attn_qkv_bias=bool(get("use_qkv_bias", False)),
            # parallel residual shares input_layernorm across both branches
            parallel_block=bool(get("use_parallel_residual", False)),
        )
    if mt == "starcoder2":
        act = get("hidden_act", "gelu_pytorch_tanh")
        act_map = {"gelu_pytorch_tanh": "gelu", "gelu_new": "gelu", "gelu": "gelu_exact"}
        if act not in act_map:
            raise ValueError(f"starcoder2: hidden_act={act!r} is not supported")
        bias = bool(get("use_bias", True))
        max_seq = get("max_position_embeddings", 4096)
        # released starcoder2 sets sliding_window=4096 with a 16k position
        # range — native banded masking (sliding_window) keeps the full range
        window = int(get("sliding_window", None) or 0)
        if window >= max_seq:
            window = 0
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            n_layers=get("num_hidden_layers"),
            n_heads=get("num_attention_heads"),
            n_kv_heads=get("num_key_value_heads", None),
            ffn_hidden_size=get("intermediate_size"),
            max_seq_len=max_seq,
            norm="layernorm",
            activation=act_map[act],
            position="rope",
            rope_theta=float(get("rope_theta", 10000.0)),
            rope_scaling=_parse_rope_scaling(get),
            norm_eps=float(get("norm_epsilon", 1e-5)),
            tie_embeddings=bool(get("tie_word_embeddings", True)),
            attn_qkv_bias=bias,
            attn_out_bias=bias,
            mlp_bias=bias,
            sliding_window=window,
        )
    if mt == "gpt_neo":
        h = get("hidden_size")
        act = get("activation_function", "gelu_new")
        if act not in ("gelu_new", "gelu_pytorch_tanh"):
            raise ValueError(f"gpt_neo: activation_function={act!r} is not supported (gelu_new only)")
        n_layers = get("num_layers")
        # expand attention_types ([[types, repeat], ...]) the way
        # GPTNeoConfig.expand_attention_types_params does
        pattern: list = []
        for types, rep in get("attention_types", [[["global"], n_layers]]):
            for _ in range(rep):
                pattern.extend(types)
        if len(pattern) != n_layers:
            raise ValueError(
                f"gpt_neo: attention_types expands to {len(pattern)} layers, "
                f"config has {n_layers}"
            )
        bad = sorted(set(pattern) - {"global", "local"})
        if bad:
            raise ValueError(f"gpt_neo: unknown attention type(s) {bad}")
        any_local = "local" in pattern
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=h,
            n_layers=n_layers,
            n_heads=get("num_heads"),
            ffn_hidden_size=get("intermediate_size", None) or 4 * h,
            max_seq_len=get("max_position_embeddings", 2048),
            norm="layernorm",
            activation="gelu",  # gelu_new = tanh approx
            position="learned",
            norm_eps=float(get("layer_norm_epsilon", 1e-5)),
            tie_embeddings=True,
            # q/k/v Linears carry no bias; out_proj and the MLP do
            attn_out_bias=True,
            mlp_bias=True,
            # GPTNeoSelfAttention never scales the logits by 1/sqrt(d)
            attn_scale=1.0,
            sliding_window=int(get("window_size", 256)) if any_local else 0,
            attn_layer_pattern=tuple(int(t == "local") for t in pattern) if any_local else None,
        )
    if mt == "clip_text_model":
        # CLIP's text encoder (reference module_inject/containers/clip.py —
        # the stable-diffusion text tower): causal pre-LN encoder, learned
        # positions, quick_gelu MLP, final LN, NO lm head (use
        # forward_hidden for features; tie_embeddings avoids a head param).
        act_map = {"quick_gelu": "quick_gelu", "gelu": "gelu_exact",
                   "gelu_new": "gelu", "gelu_pytorch_tanh": "gelu"}
        act = get("hidden_act", "quick_gelu")
        if act not in act_map:
            raise ValueError(f"clip_text_model: hidden_act={act!r} is not supported")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            n_layers=get("num_hidden_layers"),
            n_heads=get("num_attention_heads"),
            ffn_hidden_size=get("intermediate_size"),
            max_seq_len=get("max_position_embeddings", 77),
            norm="layernorm",
            activation=act_map[act],
            position="learned",
            norm_eps=float(get("layer_norm_eps", 1e-5)),
            tie_embeddings=True,  # no lm head: features come from forward_hidden
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
        )
    if mt == "bert":
        act_map = {"gelu": "gelu_exact", "gelu_new": "gelu", "gelu_pytorch_tanh": "gelu", "relu": "relu"}
        act = get("hidden_act", "gelu")
        if act not in act_map:
            raise ValueError(f"bert: hidden_act={act!r} is not supported")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            n_layers=get("num_hidden_layers"),
            n_heads=get("num_attention_heads"),
            ffn_hidden_size=get("intermediate_size"),
            max_seq_len=get("max_position_embeddings", 512),
            norm="layernorm",
            activation=act_map[act],
            position="learned",
            norm_eps=float(get("layer_norm_eps", 1e-12)),
            tie_embeddings=True,  # cls.predictions.decoder ties to embeddings
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
            attn_causal=False,
            norm_scheme="post",
            embed_norm=True,  # embeddings.LayerNorm after word+pos+type sum
            type_vocab_size=get("type_vocab_size", 2),
            final_norm=False,
            mlm_head=True,
        )
    if mt == "distilbert":
        if get("sinusoidal_pos_embds", False):
            raise ValueError("distilbert: sinusoidal_pos_embds is not supported (learned only)")
        act_map = {"gelu": "gelu_exact", "relu": "relu"}
        act = get("activation", "gelu")
        if act not in act_map:
            raise ValueError(f"distilbert: activation={act!r} is not supported")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=get("dim"),
            n_layers=get("n_layers"),
            n_heads=get("n_heads"),
            ffn_hidden_size=get("hidden_dim"),
            max_seq_len=get("max_position_embeddings", 512),
            norm="layernorm",
            activation=act_map[act],
            position="learned",
            norm_eps=1e-12,  # hardcoded in HF modeling_distilbert
            tie_embeddings=True,  # vocab_projector ties to embeddings
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
            attn_causal=False,
            norm_scheme="post",
            embed_norm=True,
            final_norm=False,
            mlm_head=True,
        )
    if mt == "megatron_gpt":
        # Megatron-LM GPT checkpoints (reference module_inject/containers/
        # megatron_gpt.py): gpt2-architecture model, megatron state-dict
        # naming with per-head-interleaved fused query_key_value
        h = get("hidden_size") or get("n_embd")
        act = get("activation_function", "gelu_new")
        act_map = {"gelu_new": "gelu", "gelu_pytorch_tanh": "gelu", "gelu": "gelu_exact"}
        if act not in act_map:
            raise ValueError(f"megatron_gpt: activation_function={act!r} is not supported")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=h,
            n_layers=get("num_layers") or get("num_hidden_layers") or get("n_layer"),
            n_heads=get("num_attention_heads") or get("n_head"),
            ffn_hidden_size=get("ffn_hidden_size", None) or 4 * h,
            max_seq_len=get("max_position_embeddings") or get("n_positions") or 1024,
            norm="layernorm",
            activation=act_map[act],
            position="learned",
            norm_eps=float(get("layernorm_epsilon", 1e-5)),
            tie_embeddings=True,  # megatron GPT always ties the output head
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
        )
    if mt == "falcon":
        if get("alibi", False):
            raise ValueError("falcon: alibi position encoding is not supported (rope checkpoints only)")
        nh = get("num_attention_heads")
        if get("new_decoder_architecture", False):
            n_kv = get("num_kv_heads", nh)
        else:
            n_kv = 1 if get("multi_query", True) else nh
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            n_layers=get("num_hidden_layers"),
            n_heads=nh,
            n_kv_heads=n_kv,
            ffn_hidden_size=get("ffn_hidden_size", None) or 4 * get("hidden_size"),
            max_seq_len=get("max_position_embeddings", 2048),
            norm="layernorm",
            activation="gelu_exact",  # falcon's MLP is torch nn.GELU (erf)
            position="rope",
            rope_theta=float(get("rope_theta", 10000.0)),
            rope_scaling=_parse_rope_scaling(get),
            norm_eps=float(get("layer_norm_epsilon", 1e-5)),
            tie_embeddings=bool(get("tie_word_embeddings", True)),
            parallel_block=bool(get("parallel_attn", True)),
            attn_qkv_bias=bool(get("bias", False)),
            attn_out_bias=bool(get("bias", False)),
            mlp_bias=bool(get("bias", False)),
        )
    if mt == "phi":
        act = get("hidden_act", "gelu_new")
        if act not in ("gelu_new", "gelu_pytorch_tanh"):
            # a 'gelu' (erf) checkpoint would silently load with tanh GELU
            raise ValueError(f"phi: hidden_act={act!r} is not supported (gelu_new only)")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            n_layers=get("num_hidden_layers"),
            n_heads=get("num_attention_heads"),
            n_kv_heads=get("num_key_value_heads", None),
            ffn_hidden_size=get("intermediate_size"),
            max_seq_len=get("max_position_embeddings", 2048),
            norm="layernorm",
            activation="gelu",
            position="rope",
            rope_theta=float(get("rope_theta", 10000.0)),
            rope_scaling=_parse_rope_scaling(get),
            norm_eps=float(get("layer_norm_eps", 1e-5)),
            tie_embeddings=bool(get("tie_word_embeddings", False)),
            parallel_block=True,
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
            lm_head_bias=True,
            rope_frac=float(get("partial_rotary_factor", 0.5)),
            # phi-1/2 qk_layernorm: one affine LayerNorm(head_dim) shared
            # across heads
            qk_norm=bool(get("qk_layernorm", False)),
            qk_norm_kind="layernorm",
        )
    if mt == "phi3":
        return _llama_like_config(get)
    if mt == "gpt2":
        h = get("n_embd")
        act = get("activation_function", "gelu_new")
        if act not in ("gelu_new", "gelu_pytorch_tanh"):
            raise ValueError(f"gpt2: activation_function={act!r} is not supported (gelu_new only)")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=h,
            n_layers=get("n_layer"),
            n_heads=get("n_head"),
            ffn_hidden_size=get("n_inner", None) or 4 * h,
            max_seq_len=get("n_positions", 1024),
            norm="layernorm",
            activation="gelu",  # gpt2 gelu_new = tanh approx
            position="learned",
            norm_eps=float(get("layer_norm_epsilon", 1e-5)),
            tie_embeddings=True,
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
        )
    if mt == "opt":
        h = get("hidden_size")
        if get("word_embed_proj_dim", h) != h:
            raise ValueError(
                "opt: word_embed_proj_dim != hidden_size (opt-350m-style "
                "embedding projection) is not supported"
            )
        if not get("do_layer_norm_before", True):
            raise ValueError("opt: post-layernorm (do_layer_norm_before=False) is not supported")
        # model_type "opt" covers relu (OPT) and gelu (Galactica) variants —
        # read the config instead of assuming, or gelu checkpoints would
        # silently run through relu
        act_map = {"relu": "relu", "gelu": "gelu_exact", "gelu_new": "gelu"}
        act = get("activation_function", "relu")
        if act not in act_map:
            raise ValueError(f"opt: activation_function={act!r} is not supported")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=h,
            n_layers=get("num_hidden_layers"),
            n_heads=get("num_attention_heads"),
            ffn_hidden_size=get("ffn_dim"),
            max_seq_len=get("max_position_embeddings", 2048),
            norm="layernorm",
            activation=act_map[act],
            position="learned",
            norm_eps=1e-5,
            tie_embeddings=bool(get("tie_word_embeddings", True)),
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
        )
    if mt == "gemma":
        act = get("hidden_activation", None) or get("hidden_act", "gelu_pytorch_tanh")
        if act != "gelu_pytorch_tanh":
            # "gelu" would mean HF's EXACT erf GELU; geglu here is tanh —
            # reject rather than silently diverge (gpt2 loader does the same)
            raise ValueError(f"gemma: hidden_activation={act!r} is not supported (gelu_pytorch_tanh only)")
        head_dim = get("head_dim", 256)
        derived = get("hidden_size") // get("num_attention_heads")
        return _llama_like_config(
            get,
            norm="rmsnorm_1p",  # zero-centered (1 + w) weights
            activation="geglu",  # gelu-gated MLP
            embed_scale=True,  # sqrt(h) embedding normalizer
            tie_embeddings=True,  # gemma always ties
            head_dim_override=int(head_dim) if int(head_dim) != derived else None,
        )
    if mt == "bloom":
        h = get("hidden_size") or get("n_embed")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=h,
            n_layers=get("n_layer") or get("num_hidden_layers"),
            n_heads=get("n_head") or get("num_attention_heads"),
            ffn_hidden_size=4 * h,
            max_seq_len=get("seq_length", 2048) or 2048,
            norm="layernorm",
            activation="gelu",  # BloomGelu is the tanh approximation
            position="alibi",
            norm_eps=float(get("layer_norm_epsilon", 1e-5)),
            tie_embeddings=True,  # bloom always ties lm_head to embeddings
            embed_norm=True,  # word_embeddings_layernorm
            attn_qkv_bias=True,
            attn_out_bias=True,
            mlp_bias=True,
        )
    if mt == "gptj":
        h = get("n_embd")
        act = get("activation_function", "gelu_new")
        if act not in ("gelu_new", "gelu_pytorch_tanh"):
            raise ValueError(f"gptj: activation_function={act!r} is not supported (gelu_new only)")
        d = h // get("n_head")
        rotary_dim = get("rotary_dim", None) or d
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=h,
            n_layers=get("n_layer"),
            n_heads=get("n_head"),
            ffn_hidden_size=get("n_inner", None) or 4 * h,
            max_seq_len=get("n_positions", 2048),
            norm="layernorm",
            activation="gelu",
            position="rope",
            # gptj's interleaved (rotate_every_two) rotary becomes the native
            # half-split convention via a load-time column permutation of
            # wq/wk (_gptj_layer) — the score q·k is permutation-invariant
            rope_frac=rotary_dim / d,
            norm_eps=float(get("layer_norm_epsilon", 1e-5)),
            tie_embeddings=False,
            parallel_block=True,  # shared ln_1 feeds both branches
            mlp_bias=True,
            lm_head_bias=True,  # GPTJForCausalLM's lm_head carries a bias
        )
    if mt == "gpt_neox":
        act = get("hidden_act", "gelu")
        act_map = {
            # HF ACT2FN: "gelu" is the ERF form; the others are tanh approx
            "gelu": "gelu_exact",
            "gelu_new": "gelu",
            "gelu_fast": "gelu",
            "gelu_pytorch_tanh": "gelu",
        }
        if act not in act_map:
            raise ValueError(f"gpt_neox: hidden_act={act!r} is not supported")
        return TransformerConfig(
            vocab_size=get("vocab_size"),
            hidden_size=get("hidden_size"),
            n_layers=get("num_hidden_layers"),
            n_heads=get("num_attention_heads"),
            ffn_hidden_size=get("intermediate_size"),
            max_seq_len=get("max_position_embeddings", 2048),
            norm="layernorm",
            activation=act_map[act],
            position="rope",
            rope_theta=float(get("rope_theta", None) or get("rotary_emb_base", 10000.0)),
            rope_scaling=_parse_rope_scaling(get),
            rope_frac=float(get("rotary_pct", 1.0)),
            norm_eps=float(get("layer_norm_eps", 1e-5)),
            tie_embeddings=bool(get("tie_word_embeddings", False)),
            parallel_block=bool(get("use_parallel_residual", True)),
            attn_qkv_bias=bool(get("attention_bias", True)),
            attn_out_bias=bool(get("attention_bias", True)),
            mlp_bias=True,
        )
    raise ValueError(
        f"unsupported model_type {mt!r}; supported: llama, mistral, qwen2, "
        "qwen2_moe, mixtral, falcon, phi, phi3, gpt2, gpt_neo, opt, gemma, "
        "bloom, gptj, gpt_neox, internlm, stablelm, starcoder2, "
        "qwen3, qwen3_moe, megatron_gpt, bert, distilbert, clip_text_model"
    )


# ---------------------------------------------------------------------------
# per-arch weight extraction
# ---------------------------------------------------------------------------
class _Taker:
    """state-dict accessor with dtype cast + [out,in]→[in,out] transpose."""

    def __init__(self, state: Dict[str, Any], dtype: str):
        self.state = state
        self.dtype = dtype

    def __call__(self, name) -> np.ndarray:
        return _np_cast(self.state.pop(name), self.dtype)

    def linear(self, name) -> np.ndarray:
        return self(name).T


def _llama_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    layers["attn_norm"].append(take(f"{p}.input_layernorm.weight"))
    layers["wq"].append(take.linear(f"{p}.self_attn.q_proj.weight"))
    layers["wk"].append(take.linear(f"{p}.self_attn.k_proj.weight"))
    layers["wv"].append(take.linear(f"{p}.self_attn.v_proj.weight"))
    layers["wo"].append(take.linear(f"{p}.self_attn.o_proj.weight"))
    layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
    if cfg.attn_qkv_bias:
        layers["wq_b"].append(take(f"{p}.self_attn.q_proj.bias"))
        layers["wk_b"].append(take(f"{p}.self_attn.k_proj.bias"))
        layers["wv_b"].append(take(f"{p}.self_attn.v_proj.bias"))
    if cfg.attn_out_bias:
        layers["wo_b"].append(take(f"{p}.self_attn.o_proj.bias"))
    if cfg.qk_norm:
        layers["q_norm"].append(take(f"{p}.self_attn.q_norm.weight"))
        layers["k_norm"].append(take(f"{p}.self_attn.k_norm.weight"))
    if cfg.n_experts > 0:
        # qwen2-moe: router gate [E, h] + per-expert FFNs + shared expert
        layers["router"].append(take.linear(f"{p}.mlp.gate.weight"))
        for name, hf in (("w_gate", "gate_proj"), ("w_up", "up_proj"), ("w_down", "down_proj")):
            layers[name].append(
                np.stack([take.linear(f"{p}.mlp.experts.{e}.{hf}.weight") for e in range(cfg.n_experts)])
            )
        if cfg.moe_shared_expert_dim > 0:
            layers["shared_gate"].append(take.linear(f"{p}.mlp.shared_expert.gate_proj.weight"))
            layers["shared_up"].append(take.linear(f"{p}.mlp.shared_expert.up_proj.weight"))
            layers["shared_down"].append(take.linear(f"{p}.mlp.shared_expert.down_proj.weight"))
            layers["shared_gate_proj"].append(take.linear(f"{p}.mlp.shared_expert_gate.weight"))
    else:
        layers["w_gate"].append(take.linear(f"{p}.mlp.gate_proj.weight"))
        layers["w_up"].append(take.linear(f"{p}.mlp.up_proj.weight"))
        layers["w_down"].append(take.linear(f"{p}.mlp.down_proj.weight"))


def _phi3_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # phi-3 fuses qkv_proj [q;k;v] and gate_up_proj [gate;up] — split rows
    layers["attn_norm"].append(take(f"{p}.input_layernorm.weight"))
    qkv = take(f"{p}.self_attn.qkv_proj.weight")  # [(nh+2*nkv)*d, h]
    q_rows = cfg.n_heads * cfg.head_dim
    kv_rows = cfg.kv_heads * cfg.head_dim
    layers["wq"].append(qkv[:q_rows].T)
    layers["wk"].append(qkv[q_rows : q_rows + kv_rows].T)
    layers["wv"].append(qkv[q_rows + kv_rows :].T)
    layers["wo"].append(take.linear(f"{p}.self_attn.o_proj.weight"))
    layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
    gate_up = take(f"{p}.mlp.gate_up_proj.weight")  # [2*ffn, h]
    ffn = gate_up.shape[0] // 2
    layers["w_gate"].append(gate_up[:ffn].T)
    layers["w_up"].append(gate_up[ffn:].T)
    layers["w_down"].append(take.linear(f"{p}.mlp.down_proj.weight"))


def _split_falcon_qkv(fused: np.ndarray, cfg: TransformerConfig) -> Tuple[np.ndarray, ...]:
    """De-interleave falcon's fused query_key_value rows.

    Every falcon layout is the per-kv-group interleave
    [q·(nh/nkv), k, v] — HF's legacy ``_split_heads`` views are its
    degenerate cases: MHA is group-of-3 per head (view ``(nh, 3, d)``) and
    multi_query is nkv=1 (all q rows, then k, then v). fused: [rows, h]
    (or [rows] for the bias). Returns (q, k, v) row-major.
    """
    d, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.kv_heads
    group = nh // nkv + 2
    blocks = fused.reshape(nkv, group, d, *fused.shape[1:])
    q = blocks[:, :-2].reshape(nh * d, *fused.shape[1:])
    k = blocks[:, -2].reshape(nkv * d, *fused.shape[1:])
    v = blocks[:, -1].reshape(nkv * d, *fused.shape[1:])
    return q, k, v


def _falcon_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    if f"{p}.ln_attn.weight" in take.state:  # new_decoder_architecture
        layers["attn_norm"].append(take(f"{p}.ln_attn.weight"))
        layers["attn_norm_b"].append(take(f"{p}.ln_attn.bias"))
        layers["mlp_norm"].append(take(f"{p}.ln_mlp.weight"))
        layers["mlp_norm_b"].append(take(f"{p}.ln_mlp.bias"))
    else:
        ln_w = take(f"{p}.input_layernorm.weight")
        ln_b = take(f"{p}.input_layernorm.bias")
        layers["attn_norm"].append(ln_w)
        layers["attn_norm_b"].append(ln_b)
        if cfg.parallel_block:
            # falcon-7b shares one norm across both branches
            layers["mlp_norm"].append(ln_w)
            layers["mlp_norm_b"].append(ln_b)
        else:
            layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
            layers["mlp_norm_b"].append(take(f"{p}.post_attention_layernorm.bias"))
    q, k, v = _split_falcon_qkv(take(f"{p}.self_attention.query_key_value.weight"), cfg)
    layers["wq"].append(q.T)
    layers["wk"].append(k.T)
    layers["wv"].append(v.T)
    layers["wo"].append(take.linear(f"{p}.self_attention.dense.weight"))
    layers["w_up"].append(take.linear(f"{p}.mlp.dense_h_to_4h.weight"))
    layers["w_down"].append(take.linear(f"{p}.mlp.dense_4h_to_h.weight"))
    if cfg.attn_qkv_bias:
        qb, kb, vb = _split_falcon_qkv(take(f"{p}.self_attention.query_key_value.bias"), cfg)
        layers["wq_b"].append(qb)
        layers["wk_b"].append(kb)
        layers["wv_b"].append(vb)
        layers["wo_b"].append(take(f"{p}.self_attention.dense.bias"))
        layers["w_up_b"].append(take(f"{p}.mlp.dense_h_to_4h.bias"))
        layers["w_down_b"].append(take(f"{p}.mlp.dense_4h_to_h.bias"))


def _phi_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # phi: one shared input_layernorm feeds both parallel branches
    ln_w = take(f"{p}.input_layernorm.weight")
    ln_b = take(f"{p}.input_layernorm.bias")
    layers["attn_norm"].append(ln_w)
    layers["attn_norm_b"].append(ln_b)
    layers["mlp_norm"].append(ln_w)
    layers["mlp_norm_b"].append(ln_b)
    for name, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj")):
        layers[name].append(take.linear(f"{p}.self_attn.{hf}.weight"))
        layers[f"{name}_b"].append(take(f"{p}.self_attn.{hf}.bias"))
    layers["wo"].append(take.linear(f"{p}.self_attn.dense.weight"))
    layers["wo_b"].append(take(f"{p}.self_attn.dense.bias"))
    if cfg.qk_norm:
        layers["q_norm"].append(take(f"{p}.self_attn.q_layernorm.weight"))
        layers["q_norm_b"].append(take(f"{p}.self_attn.q_layernorm.bias"))
        layers["k_norm"].append(take(f"{p}.self_attn.k_layernorm.weight"))
        layers["k_norm_b"].append(take(f"{p}.self_attn.k_layernorm.bias"))
    layers["w_up"].append(take.linear(f"{p}.mlp.fc1.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.fc1.bias"))
    layers["w_down"].append(take.linear(f"{p}.mlp.fc2.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.fc2.bias"))


def _gpt2_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # GPT-2 Conv1D stores [in, out] — NO transpose; c_attn fuses qkv columns
    layers["attn_norm"].append(take(f"{p}.ln_1.weight"))
    layers["attn_norm_b"].append(take(f"{p}.ln_1.bias"))
    h = cfg.hidden_size
    w = take(f"{p}.attn.c_attn.weight")  # [h, 3h]
    b = take(f"{p}.attn.c_attn.bias")  # [3h]
    layers["wq"].append(w[:, :h])
    layers["wk"].append(w[:, h : 2 * h])
    layers["wv"].append(w[:, 2 * h :])
    layers["wq_b"].append(b[:h])
    layers["wk_b"].append(b[h : 2 * h])
    layers["wv_b"].append(b[2 * h :])
    layers["wo"].append(take(f"{p}.attn.c_proj.weight"))
    layers["wo_b"].append(take(f"{p}.attn.c_proj.bias"))
    layers["mlp_norm"].append(take(f"{p}.ln_2.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.ln_2.bias"))
    layers["w_up"].append(take(f"{p}.mlp.c_fc.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.c_fc.bias"))
    layers["w_down"].append(take(f"{p}.mlp.c_proj.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.c_proj.bias"))


def _opt_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    layers["attn_norm"].append(take(f"{p}.self_attn_layer_norm.weight"))
    layers["attn_norm_b"].append(take(f"{p}.self_attn_layer_norm.bias"))
    for name, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj")):
        layers[name].append(take.linear(f"{p}.self_attn.{hf}.weight"))
        layers[f"{name}_b"].append(take(f"{p}.self_attn.{hf}.bias"))
    layers["wo"].append(take.linear(f"{p}.self_attn.out_proj.weight"))
    layers["wo_b"].append(take(f"{p}.self_attn.out_proj.bias"))
    layers["mlp_norm"].append(take(f"{p}.final_layer_norm.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.final_layer_norm.bias"))
    layers["w_up"].append(take.linear(f"{p}.fc1.weight"))
    layers["w_up_b"].append(take(f"{p}.fc1.bias"))
    layers["w_down"].append(take.linear(f"{p}.fc2.weight"))
    layers["w_down_b"].append(take(f"{p}.fc2.bias"))


def _mixtral_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # llama attention + block-sparse MoE: w1=gate, w3=up, w2=down
    layers["attn_norm"].append(take(f"{p}.input_layernorm.weight"))
    layers["wq"].append(take.linear(f"{p}.self_attn.q_proj.weight"))
    layers["wk"].append(take.linear(f"{p}.self_attn.k_proj.weight"))
    layers["wv"].append(take.linear(f"{p}.self_attn.v_proj.weight"))
    layers["wo"].append(take.linear(f"{p}.self_attn.o_proj.weight"))
    layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
    layers["router"].append(take.linear(f"{p}.block_sparse_moe.gate.weight"))
    for name, hf in (("w_gate", "w1"), ("w_up", "w3"), ("w_down", "w2")):
        layers[name].append(
            np.stack([
                take.linear(f"{p}.block_sparse_moe.experts.{e}.{hf}.weight")
                for e in range(cfg.n_experts)
            ])
        )


def _stablelm_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    ln_w = take(f"{p}.input_layernorm.weight")
    ln_b = take(f"{p}.input_layernorm.bias")
    layers["attn_norm"].append(ln_w)
    layers["attn_norm_b"].append(ln_b)
    if cfg.parallel_block:
        # parallel residual shares input_layernorm (gpt-j-style)
        layers["mlp_norm"].append(ln_w)
        layers["mlp_norm_b"].append(ln_b)
    else:
        layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
        layers["mlp_norm_b"].append(take(f"{p}.post_attention_layernorm.bias"))
    for name, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj")):
        layers[name].append(take.linear(f"{p}.self_attn.{hf}.weight"))
        if cfg.attn_qkv_bias:
            layers[f"{name}_b"].append(take(f"{p}.self_attn.{hf}.bias"))
    layers["wo"].append(take.linear(f"{p}.self_attn.o_proj.weight"))
    if cfg.qk_norm:
        # stablelm-2 qk_layernorm: a ModuleList of biasless per-head
        # LayerNorms — stack the [d] weights into [n_heads, d]
        layers["q_norm"].append(
            np.stack([take(f"{p}.self_attn.q_layernorm.norms.{h}.weight") for h in range(cfg.n_heads)])
        )
        layers["k_norm"].append(
            np.stack([take(f"{p}.self_attn.k_layernorm.norms.{h}.weight") for h in range(cfg.kv_heads)])
        )
    layers["w_gate"].append(take.linear(f"{p}.mlp.gate_proj.weight"))
    layers["w_up"].append(take.linear(f"{p}.mlp.up_proj.weight"))
    layers["w_down"].append(take.linear(f"{p}.mlp.down_proj.weight"))


def _starcoder2_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    layers["attn_norm"].append(take(f"{p}.input_layernorm.weight"))
    layers["attn_norm_b"].append(take(f"{p}.input_layernorm.bias"))
    layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.post_attention_layernorm.bias"))
    for name, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj")):
        layers[name].append(take.linear(f"{p}.self_attn.{hf}.weight"))
        if cfg.attn_qkv_bias:  # use_bias=False checkpoints ship no biases
            layers[f"{name}_b"].append(take(f"{p}.self_attn.{hf}.bias"))
    layers["wo"].append(take.linear(f"{p}.self_attn.o_proj.weight"))
    if cfg.attn_out_bias:
        layers["wo_b"].append(take(f"{p}.self_attn.o_proj.bias"))
    layers["w_up"].append(take.linear(f"{p}.mlp.c_fc.weight"))
    layers["w_down"].append(take.linear(f"{p}.mlp.c_proj.weight"))
    if cfg.mlp_bias:
        layers["w_up_b"].append(take(f"{p}.mlp.c_fc.bias"))
        layers["w_down_b"].append(take(f"{p}.mlp.c_proj.bias"))


def _bloom_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # bloom: MHA with per-head [q,k,v] interleaved fused qkv — the falcon
    # MHA degenerate case (group-of-3 per head) splits it
    layers["attn_norm"].append(take(f"{p}.input_layernorm.weight"))
    layers["attn_norm_b"].append(take(f"{p}.input_layernorm.bias"))
    layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.post_attention_layernorm.bias"))
    q, k, v = _split_falcon_qkv(take(f"{p}.self_attention.query_key_value.weight"), cfg)
    layers["wq"].append(q.T)
    layers["wk"].append(k.T)
    layers["wv"].append(v.T)
    qb, kb, vb = _split_falcon_qkv(take(f"{p}.self_attention.query_key_value.bias"), cfg)
    layers["wq_b"].append(qb)
    layers["wk_b"].append(kb)
    layers["wv_b"].append(vb)
    layers["wo"].append(take.linear(f"{p}.self_attention.dense.weight"))
    layers["wo_b"].append(take(f"{p}.self_attention.dense.bias"))
    layers["w_up"].append(take.linear(f"{p}.mlp.dense_h_to_4h.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.dense_h_to_4h.bias"))
    layers["w_down"].append(take.linear(f"{p}.mlp.dense_4h_to_h.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.dense_4h_to_h.bias"))


def _gptj_rope_perm(w: np.ndarray, cfg: TransformerConfig) -> np.ndarray:
    """Permute a [h, nh*d] projection's per-head rotary columns from gptj's
    interleaved (rotate_every_two) layout to the half-split layout: new
    column i ← old 2i, new rot/2+i ← old 2i+1. Scores are invariant because
    q and k get the SAME permutation."""
    d = cfg.head_dim
    rot = (int(d * cfg.rope_frac) // 2) * 2
    perm = np.concatenate([np.arange(0, rot, 2), np.arange(1, rot, 2), np.arange(rot, d)])
    cols = w.reshape(w.shape[0], cfg.n_heads, d)
    return cols[:, :, perm].reshape(w.shape)


def _gptj_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    ln_w = take(f"{p}.ln_1.weight")
    ln_b = take(f"{p}.ln_1.bias")
    layers["attn_norm"].append(ln_w)
    layers["attn_norm_b"].append(ln_b)
    layers["mlp_norm"].append(ln_w)  # shared norm feeds both parallel branches
    layers["mlp_norm_b"].append(ln_b)
    layers["wq"].append(_gptj_rope_perm(take.linear(f"{p}.attn.q_proj.weight"), cfg))
    layers["wk"].append(_gptj_rope_perm(take.linear(f"{p}.attn.k_proj.weight"), cfg))
    layers["wv"].append(take.linear(f"{p}.attn.v_proj.weight"))
    layers["wo"].append(take.linear(f"{p}.attn.out_proj.weight"))
    layers["w_up"].append(take.linear(f"{p}.mlp.fc_in.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.fc_in.bias"))
    layers["w_down"].append(take.linear(f"{p}.mlp.fc_out.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.fc_out.bias"))


def _clip_text_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    layers["attn_norm"].append(take(f"{p}.layer_norm1.weight"))
    layers["attn_norm_b"].append(take(f"{p}.layer_norm1.bias"))
    for name, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj")):
        layers[name].append(take.linear(f"{p}.self_attn.{hf}.weight"))
        layers[f"{name}_b"].append(take(f"{p}.self_attn.{hf}.bias"))
    layers["wo"].append(take.linear(f"{p}.self_attn.out_proj.weight"))
    layers["wo_b"].append(take(f"{p}.self_attn.out_proj.bias"))
    layers["mlp_norm"].append(take(f"{p}.layer_norm2.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.layer_norm2.bias"))
    layers["w_up"].append(take.linear(f"{p}.mlp.fc1.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.fc1.bias"))
    layers["w_down"].append(take.linear(f"{p}.mlp.fc2.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.fc2.bias"))


def _bert_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # post-LN encoder: attention.output.LayerNorm normalizes x + attn(x)
    # (→ attn_norm), output.LayerNorm normalizes + mlp (→ mlp_norm)
    for name, hf in (("wq", "query"), ("wk", "key"), ("wv", "value")):
        layers[name].append(take.linear(f"{p}.attention.self.{hf}.weight"))
        layers[f"{name}_b"].append(take(f"{p}.attention.self.{hf}.bias"))
    layers["wo"].append(take.linear(f"{p}.attention.output.dense.weight"))
    layers["wo_b"].append(take(f"{p}.attention.output.dense.bias"))
    layers["attn_norm"].append(take(f"{p}.attention.output.LayerNorm.weight"))
    layers["attn_norm_b"].append(take(f"{p}.attention.output.LayerNorm.bias"))
    layers["w_up"].append(take.linear(f"{p}.intermediate.dense.weight"))
    layers["w_up_b"].append(take(f"{p}.intermediate.dense.bias"))
    layers["w_down"].append(take.linear(f"{p}.output.dense.weight"))
    layers["w_down_b"].append(take(f"{p}.output.dense.bias"))
    layers["mlp_norm"].append(take(f"{p}.output.LayerNorm.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.output.LayerNorm.bias"))


def _distilbert_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    for name, hf in (("wq", "q_lin"), ("wk", "k_lin"), ("wv", "v_lin")):
        layers[name].append(take.linear(f"{p}.attention.{hf}.weight"))
        layers[f"{name}_b"].append(take(f"{p}.attention.{hf}.bias"))
    layers["wo"].append(take.linear(f"{p}.attention.out_lin.weight"))
    layers["wo_b"].append(take(f"{p}.attention.out_lin.bias"))
    layers["attn_norm"].append(take(f"{p}.sa_layer_norm.weight"))
    layers["attn_norm_b"].append(take(f"{p}.sa_layer_norm.bias"))
    layers["w_up"].append(take.linear(f"{p}.ffn.lin1.weight"))
    layers["w_up_b"].append(take(f"{p}.ffn.lin1.bias"))
    layers["w_down"].append(take.linear(f"{p}.ffn.lin2.weight"))
    layers["w_down_b"].append(take(f"{p}.ffn.lin2.bias"))
    layers["mlp_norm"].append(take(f"{p}.output_layer_norm.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.output_layer_norm.bias"))


def _gptneo_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # GPT-Neo uses plain Linears ([out, in] — transpose), unlike gpt2's
    # Conv1D; q/k/v carry NO bias, out_proj and the MLP do
    layers["attn_norm"].append(take(f"{p}.ln_1.weight"))
    layers["attn_norm_b"].append(take(f"{p}.ln_1.bias"))
    for name, hf in (("wq", "q_proj"), ("wk", "k_proj"), ("wv", "v_proj")):
        layers[name].append(take.linear(f"{p}.attn.attention.{hf}.weight"))
    layers["wo"].append(take.linear(f"{p}.attn.attention.out_proj.weight"))
    layers["wo_b"].append(take(f"{p}.attn.attention.out_proj.bias"))
    layers["mlp_norm"].append(take(f"{p}.ln_2.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.ln_2.bias"))
    layers["w_up"].append(take.linear(f"{p}.mlp.c_fc.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.c_fc.bias"))
    layers["w_down"].append(take.linear(f"{p}.mlp.c_proj.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.c_proj.bias"))


def _megatron_gpt_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    # megatron fuses qkv per head ([q_h, k_h, v_h] blocks) — the falcon MHA
    # de-interleave (group-of-3 per head) recovers row-major q/k/v
    layers["attn_norm"].append(take(f"{p}.input_layernorm.weight"))
    layers["attn_norm_b"].append(take(f"{p}.input_layernorm.bias"))
    q, k, v = _split_falcon_qkv(take(f"{p}.attention.query_key_value.weight"), cfg)
    layers["wq"].append(q.T)
    layers["wk"].append(k.T)
    layers["wv"].append(v.T)
    qb, kb, vb = _split_falcon_qkv(take(f"{p}.attention.query_key_value.bias"), cfg)
    layers["wq_b"].append(qb)
    layers["wk_b"].append(kb)
    layers["wv_b"].append(vb)
    layers["wo"].append(take.linear(f"{p}.attention.dense.weight"))
    layers["wo_b"].append(take(f"{p}.attention.dense.bias"))
    layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.post_attention_layernorm.bias"))
    layers["w_up"].append(take.linear(f"{p}.mlp.dense_h_to_4h.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.dense_h_to_4h.bias"))
    layers["w_down"].append(take.linear(f"{p}.mlp.dense_4h_to_h.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.dense_4h_to_h.bias"))


def _gptneox_layer(take: _Taker, cfg: TransformerConfig, p: str, layers: Dict[str, list]):
    layers["attn_norm"].append(take(f"{p}.input_layernorm.weight"))
    layers["attn_norm_b"].append(take(f"{p}.input_layernorm.bias"))
    layers["mlp_norm"].append(take(f"{p}.post_attention_layernorm.weight"))
    layers["mlp_norm_b"].append(take(f"{p}.post_attention_layernorm.bias"))
    q, k, v = _split_falcon_qkv(take(f"{p}.attention.query_key_value.weight"), cfg)
    layers["wq"].append(q.T)
    layers["wk"].append(k.T)
    layers["wv"].append(v.T)
    if cfg.attn_qkv_bias:
        qb, kb, vb = _split_falcon_qkv(take(f"{p}.attention.query_key_value.bias"), cfg)
        layers["wq_b"].append(qb)
        layers["wk_b"].append(kb)
        layers["wv_b"].append(vb)
    layers["wo"].append(take.linear(f"{p}.attention.dense.weight"))
    if cfg.attn_out_bias:
        layers["wo_b"].append(take(f"{p}.attention.dense.bias"))
    layers["w_up"].append(take.linear(f"{p}.mlp.dense_h_to_4h.weight"))
    layers["w_up_b"].append(take(f"{p}.mlp.dense_h_to_4h.bias"))
    layers["w_down"].append(take.linear(f"{p}.mlp.dense_4h_to_h.weight"))
    layers["w_down_b"].append(take(f"{p}.mlp.dense_4h_to_h.bias"))


_LAYER_EXTRACTORS: Dict[str, Callable] = {
    "llama": _llama_layer,
    "mistral": _llama_layer,
    "qwen2": _llama_layer,
    "qwen2_moe": _llama_layer,
    "qwen3": _llama_layer,
    "qwen3_moe": _llama_layer,
    "falcon": _falcon_layer,
    "phi": _phi_layer,
    "phi3": _phi3_layer,
    "bert": _bert_layer,
    "clip_text_model": _clip_text_layer,
    "distilbert": _distilbert_layer,
    "gpt2": _gpt2_layer,
    "gpt_neo": _gptneo_layer,
    "internlm": _llama_layer,
    "opt": _opt_layer,
    "gemma": _llama_layer,  # same checkpoint layout as llama
    "bloom": _bloom_layer,
    "gptj": _gptj_layer,
    "gpt_neox": _gptneox_layer,
    "megatron_gpt": _megatron_gpt_layer,
    "mixtral": _mixtral_layer,
    "stablelm": _stablelm_layer,
    "starcoder2": _starcoder2_layer,
}

# per-arch (embed key, final-norm key, layer prefix, pos-embed key or None)
_TOPLEVEL_KEYS: Dict[str, Tuple[str, str, str, Optional[str]]] = {
    "llama": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "mistral": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "qwen2": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "qwen2_moe": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "qwen3": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "qwen3_moe": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "phi3": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "phi": ("model.embed_tokens.weight", "model.final_layernorm", "model.layers", None),
    "falcon": ("transformer.word_embeddings.weight", "transformer.ln_f", "transformer.h", None),
    "gpt2": ("transformer.wte.weight", "transformer.ln_f", "transformer.h", "transformer.wpe.weight"),
    "gpt_neo": ("transformer.wte.weight", "transformer.ln_f", "transformer.h", "transformer.wpe.weight"),
    "internlm": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "opt": (
        "model.decoder.embed_tokens.weight",
        "model.decoder.final_layer_norm",
        "model.decoder.layers",
        "model.decoder.embed_positions.weight",
    ),
    "gemma": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "bloom": ("transformer.word_embeddings.weight", "transformer.ln_f", "transformer.h", None),
    "gptj": ("transformer.wte.weight", "transformer.ln_f", "transformer.h", None),
    "gpt_neox": ("gpt_neox.embed_in.weight", "gpt_neox.final_layer_norm", "gpt_neox.layers", None),
    "megatron_gpt": (
        "word_embeddings.weight",
        "transformer.final_layernorm",
        "transformer.layers",
        "position_embeddings.weight",
    ),
    "clip_text_model": (
        "text_model.embeddings.token_embedding.weight",
        "text_model.final_layer_norm",
        "text_model.encoder.layers",
        "text_model.embeddings.position_embedding.weight",
    ),
    "mixtral": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "stablelm": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
    "starcoder2": ("model.embed_tokens.weight", "model.norm", "model.layers", None),
}


def _expected_layer_keys(cfg: TransformerConfig) -> Dict[str, list]:
    """Empty stacking lists for exactly the keys this config's params carry."""
    keys = ["attn_norm", "wq", "wk", "wv", "wo", "mlp_norm", "w_up", "w_down"]
    if cfg.activation in ("swiglu", "geglu"):
        keys.append("w_gate")
    if cfg.norm == "layernorm":
        keys += ["attn_norm_b", "mlp_norm_b"]
    if cfg.attn_qkv_bias:
        keys += ["wq_b", "wk_b", "wv_b"]
    if cfg.attn_out_bias:
        keys.append("wo_b")
    if cfg.qk_norm:
        keys += ["q_norm", "k_norm"]
        if cfg.qk_norm_kind == "layernorm":
            keys += ["q_norm_b", "k_norm_b"]
    if cfg.mlp_bias and cfg.n_experts == 0:
        keys += ["w_up_b", "w_down_b"] + (["w_gate_b"] if cfg.activation in ("swiglu", "geglu") else [])
    if cfg.n_experts > 0:
        keys.append("router")
        if cfg.moe_shared_expert_dim > 0:
            keys += ["shared_gate", "shared_up", "shared_down", "shared_gate_proj"]
    return {k: [] for k in keys}


def _load_encoder(mt: str, cfg: TransformerConfig, take: _Taker, state: Dict[str, Any]):
    """bert / distilbert (reference module_inject/containers/{bert,
    distil_bert}.py): post-LN encoder stack + masked-LM head. A bare
    BertModel checkpoint (no cls.predictions / vocab_transform) loads with
    mlm_head=False and returns the final hidden states from forward_hidden."""
    # BertForMaskedLM prefixes the backbone with "bert." / "distilbert.";
    # a bare BertModel/DistilBertModel checkpoint saves root-level keys
    base = "" if "embeddings.word_embeddings.weight" in state else f"{mt}."
    stem = f"{base}embeddings"
    prefix = f"{base}encoder.layer" if mt == "bert" else f"{base}transformer.layer"
    head_probe = "cls.predictions.transform.dense.weight" if mt == "bert" else "vocab_transform.weight"
    if head_probe not in state:
        cfg = dataclasses.replace(cfg, mlm_head=False)
    layers = _expected_layer_keys(cfg)
    extract = _LAYER_EXTRACTORS[mt]
    for i in range(cfg.n_layers):
        extract(take, cfg, f"{prefix}.{i}", layers)
    params: Dict[str, Any] = {
        "embed": take(f"{stem}.word_embeddings.weight"),
        "pos_embed": take(f"{stem}.position_embeddings.weight"),
        "embed_norm": take(f"{stem}.LayerNorm.weight"),
        "embed_norm_b": take(f"{stem}.LayerNorm.bias"),
        "layers": {k: np.stack(v) for k, v in layers.items()},
    }
    if cfg.type_vocab_size > 0:
        params["type_embed"] = take(f"{stem}.token_type_embeddings.weight")
    if cfg.mlm_head:
        if mt == "bert":
            params["mlm_dense"] = take.linear("cls.predictions.transform.dense.weight")
            params["mlm_dense_b"] = take("cls.predictions.transform.dense.bias")
            params["mlm_norm"] = take("cls.predictions.transform.LayerNorm.weight")
            params["mlm_norm_b"] = take("cls.predictions.transform.LayerNorm.bias")
            params["mlm_bias"] = take("cls.predictions.bias")
            state.pop("cls.predictions.decoder.weight", None)  # tied alias
            state.pop("cls.predictions.decoder.bias", None)  # alias of cls.predictions.bias
        else:
            params["mlm_dense"] = take.linear("vocab_transform.weight")
            params["mlm_dense_b"] = take("vocab_transform.bias")
            params["mlm_norm"] = take("vocab_layer_norm.weight")
            params["mlm_norm_b"] = take("vocab_layer_norm.bias")
            params["mlm_bias"] = take("vocab_projector.bias")
            state.pop("vocab_projector.weight", None)  # tied alias
    leftover = [
        k for k in state
        if not k.endswith("position_ids")  # non-persistent HF buffer
    ]
    if leftover:
        logger.warning(f"unmapped HF weights ignored: {leftover[:8]}{'...' if len(leftover) > 8 else ''}")
    return cfg, params


def load_hf_model(
    model_name_or_path: str,
    dtype: str = "bfloat16",
) -> Tuple[TransformerConfig, Dict[str, Any]]:
    """Load a supported HF checkpoint directory into the native family's
    stacked layout. Returns (config, params) — feed them to
    ``make_loss_fn(config)`` + ``initialize(model_parameters=params)`` or the
    inference engines."""
    cfg_path = os.path.join(model_name_or_path, "config.json")
    if not os.path.isfile(cfg_path):
        raise FileNotFoundError(
            f"{model_name_or_path} is not a checkpoint dir (no config.json); "
            "download/snapshot the model first — there is no network access at load time"
        )
    hf_cfg = json.load(open(cfg_path))
    mt = hf_cfg.get("model_type", "llama")
    if mt not in _LAYER_EXTRACTORS:
        raise ValueError(f"unsupported model_type {mt!r}; supported: {sorted(_LAYER_EXTRACTORS)}")
    cfg = dataclass_replace(config_from_hf(hf_cfg), dtype=dtype)
    state = _load_state_dict(model_name_or_path)
    take = _Taker(state, dtype)

    if mt in ("bert", "distilbert"):
        return _load_encoder(mt, cfg, take, state)

    embed_key, norm_key, layer_prefix, pos_key = _TOPLEVEL_KEYS[mt]
    extract = _LAYER_EXTRACTORS[mt]
    layers = _expected_layer_keys(cfg)
    for i in range(cfg.n_layers):
        extract(take, cfg, f"{layer_prefix}.{i}", layers)

    params: Dict[str, Any] = {
        "embed": take(embed_key),
        "final_norm": take(f"{norm_key}.weight"),
        "layers": {k: np.stack(v) for k, v in layers.items()},
    }
    if cfg.norm == "layernorm":
        params["final_norm_b"] = take(f"{norm_key}.bias")
    if cfg.position == "learned":
        pe = take(pos_key)
        if mt == "opt":
            pe = pe[2:]  # OPT offsets learned positions by 2
        params["pos_embed"] = pe
    if cfg.embed_norm:
        params["embed_norm"] = take("transformer.word_embeddings_layernorm.weight")
        params["embed_norm_b"] = take("transformer.word_embeddings_layernorm.bias")
    if not cfg.tie_embeddings:
        if "embed_out.weight" in state:  # gpt_neox names its lm_head embed_out
            params["lm_head"] = take.linear("embed_out.weight")
        elif "lm_head.weight" in state:
            params["lm_head"] = take.linear("lm_head.weight")
            if cfg.lm_head_bias:
                params["lm_head_b"] = take("lm_head.bias")
        elif cfg.lm_head_bias:
            raise ValueError("checkpoint declares a biased lm_head but ships no lm_head.weight")
        else:
            logger.warning("no lm_head.weight in checkpoint; tying to embeddings")
            cfg = dataclass_replace(cfg, tie_embeddings=True)
    else:
        state.pop("lm_head.weight", None)
    leftover = [k for k in state if not k.endswith("rotary_emb.inv_freq")]
    if leftover:
        logger.warning(f"unmapped HF weights ignored: {leftover[:8]}{'...' if len(leftover) > 8 else ''}")
    return cfg, params


# legacy name (round-1 API); the registry now handles every supported arch
load_hf_llama = load_hf_model
