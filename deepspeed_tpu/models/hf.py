"""HF checkpoint import: llama/mistral-family → the native model family.

Analogue of the reference checkpoint-shard loading
(``module_inject/load_checkpoint.py``, ``inference/engine.py:303`` meta-load
path): a HF `LlamaForCausalLM` (or mistral — same layout) directory becomes a
(:class:`TransformerConfig`, stacked-params pytree) pair that trains or
serves through ``deepspeed_tpu.initialize`` / ``init_inference`` unchanged.

Weight-layout notes (why each mapping is what it is):
  * HF Linear stores ``[out, in]``; this model family uses JAX's ``[in,
    out]`` → transpose every projection.
  * Layers here are STACKED along a leading ``[n_layers, ...]`` dim (the
    ``lax.scan`` layout), so per-layer tensors stack after transposing.
  * RoPE: HF llama's ``rotate_half`` IS the half-split convention used by
    ``transformer._rope`` — weights map 1:1, no permutation needed.
  * ``torch`` is only used to read the checkpoint on host (CPU); arrays
    convert to numpy before entering JAX.
"""

import dataclasses
import json
import os
from typing import Any, Dict, Tuple

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig
from deepspeed_tpu.utils.logging import logger


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach()
    if hasattr(t, "float") and str(getattr(t, "dtype", "")).startswith("torch.bfloat16"):
        t = t.float()
    return np.asarray(t.cpu() if hasattr(t, "cpu") else t)


def config_from_hf(hf_cfg) -> TransformerConfig:
    """HF LlamaConfig/MistralConfig (object or dict) → TransformerConfig."""
    get = (lambda k, d=None: hf_cfg.get(k, d)) if isinstance(hf_cfg, dict) else (
        lambda k, d=None: getattr(hf_cfg, k, d)
    )
    head_dim = get("head_dim", None)
    derived = get("hidden_size") // get("num_attention_heads")
    if head_dim is not None and int(head_dim) != derived:
        # mistral-nemo-style decoupled head_dim: the native family derives
        # head_dim = hidden/n_heads, so the qkv shapes would not line up —
        # fail at load time with the real reason, not a reshape error later
        raise ValueError(
            f"unsupported checkpoint: head_dim={head_dim} != hidden/num_heads={derived} "
            "(decoupled head_dim is not representable in TransformerConfig yet)"
        )
    return TransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        n_layers=get("num_hidden_layers"),
        n_heads=get("num_attention_heads"),
        n_kv_heads=get("num_key_value_heads", None),
        ffn_hidden_size=get("intermediate_size"),
        max_seq_len=get("max_position_embeddings", 2048),
        norm="rmsnorm",
        activation="swiglu",
        position="rope",
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        tie_embeddings=bool(get("tie_word_embeddings", False)),
    )


def _load_state_dict(path: str) -> Dict[str, Any]:
    """Read all weights of a HF checkpoint dir (safetensors preferred,
    sharded or single-file; torch .bin fallback)."""
    index = os.path.join(path, "model.safetensors.index.json")
    single_st = os.path.join(path, "model.safetensors")
    torch_bin = os.path.join(path, "pytorch_model.bin")
    state: Dict[str, Any] = {}
    if os.path.isfile(index) or os.path.isfile(single_st):
        # framework="pt": the numpy backend cannot represent bf16 tensors;
        # torch (cpu) reads them and _to_np upcasts
        from safetensors import safe_open

        files = (
            sorted({os.path.join(path, s) for s in json.load(open(index))["weight_map"].values()})
            if os.path.isfile(index)
            else [single_st]
        )
        for shard in files:
            with safe_open(shard, framework="pt") as f:
                for k in f.keys():
                    state[k] = _to_np(f.get_tensor(k))
    elif os.path.isfile(torch_bin):
        import torch

        state = {k: _to_np(v) for k, v in torch.load(torch_bin, map_location="cpu", weights_only=True).items()}
    else:
        raise FileNotFoundError(f"no safetensors/bin checkpoint under {path}")
    return state


def load_hf_llama(
    model_name_or_path: str,
    dtype: str = "bfloat16",
) -> Tuple[TransformerConfig, Dict[str, Any]]:
    """Load a llama/mistral-family HF checkpoint directory into the native
    family's stacked layout. Returns (config, params) — feed them to
    ``make_loss_fn(config)`` + ``initialize(model_parameters=params)`` or the
    inference engine."""
    cfg_path = os.path.join(model_name_or_path, "config.json")
    if not os.path.isfile(cfg_path):
        raise FileNotFoundError(
            f"{model_name_or_path} is not a checkpoint dir (no config.json); "
            "download/snapshot the model first — there is no network access at load time"
        )
    hf_cfg = json.load(open(cfg_path))
    cfg = dataclass_replace(config_from_hf(hf_cfg), dtype=dtype)
    state = _load_state_dict(model_name_or_path)

    P = "model.layers.{i}.{name}"

    def take(name) -> np.ndarray:
        return _np_cast(state.pop(name), dtype)

    def take_linear(name) -> np.ndarray:
        return take(name).T  # [out, in] → [in, out]

    layers: Dict[str, list] = {
        "attn_norm": [], "wq": [], "wk": [], "wv": [], "wo": [],
        "mlp_norm": [], "w_gate": [], "w_up": [], "w_down": [],
    }
    for i in range(cfg.n_layers):
        layers["attn_norm"].append(take(P.format(i=i, name="input_layernorm.weight")))
        layers["wq"].append(take_linear(P.format(i=i, name="self_attn.q_proj.weight")))
        layers["wk"].append(take_linear(P.format(i=i, name="self_attn.k_proj.weight")))
        layers["wv"].append(take_linear(P.format(i=i, name="self_attn.v_proj.weight")))
        layers["wo"].append(take_linear(P.format(i=i, name="self_attn.o_proj.weight")))
        layers["mlp_norm"].append(take(P.format(i=i, name="post_attention_layernorm.weight")))
        layers["w_gate"].append(take_linear(P.format(i=i, name="mlp.gate_proj.weight")))
        layers["w_up"].append(take_linear(P.format(i=i, name="mlp.up_proj.weight")))
        layers["w_down"].append(take_linear(P.format(i=i, name="mlp.down_proj.weight")))

    params: Dict[str, Any] = {
        "embed": _np_cast(state.pop("model.embed_tokens.weight"), dtype),
        "final_norm": take("model.norm.weight"),
        "layers": {k: np.stack(v) for k, v in layers.items()},
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" in state:
            params["lm_head"] = _np_cast(state.pop("lm_head.weight"), dtype).T
        else:
            logger.warning("no lm_head.weight in checkpoint; tying to embeddings")
            cfg = dataclass_replace(cfg, tie_embeddings=True)
    else:
        state.pop("lm_head.weight", None)
    leftover = [k for k in state if not k.endswith("rotary_emb.inv_freq")]
    if leftover:
        logger.warning(f"unmapped HF weights ignored: {leftover[:8]}{'...' if len(leftover) > 8 else ''}")
    return cfg, params


def _np_cast(a, dtype: str) -> np.ndarray:
    """Host-only dtype cast (ml_dtypes carries bf16 in numpy — no device
    round-trip for multi-GB checkpoints)."""
    import ml_dtypes

    a = _to_np(a)
    if a.dtype == np.dtype("V2") or str(a.dtype) == "bfloat16":
        a = a.view(ml_dtypes.bfloat16).astype(np.float32)
    target = {"bfloat16": ml_dtypes.bfloat16, "float32": np.float32, "float16": np.float16}[dtype]
    return a.astype(target)


def dataclass_replace(cfg, **kw):
    return dataclasses.replace(cfg, **kw)
