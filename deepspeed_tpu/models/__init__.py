"""First-class TPU model family (llama/gpt2/mixtral-style decoders).

The reference reaches models through HF + injection policies
(module_inject/containers/); the TPU build ships the architectures natively
as pure-functional JAX with declarative sharding.
"""

from deepspeed_tpu.models.adapters import flax_loss_fn
from deepspeed_tpu.models.hf import config_from_hf, load_hf_llama, load_hf_model
from deepspeed_tpu.models.transformer import (
    PRESETS,
    TransformerConfig,
    decode_step,
    flops_per_token,
    forward,
    get_config,
    init_kv_cache,
    init_params,
    make_loss_fn,
    num_params,
    param_partition_specs,
)

__all__ = [
    "PRESETS",
    "config_from_hf",
    "flax_loss_fn",
    "load_hf_llama",
    "load_hf_model",
    "TransformerConfig",
    "decode_step",
    "flops_per_token",
    "forward",
    "get_config",
    "init_kv_cache",
    "init_params",
    "make_loss_fn",
    "num_params",
    "param_partition_specs",
]
