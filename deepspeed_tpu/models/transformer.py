"""TPU-native decoder-only transformer family (the flagship model).

The reference framework wraps user torch models; the TPU build additionally
ships a first-class model family (the analogue of the model zoo the reference
targets through HF + module_inject containers: llama/gpt2/opt/bloom — see
deepspeed/module_inject/containers/). One config covers:

  * Llama-style: RMSNorm, RoPE, SwiGLU, grouped-query attention
  * GPT-2-style: LayerNorm, learned positions, GELU MLP, tied embeddings

TPU-first design decisions:
  * Layer parameters are STACKED along a leading [n_layers, ...] dim and the
    forward is a single ``lax.scan`` over layers — compile time is flat in
    depth and XLA pipelines the layer loop.
  * All weights live in a flat dict pytree; sharding is declared as a
    parallel pytree of ``PartitionSpec`` (``param_partition_specs``) that
    composes Megatron-style tensor parallelism (``model`` axis) with ZeRO
    (``data`` axis added by runtime/zero/partition.py) — the AutoTP analogue
    (module_inject/auto_tp.py:193) done declaratively.
  * Activations carry ``with_sharding_constraint`` on [batch, seq, hidden]:
    batch over data/expert, seq over sequence (Ulysses), hidden replicated.
  * Attention dispatches to the Pallas flash kernel (ops/attention) on TPU.
  * ``remat``: per-layer ``jax.checkpoint`` with a dots-saveable policy —
    the activation-checkpointing analogue (runtime/activation_checkpointing/
    checkpointing.py:488) without RNG state juggling (jax threads RNG keys).
  * Sequence parallelism: when the mesh's ``sequence`` axis > 1 the attention
    runs under Ulysses all-to-all (parallel/sequence/ulysses.py), scattering
    heads and gathering sequence exactly like the reference
    ``DistributedAttention`` (sequence/layer.py:331).
"""

import dataclasses
import functools
import math
from contextlib import contextmanager
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.ops.attention import attention as attention_op
from deepspeed_tpu.parallel.topology import (
    BATCH_AXES,
    CONTEXT_AXIS,
    MODEL_AXIS,
    SEQUENCE_AXIS,
    constrain,
    get_topology,
)

DTYPES = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}

# attn_sparsity spec kinds → SparsityConfig families (ops/sparse_attention)
_SPARSITY_KINDS = ("dense", "fixed", "bigbird", "bslongformer", "variable")


@functools.lru_cache(maxsize=32)
def _sparsity_schedule(spec, n_heads, seq_len, block, causal):
    """attn_sparsity spec → compacted BlockSchedule. lru-cached so the
    schedule is built once per (spec, seq_len) and reused across every
    trace — a trace-time constant, never recomputed per step. The model's
    causal flag is ANDed in: a bidirectional sparsity family under a
    causal LM must not leak future positions."""
    from deepspeed_tpu.ops.sparse_attention import config as sa_config
    from deepspeed_tpu.ops.sparse_attention import schedule_from_layout

    cls = {
        "dense": sa_config.DenseSparsityConfig,
        "fixed": sa_config.FixedSparsityConfig,
        "bigbird": sa_config.BigBirdSparsityConfig,
        "bslongformer": sa_config.BSLongformerSparsityConfig,
        "variable": sa_config.VariableSparsityConfig,
    }[spec[0]]
    kwargs = dict(spec[1]) if len(spec) > 1 else {}
    cfg = cls(num_heads=n_heads, **kwargs)
    uni = getattr(cfg, "attention", "bidirectional") == "unidirectional"
    return schedule_from_layout(
        cfg.make_layout(seq_len), cfg.block, causal=causal or uni,
        block_q=block or None, block_kv=block or None,
    )


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Architecture config. Defaults give a Llama-style decoder."""

    vocab_size: int = 32000
    hidden_size: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: Optional[int] = None  # None → MHA; < n_heads → GQA; 1 → MQA
    ffn_hidden_size: Optional[int] = None  # None → 4x (gelu) / 8/3x rounded (swiglu)
    max_seq_len: int = 2048
    norm: str = "rmsnorm"  # rmsnorm | rmsnorm_1p (gemma zero-centered) | layernorm
    # swiglu | geglu (gemma) | gelu (tanh) | gelu_exact (erf) | relu |
    # quick_gelu (CLIP: x * sigmoid(1.702 x))
    activation: str = "swiglu"
    position: str = "rope"  # rope | learned | alibi (bloom) | none
    rope_theta: float = 10000.0
    # Scaled RoPE (HF rope_scaling; reference AutoTP serves these checkpoints
    # via the wrapped HF module — module_inject/auto_tp.py:193 — so parity
    # requires native support): canonical hashable form, a sorted tuple of
    # (key, value) pairs with list values as tuples. Build it with
    # ``rope_scaling_from_hf``. Supported rope_type: linear, dynamic, yarn,
    # longrope, llama3. None → plain theta RoPE.
    rope_scaling: Optional[Tuple[Tuple[str, Any], ...]] = None
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    # --- per-arch variations (reference module_inject/containers/ +
    # inference/v2/model_implementations/ breadth) -------------------------
    # decoupled head dim (mistral-nemo / qwen3 style): projections become
    # [h, n_heads*head_dim] with head_dim != h/n_heads
    head_dim_override: Optional[int] = None
    # q/k normalization over head_dim, applied to the head-reshaped
    # projections BEFORE rope. "rmsnorm" (qwen3): one [d] weight per layer
    # shared across heads. "layernorm_per_head" (stablelm-2 qk_layernorm):
    # biasless LayerNorm with PER-HEAD weights ([nh, d] / [nkv, d]).
    # "layernorm" (phi qk_layernorm): one affine LayerNorm ([d] weight +
    # bias) shared across heads.
    qk_norm: bool = False
    qk_norm_kind: str = "rmsnorm"
    attn_qkv_bias: bool = False  # qwen2-style bias on q/k/v projections
    attn_out_bias: bool = False  # phi-style bias on the output projection
    mlp_bias: bool = False  # phi-style bias on MLP projections
    lm_head_bias: bool = False  # phi ships a biased lm_head
    # falcon/phi parallel block: x + attn(norm1(x)) + mlp(norm2(x)) — one
    # residual stream, attention and MLP branches computed from pre-attn
    # state (falcon-7b/phi share one norm: import the same weights into both)
    parallel_block: bool = False
    # phi partial rotary: rope applies to the first rope_frac*head_dim dims
    rope_frac: float = 1.0
    # softmax scale override: None → 1/sqrt(head_dim); gpt_neo uses 1.0
    # (HF GPTNeoSelfAttention never divides the logits)
    attn_scale: Optional[float] = None
    # --- encoder family (bert/distilbert — reference module_inject/
    # containers/{bert,distil_bert}.py serve these through kernel injection;
    # the training transformer kernel, csrc/transformer/, is BERT-shaped) ---
    # False → bidirectional self-attention (encoder)
    attn_causal: bool = True
    # "pre" (GPT/llama: norm before each block) | "post" (BERT: norm AFTER
    # each residual add — x = LN(x + attn(x)); x = LN(x + mlp(x)))
    norm_scheme: str = "pre"
    # > 0: token-type (segment) embeddings added into the stem (BERT);
    # forward takes token_type_ids (defaults to all-zeros)
    type_vocab_size: int = 0
    # BERT has no final norm (the post-LN layers end normalized already)
    final_norm: bool = True
    # masked-LM head: dense[h,h] + activation + LN before the tied decoder
    # (+ per-vocab bias) — BertForMaskedLM's cls.predictions transform
    mlm_head: bool = False
    # sliding-window attention (mistral/starcoder2 sliding_window, gpt_neo
    # local attention): query i sees keys in (i - window, i]. 0 = full
    # causal. Applies to every layer unless attn_layer_pattern says which.
    sliding_window: int = 0
    # per-layer window flags for alternating local/global stacks (gpt_neo
    # attention_types): tuple of n_layers ints, 1 = windowed, 0 = global.
    # None with sliding_window > 0 → all layers windowed.
    attn_layer_pattern: Optional[Tuple[int, ...]] = None
    # gemma scales embeddings by sqrt(hidden_size) after lookup
    embed_scale: bool = False
    # bloom applies a LayerNorm to the embedding output
    # (word_embeddings_layernorm); params carry embed_norm/embed_norm_b
    embed_norm: bool = False
    # layer-projection matmul precision (VERDICT fp8 lever; ops/qmatmul.py):
    # "default" = model dtype; "fp8" = e4m3 tensor-scaled forward operands;
    # "int8" = symmetric int8 forward (native 2x MXU rate on v5e). Backward
    # stays full precision (straight-through vjp). Head/embed stay dense.
    matmul_precision: str = "default"
    dtype: str = "bfloat16"
    remat: bool = True
    # remat policy knob (reference activation_checkpointing config; VERDICT
    # asked for this to be tunable): see remat_policy() for the names
    remat_policy: str = "dots_with_no_batch_dims"
    # Pallas fused head+CE (ops/fused_ce.py): skip materializing [b*s, V]
    # logits. Takes effect on single-device TPU; multi-chip uses the sharded
    # dense head.
    fused_ce: bool = False
    # MoE (0 → dense). When n_experts > 0 the MLP becomes a top-k gated MoE
    # over the `expert` mesh axis (parallel/moe/).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_coef: float = 0.01
    # Residual-MoE (reference moe/layer.py:29 use_residual, arXiv 2201.05596):
    # out = expert_out·coef₀ + dense_mlp(x)·coef₁ with coef = softmax of a
    # learned [h, 2] projection per token
    moe_residual: bool = False
    # qwen2-moe shared expert: a dense expert of this ffn width runs on every
    # token, added as sigmoid(shared_gate(x))·shared_mlp(x) (0 → none)
    moe_shared_expert_dim: int = 0
    # renormalize top-k combine weights over surviving experts (mixtral /
    # qwen2 norm_topk_prob=True); False keeps raw softmax mass (qwen1.5-moe)
    moe_norm_topk_prob: bool = True
    vocab_parallel: bool = True  # shard embedding/lm_head vocab dim on `model`
    # sequence-parallel attention: "ulysses" (all-to-all head scatter) or
    # "ring" (ppermute blockwise — O(s/N) per-device memory, unbounded SP
    # degree; no segment_ids support)
    seq_impl: str = "ulysses"
    # attention backend seam (ops.attention.core dispatch): "auto" picks the
    # flash ring when the mesh's `context` axis is >1, else the platform
    # best; "flash_ring" / "flash_head_sharded" / "flash" / "reference"
    # force a specific path (hard error when shapes/mesh don't support it);
    # "splash" routes through the scheduled block-sparse kernel
    # (ops/sparse_attention/splash_pallas.py) — masked kv blocks are never
    # scheduled, cost scales with mask density not s²
    attention_impl: str = "auto"
    # splash mask family as a hashable spec: (kind, ((kwarg, value), ...))
    # with kind ∈ "fixed" | "bigbird" | "bslongformer" | "variable" |
    # "dense" (the SparsityConfig families). The spec is compiled into a
    # compacted per-q-block schedule at trace time (a Python constant —
    # never rebuilt per step). None with attention_impl="splash" derives
    # the schedule from attn_causal/sliding_window instead.
    attn_sparsity: Optional[Tuple] = None
    # kernel block edge for splash schedules; 0 → the op-layer default
    # (DSTPU_SPLASH_BLOCK env or 512, shrunk to fit the sequence)
    splash_block: int = 0
    # >1: compute the LM loss per sequence tile so [b, s, vocab] logits never
    # materialize (ALST TiledFusedLogitsLoss, ulysses_sp.py:960) — frees
    # ~b*s*vocab bytes of activations at the cost of recomputing the head
    # matmul in backward (~1pp MFU at 32k vocab); enable when memory-bound
    loss_tiles: int = 0
    # quantized collectives seam (comm/quantized.py): "int8" moves the MoE
    # expert-parallel dispatch/combine exchange (and, via the pipe/serving
    # configs that read it, the pipeline activation sends and the serving TP
    # psum) as int8 payloads + fp32 block scales INSIDE the collective;
    # "none" keeps full-width GSPMD collectives (bit-identical to before)
    comm_quant: str = "none"
    # ZeRO-Infinity weight streaming (reference partition_parameters.py
    # remote_device + partitioned_param_coordinator prefetch): params rest in
    # pinned_host; each scan iteration stages ONE layer's weights into HBM
    # (XLA's latency-hiding scheduler overlaps the copy with compute — the
    # reference's prefetch_bucket_size machinery for free), remat re-stages
    # them in backward, and weight grads stream back to host via the staging
    # vjp. HBM then holds one layer + activations, so models far larger than
    # HBM train on one chip. Requires offload_param + a TPU backend; no-op
    # elsewhere.
    weight_stream: bool = False

    def __post_init__(self):
        if self.norm_scheme not in ("pre", "post"):
            raise ValueError(f"norm_scheme={self.norm_scheme!r}: expected 'pre' or 'post'")
        if self.qk_norm_kind not in ("rmsnorm", "layernorm", "layernorm_per_head"):
            raise ValueError(
                f"qk_norm_kind={self.qk_norm_kind!r}: expected 'rmsnorm', "
                "'layernorm' or 'layernorm_per_head'"
            )
        if self.position == "alibi" and (self.sliding_window > 0 or self.attn_scale is not None):
            # the alibi training branch rides the flash kernel's rank-1 bias
            # and takes no window/scale — silently ignoring them would train
            # full-context and then DECODE windowed (train/serve mismatch)
            raise ValueError(
                "alibi attention does not compose with sliding_window or "
                "attn_scale (no supported arch combines them)"
            )
        if self.seq_impl not in ("ulysses", "ring"):
            raise ValueError(
                f"seq_impl={self.seq_impl!r}: expected 'ulysses' or 'ring' "
                "(a typo would silently fall back to the wrong parallelism)"
            )
        if self.attention_impl not in (
            "auto", "flash", "flash_head_sharded", "flash_ring", "reference",
            "splash",
        ):
            raise ValueError(
                f"attention_impl={self.attention_impl!r}: expected 'auto', "
                "'flash', 'flash_head_sharded', 'flash_ring', 'reference' "
                "or 'splash'"
            )
        if self.attn_sparsity is not None:
            if self.attention_impl not in ("auto", "splash"):
                raise ValueError(
                    f"attn_sparsity set with attention_impl="
                    f"{self.attention_impl!r} — the sparsity schedule only "
                    "routes through 'splash' (or 'auto' promotion)"
                )
            kind = self.attn_sparsity[0] if self.attn_sparsity else None
            if kind not in _SPARSITY_KINDS:
                raise ValueError(
                    f"attn_sparsity kind {kind!r}: expected one of "
                    f"{sorted(_SPARSITY_KINDS)}"
                )
            if self.sliding_window > 0:
                raise ValueError(
                    "attn_sparsity and sliding_window are mutually "
                    "exclusive — the sparsity layout replaces the window "
                    "band (silently ignoring the window would train a "
                    "different mask than configured)"
                )
        if self.attention_impl == "splash" or self.attn_sparsity is not None:
            if self.attn_layer_pattern is not None:
                raise ValueError(
                    "splash attention does not compose with "
                    "attn_layer_pattern — the per-layer window flag is a "
                    "traced scalar inside the layer scan, but splash "
                    "schedules are trace-time constants"
                )
            if self.position == "alibi":
                raise ValueError(
                    "splash attention does not compose with alibi (the "
                    "scheduled kernel takes no positional bias)"
                )
        if self.attn_layer_pattern is not None:
            if self.sliding_window <= 0:
                raise ValueError(
                    "attn_layer_pattern set without sliding_window — the "
                    "pattern flags which layers use the window"
                )
            if len(self.attn_layer_pattern) != self.n_layers:
                raise ValueError(
                    f"attn_layer_pattern has {len(self.attn_layer_pattern)} "
                    f"entries for {self.n_layers} layers"
                )
        if self.comm_quant not in ("none", "int8"):
            raise ValueError(
                f"comm_quant={self.comm_quant!r}: expected 'none' or 'int8' "
                "(a typo would silently serve full-width collectives)"
            )
        if self.matmul_precision not in ("default", "fp8", "int8", "int8_tensor"):
            raise ValueError(
                f"matmul_precision={self.matmul_precision!r}: expected "
                "'default', 'fp8', 'int8' (per-token/per-channel scales) or "
                "'int8_tensor' (legacy per-tensor scales)"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        if self.hidden_size % self.n_heads != 0:
            raise ValueError(f"hidden_size {self.hidden_size} not divisible by "
                             f"n_heads {self.n_heads}")
        return self.hidden_size // self.n_heads

    @property
    def ffn_dim(self) -> int:
        if self.ffn_hidden_size:
            return self.ffn_hidden_size
        if self.activation in ("swiglu", "geglu"):
            # llama-style 2/3 * 4h rounded up to a multiple of 256
            d = int(8 * self.hidden_size / 3)
            return ((d + 255) // 256) * 256
        return 4 * self.hidden_size


# Presets roughly tracking the reference's benchmark targets (BASELINE.json).
PRESETS: Dict[str, Dict[str, Any]] = {
    "tiny": dict(vocab_size=512, hidden_size=128, n_layers=2, n_heads=4, max_seq_len=256),
    "gpt2-small": dict(
        vocab_size=50257, hidden_size=768, n_layers=12, n_heads=12, max_seq_len=1024,
        norm="layernorm", activation="gelu", position="learned", tie_embeddings=True,
    ),
    "llama-7b": dict(
        vocab_size=32000, hidden_size=4096, n_layers=32, n_heads=32, max_seq_len=4096,
        ffn_hidden_size=11008,
    ),
    "llama-1b": dict(
        vocab_size=32000, hidden_size=2048, n_layers=16, n_heads=16, n_kv_heads=8,
        max_seq_len=4096, ffn_hidden_size=5632,
    ),
    # the round-3 bench flagship: best measured MFU shape on one v5e chip
    # (55.4% — PERF.md width sweep); d=128 heads, 3:1 GQA, 3x ffn
    "bench-767m": dict(
        vocab_size=32000, hidden_size=2304, n_layers=10, n_heads=18,
        n_kv_heads=6, ffn_hidden_size=6912, max_seq_len=2048,
        remat_policy="flash",
    ),
    "mixtral-tiny": dict(
        vocab_size=1024, hidden_size=256, n_layers=2, n_heads=4, n_kv_heads=2,
        max_seq_len=512, n_experts=4, moe_top_k=2,
    ),
}


def get_config(preset: str = "tiny", **overrides) -> TransformerConfig:
    kw = dict(PRESETS[preset])
    kw.update(overrides)
    return TransformerConfig(**kw)


# ---------------------------------------------------------------------------
# parameter init
# ---------------------------------------------------------------------------
def init_params(config: TransformerConfig, key: jax.Array) -> Dict[str, Any]:
    """Initialize the parameter pytree. Layer weights are stacked on a leading
    [n_layers] dim for the scan-based forward."""
    c = config
    dtype = DTYPES[c.dtype]
    h, d, nh, nkv = c.hidden_size, c.head_dim, c.n_heads, c.kv_heads
    ffn = c.ffn_dim
    L = c.n_layers
    keys = iter(jax.random.split(key, 32))

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * (1.0 / math.sqrt(fan_in))).astype(dtype)

    # rmsnorm_1p's effective scale is (1 + w): identity init is ZEROS there
    norm_one = jnp.zeros if c.norm == "rmsnorm_1p" else jnp.ones
    layers: Dict[str, Any] = {
        "attn_norm": norm_one((L, h), dtype),
        "wq": dense(next(keys), (L, h, nh * d), h),
        "wk": dense(next(keys), (L, h, nkv * d), h),
        "wv": dense(next(keys), (L, h, nkv * d), h),
        "wo": dense(next(keys), (L, nh * d, h), nh * d),
        "mlp_norm": norm_one((L, h), dtype),
    }
    if c.norm == "layernorm":
        layers["attn_norm_b"] = jnp.zeros((L, h), dtype)
        layers["mlp_norm_b"] = jnp.zeros((L, h), dtype)
    if c.attn_qkv_bias:
        layers["wq_b"] = jnp.zeros((L, nh * d), dtype)
        layers["wk_b"] = jnp.zeros((L, nkv * d), dtype)
        layers["wv_b"] = jnp.zeros((L, nkv * d), dtype)
    if c.qk_norm:
        if c.qk_norm_kind == "layernorm_per_head":
            layers["q_norm"] = jnp.ones((L, nh, d), dtype)
            layers["k_norm"] = jnp.ones((L, nkv, d), dtype)
        else:
            layers["q_norm"] = jnp.ones((L, d), dtype)
            layers["k_norm"] = jnp.ones((L, d), dtype)
            if c.qk_norm_kind == "layernorm":
                layers["q_norm_b"] = jnp.zeros((L, d), dtype)
                layers["k_norm_b"] = jnp.zeros((L, d), dtype)
    if c.attn_out_bias:
        layers["wo_b"] = jnp.zeros((L, h), dtype)
    if c.n_experts > 0:
        E = c.n_experts
        layers["router"] = dense(next(keys), (L, h, E), h)
        layers["w_up"] = dense(next(keys), (L, E, h, ffn), h)
        layers["w_down"] = dense(next(keys), (L, E, ffn, h), ffn)
        if c.activation in ("swiglu", "geglu"):
            layers["w_gate"] = dense(next(keys), (L, E, h, ffn), h)
        if c.moe_residual:
            # dense residual expert + 2-way mixing coefficient (layer.py:47)
            layers["res_up"] = dense(next(keys), (L, h, ffn), h)
            layers["res_down"] = dense(next(keys), (L, ffn, h), ffn)
            if c.activation in ("swiglu", "geglu"):
                layers["res_gate"] = dense(next(keys), (L, h, ffn), h)
            layers["res_coef"] = dense(next(keys), (L, h, 2), h)
        if c.moe_shared_expert_dim > 0:
            sd = c.moe_shared_expert_dim
            layers["shared_up"] = dense(next(keys), (L, h, sd), h)
            layers["shared_down"] = dense(next(keys), (L, sd, h), sd)
            if c.activation in ("swiglu", "geglu"):
                layers["shared_gate"] = dense(next(keys), (L, h, sd), h)
            layers["shared_gate_proj"] = dense(next(keys), (L, h, 1), h)
    else:
        layers["w_up"] = dense(next(keys), (L, h, ffn), h)
        layers["w_down"] = dense(next(keys), (L, ffn, h), ffn)
        if c.activation in ("swiglu", "geglu"):
            layers["w_gate"] = dense(next(keys), (L, h, ffn), h)
    if c.mlp_bias and c.n_experts == 0:
        layers["w_up_b"] = jnp.zeros((L, ffn), dtype)
        layers["w_down_b"] = jnp.zeros((L, h), dtype)
        if c.activation in ("swiglu", "geglu"):
            layers["w_gate_b"] = jnp.zeros((L, ffn), dtype)

    params: Dict[str, Any] = {
        "embed": (jax.random.normal(next(keys), (c.vocab_size, h), jnp.float32) * 0.02).astype(dtype),
        "layers": layers,
    }
    if c.final_norm:
        params["final_norm"] = norm_one((h,), dtype)
        if c.norm == "layernorm":
            params["final_norm_b"] = jnp.zeros((h,), dtype)
    if c.position == "learned":
        params["pos_embed"] = (
            jax.random.normal(next(keys), (c.max_seq_len, h), jnp.float32) * 0.02
        ).astype(dtype)
    if c.type_vocab_size > 0:
        params["type_embed"] = (
            jax.random.normal(next(keys), (c.type_vocab_size, h), jnp.float32) * 0.02
        ).astype(dtype)
    if c.embed_norm:
        params["embed_norm"] = jnp.ones((h,), dtype)
        params["embed_norm_b"] = jnp.zeros((h,), dtype)
    if c.mlm_head:
        params["mlm_dense"] = dense(next(keys), (h, h), h)
        params["mlm_dense_b"] = jnp.zeros((h,), dtype)
        params["mlm_norm"] = jnp.ones((h,), dtype)
        params["mlm_norm_b"] = jnp.zeros((h,), dtype)
        params["mlm_bias"] = jnp.zeros((c.vocab_size,), dtype)
    if not c.tie_embeddings:
        params["lm_head"] = dense(next(keys), (h, c.vocab_size), h)
        if c.lm_head_bias:
            params["lm_head_b"] = jnp.zeros((c.vocab_size,), dtype)
    return params


def param_partition_specs(config: TransformerConfig) -> Dict[str, Any]:
    """Tensor-parallel PartitionSpecs (the declarative AutoTP): Megatron
    column/row sharding over the ``model`` axis. Leading layer-stack dim is
    never sharded. ZeRO later adds the ``data`` axis on free dims
    (runtime/zero/partition.py choose_zero_spec)."""
    c = config
    m = MODEL_AXIS
    layers: Dict[str, Any] = {
        "attn_norm": P(None, None),
        "wq": P(None, None, m),  # column-parallel: shard heads
        "wk": P(None, None, m),
        "wv": P(None, None, m),
        "wo": P(None, m, None),  # row-parallel
        "mlp_norm": P(None, None),
    }
    if c.norm == "layernorm":
        layers["attn_norm_b"] = P(None, None)
        layers["mlp_norm_b"] = P(None, None)
    if c.attn_qkv_bias:
        # column-parallel biases shard with the output dim
        layers["wq_b"] = P(None, m)
        layers["wk_b"] = P(None, m)
        layers["wv_b"] = P(None, m)
    if c.qk_norm:
        if c.qk_norm_kind == "layernorm_per_head":
            # per-head weights shard with the heads (column-parallel q/k)
            layers["q_norm"] = P(None, m, None)
            layers["k_norm"] = P(None, m, None)
        else:
            # head-count-free [d] weights: replicated
            layers["q_norm"] = P(None, None)
            layers["k_norm"] = P(None, None)
            if c.qk_norm_kind == "layernorm":
                layers["q_norm_b"] = P(None, None)
                layers["k_norm_b"] = P(None, None)
    if c.attn_out_bias:
        layers["wo_b"] = P(None, None)  # row-parallel bias: replicated
    if c.n_experts > 0:
        from deepspeed_tpu.parallel.topology import EXPERT_AXIS

        e = EXPERT_AXIS
        layers["router"] = P(None, None, None)
        layers["w_up"] = P(None, e, None, m)
        layers["w_down"] = P(None, e, m, None)
        if c.activation in ("swiglu", "geglu"):
            layers["w_gate"] = P(None, e, None, m)
        if c.moe_residual:
            layers["res_up"] = P(None, None, m)
            layers["res_down"] = P(None, m, None)
            if c.activation in ("swiglu", "geglu"):
                layers["res_gate"] = P(None, None, m)
            layers["res_coef"] = P(None, None, None)
        if c.moe_shared_expert_dim > 0:
            layers["shared_up"] = P(None, None, m)
            layers["shared_down"] = P(None, m, None)
            if c.activation in ("swiglu", "geglu"):
                layers["shared_gate"] = P(None, None, m)
            layers["shared_gate_proj"] = P(None, None, None)
    else:
        layers["w_up"] = P(None, None, m)
        layers["w_down"] = P(None, m, None)
        if c.activation in ("swiglu", "geglu"):
            layers["w_gate"] = P(None, None, m)
    if c.mlp_bias and c.n_experts == 0:
        layers["w_up_b"] = P(None, m)
        layers["w_down_b"] = P(None, None)
        if c.activation in ("swiglu", "geglu"):
            layers["w_gate_b"] = P(None, m)

    vocab_spec = P(m, None) if c.vocab_parallel else P(None, None)
    specs: Dict[str, Any] = {
        "embed": vocab_spec,
        "layers": layers,
    }
    if c.final_norm:
        specs["final_norm"] = P(None)
        if c.norm == "layernorm":
            specs["final_norm_b"] = P(None)
    if c.position == "learned":
        specs["pos_embed"] = P(None, None)
    if c.type_vocab_size > 0:
        specs["type_embed"] = P(None, None)
    if c.embed_norm:
        specs["embed_norm"] = P(None)
        specs["embed_norm_b"] = P(None)
    if c.mlm_head:
        specs["mlm_dense"] = P(None, None)
        specs["mlm_dense_b"] = P(None)
        specs["mlm_norm"] = P(None)
        specs["mlm_norm_b"] = P(None)
        specs["mlm_bias"] = P(m) if c.vocab_parallel else P(None)
    if not c.tie_embeddings:
        specs["lm_head"] = P(None, m) if c.vocab_parallel else P(None, None)
        if c.lm_head_bias:
            specs["lm_head_b"] = P(m) if c.vocab_parallel else P(None)
    return specs


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def remat_policy(name: str):
    """Map a config name to a jax.checkpoint policy. Memory/recompute trade,
    cheapest-memory first: nothing < dots_with_no_batch_dims < dots <
    everything (no recompute; remat becomes a no-op barrier)."""
    policies = {
        "nothing": jax.checkpoint_policies.nothing_saveable,
        # "flash": save ONLY the attention output + LSE (tagged in
        # ops.attention.flash_pallas._flash_fwd) — backward recomputes the
        # cheap elementwise work but never re-runs the flash forward kernel.
        # Costs b·h·s·(d·2+4) bytes/layer (~37 MB at the bench config).
        "flash": jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse"
        ),
        # "flash_qkv" additionally saves the rope'd q/k/v feeding the kernel,
        # so the backward skips the qkv projections + rope recompute too
        # (+74 MB/layer at the bench config on top of "flash").
        "flash_qkv": jax.checkpoint_policies.save_only_these_names(
            "flash_out", "flash_lse", "flash_qkv"
        ),
        "dots_with_no_batch_dims": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        "dots": jax.checkpoint_policies.dots_saveable,
        "everything": jax.checkpoint_policies.everything_saveable,
    }
    if name not in policies:
        raise ValueError(f"remat_policy must be one of {sorted(policies)}, got {name!r}")
    return policies[name]


# ---------------------------------------------------------------------------
# weight streaming (ZeRO-Infinity tier)
# ---------------------------------------------------------------------------
@jax.custom_vjp
def _stage_to_device(w):
    """pinned_host → HBM copy whose cotangent flows back to host, so weight
    gradients of streamed layers accumulate in host memory, never HBM."""
    return jax.device_put(w, jax.memory.Space.Device)


def _stage_fwd(w):
    return _stage_to_device(w), None


def _stage_bwd(_, g):
    import os

    if os.environ.get("DSTPU_STREAM_GRADS_DEVICE", "0") == "1":
        # debug/bisect knob: leave weight grads in HBM (needs grads to fit)
        return (g,)
    return (jax.device_put(g, jax.memory.Space.Host),)


_stage_to_device.defvjp(_stage_fwd, _stage_bwd)


def _stream_active(c: TransformerConfig) -> bool:
    return c.weight_stream and jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# bucketed parameter prefetch (ZeRO-3 comm/compute overlap)
# ---------------------------------------------------------------------------
# Scan-chunk width for ``forward_hidden``: with chunk B > 1 the layer scan
# runs over L/B chunks of B layers each, the inner B layers unrolled in the
# scan body. Layer b+1's parameter all-gather (ZeRO-3 GSPMD) or
# pinned_host→HBM stage (weight_stream) is data-independent of layer b's
# output, so inside ONE body the latency-hiding scheduler overlaps
# collective(b+1) with compute(b) — impossible across sequential scan
# iterations, where iteration i+1's HLO only exists after iteration i
# completes. B=2 is the two-slot double buffer; the engine sizes B from
# ``stage3_prefetch_bucket_size`` (runtime/zero/overlap.py overlap_chunk).
# Set via the ``overlap_scan`` context manager around TRACING (the engine
# wraps its loss calls); read once at trace time, so compiled steps keep the
# chunking they were traced with.
_OVERLAP_SCAN_CHUNK = 1


@contextmanager
def overlap_scan(chunk_layers: int):
    """Trace-scoped layer-scan chunking for comm/compute overlap."""
    global _OVERLAP_SCAN_CHUNK
    prev = _OVERLAP_SCAN_CHUNK
    _OVERLAP_SCAN_CHUNK = max(1, int(chunk_layers))
    try:
        yield
    finally:
        _OVERLAP_SCAN_CHUNK = prev


def _maybe_stage(w):
    """Stage only leaves that actually live in host memory (the engine keeps
    small leaves — norm vectors, biases — device-resident: their [1, h] scan
    slices violate libtpu's >=8-sublane host-DUS requirement, and at a few
    hundred KB they cost nothing in HBM)."""
    try:
        space = jax.typeof(w).memory_space
    except Exception:
        return _stage_to_device(w)
    return _stage_to_device(w) if space == jax.memory.Space.Host else w


def _stage_tree(tree):
    return jax.tree.map(_maybe_stage, tree)


def _norm(x, w, b, kind, eps):
    """Delegates to the ops layer (single definition; Pallas on TPU)."""
    from deepspeed_tpu.ops.normalization import fused_layer_norm, rms_norm

    if kind == "rmsnorm":
        y = rms_norm(x, w, eps)
        return y + b if b is not None else y
    if kind == "rmsnorm_1p":
        # gemma zero-centered weight: y = rms(x) * (1 + w), with the add in
        # fp32 (HF casts to float for it); the kernel accepts an fp32 weight
        y = rms_norm(x, 1.0 + w.astype(jnp.float32), eps)
        return y + b if b is not None else y
    return fused_layer_norm(x, w, b if b is not None else jnp.zeros_like(w), eps)


def rope_scaling_from_hf(scaling, original_max_position_embeddings=None):
    """HF ``rope_scaling`` dict → the canonical hashable config form.

    Returns None for absent/default scaling. ``original_max_position_
    embeddings`` is the TOP-LEVEL HF config field (phi3 longrope keeps the
    pretraining length there, not in the dict — modeling_rope_utils reads
    ``config.original_max_position_embeddings``); when given it is folded
    into the canonical dict so one structure carries all parameters.
    """
    if not scaling:
        return None
    if not isinstance(scaling, dict):
        raise ValueError(f"unsupported rope_scaling={scaling!r} (expected a dict)")
    kind = scaling.get("rope_type", scaling.get("type", "default"))
    if kind == "default":
        return None
    if kind not in ("linear", "dynamic", "yarn", "longrope", "llama3"):
        raise ValueError(
            f"unsupported rope_scaling type {kind!r}; supported: "
            "linear, dynamic, yarn, longrope, llama3"
        )
    out = {"rope_type": kind}
    for k, v in scaling.items():
        if k in ("rope_type", "type"):
            continue
        out[k] = tuple(float(x) for x in v) if isinstance(v, (list, tuple)) else v
    if kind == "longrope" and original_max_position_embeddings:
        # the top-level field wins (HF ignores any in-dict copy here)
        out["original_max_position_embeddings"] = int(original_max_position_embeddings)
    return tuple(sorted(out.items()))


def rope_params(c: "TransformerConfig", rot: int, seq_len: Optional[Any] = None):
    """Inverse frequencies [rot//2] + cos/sin attention factor.

    Faithful to HF ``modeling_rope_utils`` so scaled-RoPE checkpoints
    (llama-3.x, yarn, longrope/phi3, linear, dynamic-NTK) produce identical
    logits. ``seq_len`` plays HF's dynamic ``max(position_ids)+1`` role
    (longrope long/short-factor switch, dynamic-NTK growth) and may be a
    TRACED scalar — decode paths pass the live cache length, so the factor
    choice tracks the actual sequence, not the cache capacity. Seq-dependent
    kinds return a jnp inv_freq; the rest return static numpy (one HF
    divergence, deliberate: HF's dynamic-NTK ratchets to the longest length
    seen between resets, ours is per-call — identical on monotonic decode).
    """
    theta = float(c.rope_theta)
    dims = np.arange(0, rot, 2, dtype=np.float32) / rot
    inv_freq = 1.0 / (theta**dims)
    sc = dict(c.rope_scaling) if c.rope_scaling else None
    if not sc:
        return inv_freq.astype(np.float32), 1.0
    kind = sc["rope_type"]
    factor = float(sc.get("factor", 1.0))
    if kind == "linear":
        return (inv_freq / factor).astype(np.float32), 1.0
    if kind == "dynamic":
        maxp = c.max_seq_len
        seq = jnp.maximum(jnp.asarray(seq_len if seq_len is not None else maxp, jnp.float32), maxp)
        base = theta * ((factor * seq / maxp) - (factor - 1)) ** (rot / (rot - 2))
        return 1.0 / (base ** jnp.asarray(dims)), 1.0
    if kind == "llama3":
        old_len = float(sc["original_max_position_embeddings"])
        low_wl = old_len / float(sc["low_freq_factor"])
        high_wl = old_len / float(sc["high_freq_factor"])
        wavelen = 2 * math.pi / inv_freq
        scaled = np.where(wavelen > low_wl, inv_freq / factor, inv_freq)
        smooth = (old_len / wavelen - float(sc["low_freq_factor"])) / (
            float(sc["high_freq_factor"]) - float(sc["low_freq_factor"])
        )
        mid = (1 - smooth) * scaled / factor + smooth * scaled
        is_mid = (wavelen >= high_wl) & (wavelen <= low_wl)
        return np.where(is_mid, mid, scaled).astype(np.float32), 1.0
    if kind == "yarn":
        old_len = float(sc.get("original_max_position_embeddings") or c.max_seq_len)
        attn = sc.get("attention_factor")
        mscale, mscale_all = sc.get("mscale"), sc.get("mscale_all_dim")

        def get_mscale(scale, m=1.0):
            return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

        if attn is None:
            attn = (
                get_mscale(factor, mscale) / get_mscale(factor, mscale_all)
                if mscale and mscale_all
                else get_mscale(factor)
            )
        beta_fast = float(sc.get("beta_fast") or 32)
        beta_slow = float(sc.get("beta_slow") or 1)

        def corr_dim(n_rot):
            return rot * math.log(old_len / (n_rot * 2 * math.pi)) / (2 * math.log(theta))

        low, high = corr_dim(beta_fast), corr_dim(beta_slow)
        if sc.get("truncate", True):
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, rot - 1)
        if low == high:
            high += 0.001
        ramp = np.clip((np.arange(rot // 2, dtype=np.float32) - low) / (high - low), 0, 1)
        extrap = 1.0 - ramp  # 1 → keep base freq, 0 → interpolate by factor
        return (
            (inv_freq / factor * (1 - extrap) + inv_freq * extrap).astype(np.float32),
            float(attn),
        )
    # longrope: per-dim factor lists, chosen by seq length vs pretrain length
    old_len = int(sc.get("original_max_position_embeddings") or c.max_seq_len)
    if factor == 1.0 and sc.get("original_max_position_embeddings"):
        factor = c.max_seq_len / old_len
    attn = sc.get("attention_factor")
    if attn is None:
        attn = 1.0 if factor <= 1.0 else math.sqrt(1 + math.log(factor) / math.log(old_len))
    short = 1.0 / (np.asarray(sc["short_factor"], np.float32) * theta**dims)
    long = 1.0 / (np.asarray(sc["long_factor"], np.float32) * theta**dims)
    if seq_len is None or isinstance(seq_len, (int, float)):
        return (long if (seq_len or 0) > old_len else short).astype(np.float32), float(attn)
    return jnp.where(seq_len > old_len, jnp.asarray(long), jnp.asarray(short)), float(attn)


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Per-head ALiBi slopes (HF ``build_alibi_tensor`` formula, incl. the
    non-power-of-2 interpolation). Returns fp32 [n_heads]."""
    closest = 2 ** math.floor(math.log2(n_heads))
    base = 2.0 ** (-(2.0 ** -(math.log2(closest) - 3)))
    slopes = base ** np.arange(1, closest + 1, dtype=np.float32)
    if closest != n_heads:
        extra_base = 2.0 ** (-(2.0 ** -(math.log2(2 * closest) - 3)))
        extra = extra_base ** np.arange(1, 2 * (n_heads - closest) + 1, 2, dtype=np.float32)
        slopes = np.concatenate([slopes, extra])
    return slopes.astype(np.float32)


def _alibi_bias(c: TransformerConfig, key_positions: jax.Array) -> jax.Array:
    """ALiBi attention bias ``slope_h * key_position`` → [1|b, nh, 1, sk].

    HF bloom biases by the ABSOLUTE key position (cumsum(mask)-1 == arange);
    with a causal mask this equals the relative form up to a per-row constant
    the softmax cancels — matching HF exactly keeps logits bit-comparable."""
    slopes = jnp.asarray(alibi_slopes(c.n_heads))
    if key_positions.ndim == 1:
        key_positions = key_positions[None]
    return slopes[None, :, None, None] * key_positions[:, None, None, :].astype(jnp.float32)


def _rope(
    x: jax.Array,
    positions: jax.Array,
    c: "TransformerConfig",
    seq_len: Optional[Any] = None,
) -> jax.Array:
    """Rotary embedding on [b, h, s, d] given positions [b, s] or [s].

    rope_frac < 1 (phi partial rotary, HF partial_rotary_factor): only the
    first ``frac*d`` dims rotate; the tail passes through unrotated.
    Scaled RoPE (config.rope_scaling) adjusts the frequencies and cos/sin
    magnitude per ``rope_params``; ``seq_len`` (static or traced) feeds its
    longrope/dynamic-NTK length dependence."""
    d = x.shape[-1]
    frac = c.rope_frac
    rot = d if frac >= 1.0 else (int(d * frac) // 2) * 2
    tail = None
    if rot < d:
        x, tail = x[..., :rot], x[..., rot:]
    inv_freq, attn_factor = rope_params(c, rot, seq_len)
    freqs = jnp.asarray(inv_freq)
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, rot/2]
    # attn_factor scales cos/sin directly (HF convention: yarn/longrope
    # "attention_scaling" multiplies the embedding, hence scores by factor²)
    cos = jnp.cos(angles)[:, None] * attn_factor  # [b, 1, s, rot/2]
    sin = jnp.sin(angles)[:, None] * attn_factor
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    out = out.astype(tail.dtype if tail is not None else x.dtype)
    return out if tail is None else jnp.concatenate([out, tail], axis=-1)


def _scale_embed(x, c: TransformerConfig, dtype):
    """gemma sqrt(h) embedding normalizer — HF rounds it to the model dtype
    BEFORE the multiply, so match that exactly (one definition for every
    embed-lookup site)."""
    if not c.embed_scale:
        return x
    return x * jnp.asarray(math.sqrt(c.hidden_size), dtype)


def _act_constraint(x, seq_sharded=True):
    """Sharding constraint for [b, s, h] activations. The sequence dim
    shards over ``context`` (ring — every layer op outside attention is
    pointwise over s, so per-device activations stay O(s/N) end to end)
    and/or ``sequence`` (Ulysses)."""
    topo = get_topology()
    axes = []
    if seq_sharded and topo.context_parallel_size > 1:
        axes.append(CONTEXT_AXIS)
    if seq_sharded and topo.sequence_parallel_size > 1:
        axes.append(SEQUENCE_AXIS)
    seq = tuple(axes) if len(axes) > 1 else (axes[0] if axes else None)
    return constrain(x, BATCH_AXES, seq, None)


def _proj(c: TransformerConfig, x, w):
    """Layer projection honoring matmul_precision (quantized forward,
    full-precision backward — ops/qmatmul.py)."""
    if c.matmul_precision == "default":
        return x @ w
    from deepspeed_tpu.ops.qmatmul import qmatmul

    return qmatmul(x, w, c.matmul_precision)


def qk_norm_apply(c: TransformerConfig, x, w, head_axis: int, b=None):
    """THE q/k-norm application, shared by the training/decode attention
    block and both v2 paged layer bodies. x: [..., d] with a head axis at
    ``head_axis``; w: [d] (qwen3 rmsnorm / phi affine layernorm, shared
    across heads) or [n_heads, d] (stablelm-2 biasless per-head LayerNorm);
    ``b``: [d] bias for the phi form."""
    if c.qk_norm_kind == "rmsnorm":
        from deepspeed_tpu.ops.normalization.fused_norm import rms_norm_reference

        return rms_norm_reference(x, w, c.norm_eps)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + c.norm_eps)
    if c.qk_norm_kind == "layernorm":  # shared affine (phi)
        y = y * w.astype(jnp.float32)
        if b is not None:
            y = y + b.astype(jnp.float32)
        return y.astype(x.dtype)
    shape = [1] * x.ndim
    shape[head_axis] = w.shape[0]
    shape[-1] = w.shape[1]
    return (y * w.astype(jnp.float32).reshape(shape)).astype(x.dtype)


def _window_bias(c: TransformerConfig, q_glob, k_pos, local_flag):
    """[sq, sk] fp32 additive bias masking keys ≥ sliding_window behind the
    query (band convention shared via ops.attention.core.window_too_far).
    ``local_flag`` (traced 0/1 scalar from attn_layer_pattern, or None)
    switches the window off for global layers inside the layer scan — the
    scan stays uniform while layers alternate (gpt_neo)."""
    from deepspeed_tpu.ops.attention.core import window_too_far

    far = window_too_far(q_glob[:, None], k_pos[None, :], c.sliding_window, local_flag)
    return jnp.where(far, jnp.float32(-1e30), jnp.float32(0.0))


def _attention_block(c: TransformerConfig, lp, x, positions, segment_ids, kv_cache=None,
                     local_flag=None):
    """Self-attention for one layer. x: [b, s, h]."""
    b, s, h = x.shape
    nh, nkv, d = c.n_heads, c.kv_heads, c.head_dim
    q = _proj(c, x, lp["wq"])
    k = _proj(c, x, lp["wk"])
    v = _proj(c, x, lp["wv"])
    if c.attn_qkv_bias:
        q, k, v = q + lp["wq_b"], k + lp["wk_b"], v + lp["wv_b"]
    q = q.reshape(b, s, nh, d).transpose(0, 2, 1, 3)
    k = k.reshape(b, s, nkv, d).transpose(0, 2, 1, 3)
    v = v.reshape(b, s, nkv, d).transpose(0, 2, 1, 3)
    if c.qk_norm:
        # qwen3 rmsnorm / phi affine layernorm / stablelm-2 per-head, pre-rope
        q = qk_norm_apply(c, q, lp["q_norm"], head_axis=1, b=lp.get("q_norm_b"))
        k = qk_norm_apply(c, k, lp["k_norm"], head_axis=1, b=lp.get("k_norm_b"))
    if c.position == "rope":
        # seq len: the LIVE sequence length (HF's max(position_ids)+1) — in
        # decode that is cache fill + this block, traced; else the static s
        seq_len = kv_cache[2] + s if kv_cache is not None else s
        q = _rope(q, positions, c, seq_len)
        k = _rope(k, positions, c, seq_len)

    new_cache = None
    if kv_cache is not None:
        # decode: append to cache along seq
        ck, cv, clen = kv_cache  # [b, nkv, S, d], [b, nkv, S, d], scalar
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k, clen, axis=2)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v, clen, axis=2)
        k, v = ck, cv
        new_cache = (ck, cv, clen + s)
        S = ck.shape[2]
        # causal within the new block AND bounded by the filled cache: query i
        # (global position clen+i) sees keys at positions <= clen+i only.
        q_glob = clen + jnp.arange(s)  # [s]
        kpos = jnp.arange(S)  # [S]
        mask_bias = jnp.where(kpos[None, :] <= q_glob[:, None], 0.0, -1e30).astype(jnp.float32)
        if c.sliding_window > 0:
            mask_bias = mask_bias + _window_bias(c, q_glob, kpos, local_flag)
        bias = mask_bias[None, None]
        if c.position == "alibi":
            bias = bias + _alibi_bias(c, kpos)
        out = attention_op(q, k, v, causal=False, bias=bias, scale=c.attn_scale)
    else:
        topo = get_topology()
        impl = c.attention_impl
        if impl == "auto" and topo.context_parallel_size > 1:
            impl = "flash_ring"
        if impl == "flash_ring":
            # context parallelism: the ring shards the sequence dim itself
            # (O(s/N) per-device activations); dispatch through the
            # ops.attention seam so sharding constraints are pinned there
            if topo.sequence_parallel_size > 1:
                raise NotImplementedError(
                    "ring context parallelism combined with sequence "
                    "parallelism (Ulysses within a context shard) is not "
                    "wired in the model attention block yet"
                )
            if not c.attn_causal:
                raise NotImplementedError(
                    "ring context parallelism is causal-only (the ring "
                    "schedule streams the causal triangle)"
                )
            if c.sliding_window:
                raise NotImplementedError(
                    "sliding_window under ring context parallelism is not "
                    "supported (band masks are global-position)"
                )
            out = attention_op(
                q, k, v, causal=True, segment_ids=segment_ids,
                scale=c.attn_scale, impl="flash_ring",
                alibi_slopes=(jnp.asarray(alibi_slopes(nh))
                              if c.position == "alibi" else None),
            )
        elif topo.sequence_parallel_size > 1:
            if c.position == "alibi":
                raise NotImplementedError(
                    "alibi attention under sequence parallelism is not supported "
                    "(the ring/ulysses kernels take no bias)"
                )
            if not c.attn_causal:
                raise NotImplementedError(
                    "bidirectional attention under sequence parallelism is "
                    "not supported (the ring/ulysses paths are causal)"
                )
            if c.seq_impl == "ring":
                from deepspeed_tpu.parallel.sequence import ring_attention

                # window masks over GLOBAL positions inside the ring loop
                out = ring_attention(
                    q, k, v, causal=True, segment_ids=segment_ids,
                    scale=c.attn_scale, window=c.sliding_window,
                    window_flag=local_flag,
                )
            else:
                from deepspeed_tpu.parallel.sequence import ulysses_attention

                out = ulysses_attention(
                    q, k, v, causal=True, segment_ids=segment_ids,
                    scale=c.attn_scale, window=c.sliding_window,
                    window_flag=local_flag,
                )
        elif c.position == "alibi":
            # rank-1 form rides the flash kernel (slope * key_position added
            # in-kernel) — the dense [s, s] bias never materializes
            out = attention_op(
                q, k, v, causal=True, segment_ids=segment_ids,
                alibi_slopes=jnp.asarray(alibi_slopes(nh)),
                alibi_positions=positions, impl=impl,
            )
        else:
            # sliding windows ride the flash kernel (in-kernel band mask;
            # static windows — no attn_layer_pattern — additionally prune
            # out-of-band kv blocks, O(s·window) compute); window distance is
            # the token index, packing composes via segment_ids.
            # splash: the mask compiles into a compacted block schedule at
            # trace time (lru-cached Python constant); masked blocks never
            # enter the kernel grid. attn_sparsity promotes "auto" too.
            schedule = None
            if c.attn_sparsity is not None:
                schedule = _sparsity_schedule(
                    c.attn_sparsity, nh, s, c.splash_block, c.attn_causal)
            elif impl == "splash" and c.splash_block:
                from deepspeed_tpu.ops.attention.core import _derived_splash_schedule

                schedule = _derived_splash_schedule(
                    s, s, c.attn_causal, c.sliding_window, c.splash_block)
            out = attention_op(
                q, k, v, causal=c.attn_causal, segment_ids=segment_ids,
                scale=c.attn_scale, window=c.sliding_window,
                window_flag=local_flag, impl=impl, schedule=schedule,
            )
    out = out.transpose(0, 2, 1, 3).reshape(b, s, nh * d)
    out = _proj(c, out, lp["wo"])
    if c.attn_out_bias:
        out = out + lp["wo_b"]
    return out, new_cache


def _mlp_block(c: TransformerConfig, lp, x):
    if c.n_experts > 0:
        from deepspeed_tpu.parallel.moe import moe_mlp

        return moe_mlp(c, lp, x)
    up = _proj(c, x, lp["w_up"])
    if c.mlp_bias:
        up = up + lp["w_up_b"]
    if c.activation in ("swiglu", "geglu"):
        gate = _proj(c, x, lp["w_gate"])
        if c.mlp_bias:
            gate = gate + lp["w_gate_b"]
        act = (jax.nn.gelu(gate) if c.activation == "geglu" else jax.nn.silu(gate)) * up
    elif c.activation == "relu":
        act = jax.nn.relu(up)
    elif c.activation == "quick_gelu":
        act = up * jax.nn.sigmoid(1.702 * up)
    else:
        act = jax.nn.gelu(up, approximate=c.activation != "gelu_exact")
    out = _proj(c, act, lp["w_down"])
    if c.mlp_bias:
        out = out + lp["w_down_b"]
    return out, jnp.float32(0.0)


def _dequant_tree(lp, dtype):
    """Transparent weight-only quantized inference: QuantizedWeight leaves
    (inference/quantization) widen HERE — inside the layer scan body — so
    the transient bf16 copy is one layer, never the model."""
    try:
        from deepspeed_tpu.inference.quantization.quantize import (
            is_quantized_leaf,
            maybe_dequantize,
        )
    except ImportError:  # quantization package optional at import time
        return lp
    if not any(
        is_quantized_leaf(l)
        for l in jax.tree_util.tree_leaves(lp, is_leaf=is_quantized_leaf)
    ):
        return lp
    return jax.tree.map(
        lambda n: maybe_dequantize(n, dtype), lp, is_leaf=is_quantized_leaf
    )


def _layer(c: TransformerConfig, lp, x, positions, segment_ids, local_flag=None):
    lp = _dequant_tree(lp, DTYPES[c.dtype])
    # Autocast: run the layer at the model's configured compute dtype even
    # when the engine hands in wider params (e.g. fp32 masters with no bf16
    # block in the DS config). Without this, f32 weights promote the residual
    # stream and the layer-scan carry dtype flips mid-scan.
    dt = DTYPES[c.dtype]
    lp = jax.tree.map(
        lambda w: w.astype(dt)
        if hasattr(w, "dtype") and jnp.issubdtype(w.dtype, jnp.floating) and w.dtype != dt
        else w,
        lp,
    )
    if c.norm_scheme == "post":
        # BERT: norm AFTER each residual add; attention reads the raw stream
        attn_out, _ = _attention_block(c, lp, x, positions, segment_ids, local_flag=local_flag)
        x = _norm(x + attn_out, lp["attn_norm"], lp.get("attn_norm_b"), c.norm, c.norm_eps)
        x = _act_constraint(x)
        mlp_out, aux_loss = _mlp_block(c, lp, x)
        x = _norm(x + mlp_out, lp["mlp_norm"], lp.get("mlp_norm_b"), c.norm, c.norm_eps)
        return _act_constraint(x), aux_loss
    a = _norm(x, lp["attn_norm"], lp.get("attn_norm_b"), c.norm, c.norm_eps)
    attn_out, _ = _attention_block(c, lp, a, positions, segment_ids, local_flag=local_flag)
    if c.parallel_block:
        # falcon/phi: both branches from the pre-attention state, one residual
        m = _norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"), c.norm, c.norm_eps)
        mlp_out, aux_loss = _mlp_block(c, lp, m)
        x = x + attn_out + mlp_out
        return _act_constraint(x), aux_loss
    x = x + attn_out
    x = _act_constraint(x)
    m = _norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"), c.norm, c.norm_eps)
    mlp_out, aux_loss = _mlp_block(c, lp, m)
    x = x + mlp_out
    x = _act_constraint(x)
    return x, aux_loss


def forward_hidden(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: TransformerConfig,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Body forward: tokens [b, s] → (final-norm'd hidden [b, s, h], aux_loss).

    Layers run under ``lax.scan`` over the stacked layer pytree; with
    ``config.remat`` each layer is rematerialized (dots saveable) so
    activation memory is O(1) in depth.
    """
    c = config
    b, s = tokens.shape
    stream = _stream_active(c)
    if positions is None:
        positions = jnp.arange(s, dtype=jnp.int32)
    embed = _maybe_stage(params["embed"]) if stream else params["embed"]
    x = _scale_embed(embed.astype(DTYPES[c.dtype])[tokens], c, DTYPES[c.dtype])
    if c.position == "learned":
        pe = _maybe_stage(params["pos_embed"]) if stream else params["pos_embed"]
        x = x + pe[positions][None] if positions.ndim == 1 else x + pe[positions]
    if c.type_vocab_size > 0:
        te = _maybe_stage(params["type_embed"]) if stream else params["type_embed"]
        te = te.astype(x.dtype)
        # default token type 0 (HF convention when token_type_ids is omitted)
        x = x + (te[0] if token_type_ids is None else te[token_type_ids])
    if c.embed_norm:
        x = _embed_norm(params, c, x, stream)
    x = _act_constraint(x)

    layer_fn = partial(_layer, c)
    if stream:
        # stage INSIDE the (remat'd) layer body: forward brings one layer's
        # weights to HBM per scan step, backward re-stages them on recompute
        inner_fn = layer_fn
        layer_fn = lambda lp, *a: inner_fn(_stage_tree(lp), *a)  # noqa: E731
    if c.remat:
        layer_fn = jax.checkpoint(layer_fn, policy=remat_policy(c.remat_policy))

    if c.attn_layer_pattern is not None:
        flags = jnp.asarray(c.attn_layer_pattern, jnp.int32)
        xs = (params["layers"], flags)

        def call_layer(xs_i, x):
            lp, flag = xs_i
            return layer_fn(lp, x, positions, segment_ids, flag)
    else:
        xs = params["layers"]

        def call_layer(xs_i, x):
            return layer_fn(xs_i, x, positions, segment_ids)

    n_layer = jax.tree_util.tree_leaves(xs)[0].shape[0]
    chunk = _OVERLAP_SCAN_CHUNK
    if chunk > 1 and n_layer % chunk == 0:
        # bucketed prefetch: scan L/chunk chunks, the inner `chunk` layers
        # unrolled so layer b+1's weight gather/stage (which stays INSIDE
        # the remat'd layer body — hoisting it out would pin every gathered
        # layer as a saved residual) sits in the same scan body as layer
        # b's compute, where the scheduler can overlap them
        xs_c = jax.tree.map(
            lambda a: a.reshape((n_layer // chunk, chunk) + a.shape[1:]), xs
        )

        def scan_body(carry, xs_b):
            x = carry
            auxs = []
            for b_i in range(chunk):
                x, aux = call_layer(jax.tree.map(lambda a: a[b_i], xs_b), x)
                auxs.append(aux)
            return x, jnp.stack(auxs)

        x, aux_losses = jax.lax.scan(scan_body, x, xs_c)
    else:

        def scan_body(carry, xs_i):
            return call_layer(xs_i, carry)

        x, aux_losses = jax.lax.scan(scan_body, x, xs)
    if c.final_norm:
        fn_w = _maybe_stage(params["final_norm"]) if stream else params["final_norm"]
        fn_b = params.get("final_norm_b")
        if stream and fn_b is not None:
            fn_b = _maybe_stage(fn_b)
        x = _norm(x, fn_w, fn_b, c.norm, c.norm_eps)
    return x, jnp.sum(aux_losses)


def _embed_norm(params, c: TransformerConfig, x, stream: bool):
    """bloom word_embeddings_layernorm applied to the embedding output."""
    w = _maybe_stage(params["embed_norm"]) if stream else params["embed_norm"]
    b = _maybe_stage(params["embed_norm_b"]) if stream else params["embed_norm_b"]
    return _norm(x, w, b, "layernorm", c.norm_eps)


def _lm_head_matrix(params, config: TransformerConfig, dtype):
    stream = _stream_active(config)
    if config.tie_embeddings:
        w = params["embed"]
        return (_maybe_stage(w) if stream else w).astype(dtype).T
    w = _dequant_tree(params["lm_head"], dtype)
    return _maybe_stage(w) if stream else w


def _apply_lm_head(params, x, config: TransformerConfig):
    logits = x @ _lm_head_matrix(params, config, x.dtype)
    if config.lm_head_bias and not config.tie_embeddings:
        logits = logits + params["lm_head_b"].astype(logits.dtype)
    return logits


def forward(
    params: Dict[str, Any],
    tokens: jax.Array,
    config: TransformerConfig,
    positions: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    token_type_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Full forward: tokens [b, s] int32 → (logits [b, s, vocab], aux_loss)."""
    x, aux = forward_hidden(params, tokens, config, positions, segment_ids, token_type_ids)
    if config.mlm_head:
        # BertForMaskedLM cls.predictions: transform (dense + act + LN), then
        # the tied decoder with its standalone vocab bias
        c = config
        t = x @ params["mlm_dense"].astype(x.dtype) + params["mlm_dense_b"].astype(x.dtype)
        # the transform uses the config's hidden activation (HF ACT2FN)
        if c.activation == "relu":
            t = jax.nn.relu(t)
        else:
            t = jax.nn.gelu(t, approximate=c.activation != "gelu_exact")
        t = _norm(t, params["mlm_norm"], params["mlm_norm_b"], "layernorm", c.norm_eps)
        return _apply_lm_head(params, t, c) + params["mlm_bias"].astype(x.dtype), aux
    return _apply_lm_head(params, x, config), aux


def decode_step(params, tokens, config, kv_caches, positions):
    """Single decode step with KV caches (inference path).

    tokens: [b, t] new tokens; kv_caches: per-layer list of (k, v, len).
    Returns (logits [b, t, vocab], new_caches). Runs layers as a Python loop
    over unstacked weights (decode graphs are small; scan would force cache
    stacking anyway, which we do — caches are stacked [L, ...]).
    """
    c = config
    if not c.attn_causal:
        raise ValueError(
            "decode_step: bidirectional encoder models (attn_causal=False) "
            "do not autoregressively decode — call forward() instead"
        )
    b, t = tokens.shape
    stream = _stream_active(c)
    embed = _maybe_stage(params["embed"]) if stream else params["embed"]
    x = _scale_embed(embed.astype(DTYPES[c.dtype])[tokens], c, DTYPES[c.dtype])
    if c.position == "learned":
        pe = _maybe_stage(params["pos_embed"]) if stream else params["pos_embed"]
        x = x + pe[positions]
    if c.embed_norm:
        x = _embed_norm(params, c, x, stream)

    def scan_body(x, inputs):
        lp, cache, local_flag = inputs
        if stream:
            lp = _stage_tree(lp)
        lp = _dequant_tree(lp, DTYPES[c.dtype])
        a = _norm(x, lp["attn_norm"], lp.get("attn_norm_b"), c.norm, c.norm_eps)
        attn_out, new_cache = _attention_block(
            c, lp, a, positions, None, kv_cache=cache, local_flag=local_flag
        )
        if c.parallel_block:
            m = _norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"), c.norm, c.norm_eps)
            mlp_out, _ = _mlp_block(c, lp, m)
            return x + attn_out + mlp_out, new_cache
        x = x + attn_out
        m = _norm(x, lp["mlp_norm"], lp.get("mlp_norm_b"), c.norm, c.norm_eps)
        mlp_out, _ = _mlp_block(c, lp, m)
        return x + mlp_out, new_cache

    flags = jnp.asarray(
        c.attn_layer_pattern if c.attn_layer_pattern is not None else [1] * c.n_layers,
        jnp.int32,
    )
    x, new_caches = jax.lax.scan(scan_body, x, (params["layers"], kv_caches, flags))
    fn_w = _maybe_stage(params["final_norm"]) if stream else params["final_norm"]
    fn_b = params.get("final_norm_b")
    if stream and fn_b is not None:
        fn_b = _maybe_stage(fn_b)
    x = _norm(x, fn_w, fn_b, c.norm, c.norm_eps)
    return _apply_lm_head(params, x, c), new_caches


def init_kv_cache(config: TransformerConfig, batch: int, max_len: int):
    """Stacked per-layer KV cache pytree for decode_step."""
    c = config
    dtype = DTYPES[c.dtype]
    shape = (c.n_layers, batch, c.kv_heads, max_len, c.head_dim)
    return (
        jnp.zeros(shape, dtype),
        jnp.zeros(shape, dtype),
        jnp.zeros((c.n_layers,), jnp.int32),
    )


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def split_lm_batch(batch):
    """Normalize a causal-LM batch dict to (inputs, labels, loss_mask,
    positions, segment_ids); labels default to shifted input_ids."""
    tokens = batch["input_ids"]
    labels = batch.get("labels")
    mask = batch.get("loss_mask")
    if labels is None:
        labels = tokens[:, 1:]
        inputs = tokens[:, :-1]
        if mask is not None and mask.shape[1] == tokens.shape[1]:
            mask = mask[:, 1:]  # align with shifted labels
    else:
        inputs = tokens
    return inputs, labels, mask, batch.get("positions"), batch.get("segment_ids")


def embed_tokens(params, tokens, positions, config: TransformerConfig):
    """Embedding (+ learned positions, + bloom's embedding layernorm) — the
    model's stem, shared by the dense and pipelined paths."""
    x = _scale_embed(params["embed"].astype(DTYPES[config.dtype])[tokens], config, DTYPES[config.dtype])
    if config.position == "learned":
        pe = params["pos_embed"][positions]
        x = x + (pe[None] if positions.ndim == 1 else pe)
    if config.embed_norm:
        x = _embed_norm(params, config, x, stream=False)
    return x


def _masked_nll(logits, labels, mask):
    """Shared CE core: fp32 NLL → (sum_loss, count).

    log_softmax(x)[label] = x[label] - logsumexp(x): gathering the label
    logit + an fp32 logsumexp REDUCTION avoids materializing the [n, vocab]
    fp32 log-prob array the naive form writes and re-reads (~3 GB of HBM
    traffic per step at the bench shape; the cast fuses into the reduce)."""
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    ll = picked.astype(jnp.float32) - lse
    return jnp.sum(-ll * mask), jnp.sum(mask)


def nll_loss(logits, labels, mask=None):
    """Masked next-token NLL from full logits."""
    if mask is None:
        mask = jnp.ones(labels.shape, jnp.float32)
    total, count = _masked_nll(logits, labels, mask)
    return total / jnp.maximum(count, 1.0)


def lm_head_loss(params, x, labels, mask, config: TransformerConfig, aux=None):
    """Final norm → logits → masked NLL (+ MoE aux) — the model's head,
    shared by the dense and pipelined paths."""
    c = config
    x = _norm(x, params["final_norm"], params.get("final_norm_b"), c.norm, c.norm_eps)
    logits = _apply_lm_head(params, x, c)
    loss = nll_loss(logits, labels, mask)
    if c.n_experts > 0 and aux is not None:
        loss = loss + c.moe_aux_loss_coef * aux
    return loss


def make_loss_fn(config: TransformerConfig):
    """Causal-LM loss over a batch dict {'input_ids': [b, s] (, 'labels',
    'loss_mask', 'segment_ids', 'positions')}. Next-token prediction; labels
    default to input_ids shifted. Matches the engine's loss_fn(params, batch)
    contract."""

    def loss_fn(params, batch):
        inputs, labels, mask, positions, segment_ids = split_lm_batch(batch)
        # fused/tiled heads feed the bare head matrix to the kernel — a biased
        # lm_head (phi) falls through to the dense path
        if (
            config.fused_ce
            and not config.lm_head_bias
            and not config.mlm_head  # the fused kernel has no MLM transform
            and jax.default_backend() == "tpu"
            and get_topology().world_size == 1
        ):
            # Pallas fused head+CE: logits never materialize in HBM
            # (ops/fused_ce.py). Single-device only: pallas_call is opaque to
            # GSPMD, and the head matmul wants the model-axis sharding on
            # multi-chip meshes.
            from deepspeed_tpu.ops.fused_ce import fused_ce_loss

            x, aux = forward_hidden(params, inputs, config, positions=positions, segment_ids=segment_ids)
            b, s, h = x.shape
            w = _lm_head_matrix(params, config, x.dtype)
            m = mask if mask is not None else jnp.ones(labels.shape, jnp.float32)
            # pad rows to the kernel's tile size: b*s is often 2^k - b (labels
            # shift drops one position), and a degenerate row block would
            # explode the Pallas grid
            n = b * s
            pad = (-n) % 256
            flat_x = x.reshape(n, h)
            flat_l = labels.reshape(-1)
            flat_m = m.reshape(-1)
            if pad:
                flat_x = jnp.concatenate([flat_x, jnp.zeros((pad, h), x.dtype)])
                flat_l = jnp.concatenate([flat_l, jnp.zeros((pad,), flat_l.dtype)])
                flat_m = jnp.concatenate([flat_m, jnp.zeros((pad,), flat_m.dtype)])
            per_row = fused_ce_loss(flat_x, w, flat_l)
            loss = jnp.sum(per_row * flat_m) / jnp.maximum(jnp.sum(flat_m), 1.0)
        elif config.loss_tiles > 1 and not config.lm_head_bias and not config.mlm_head:
            from deepspeed_tpu.parallel.sequence.tiled import tiled_logits_loss

            x, aux = forward_hidden(params, inputs, config, positions=positions, segment_ids=segment_ids)
            loss = tiled_logits_loss(
                _masked_nll,
                x,
                _lm_head_matrix(params, config, x.dtype),
                labels,
                num_tiles=config.loss_tiles,
                mask=mask,
            )
        else:
            logits, aux = forward(params, inputs, config, positions=positions, segment_ids=segment_ids)
            loss = nll_loss(logits, labels, mask)
        return loss + config.moe_aux_loss_coef * aux if config.n_experts > 0 else loss

    # the hybrid engine (train↔generate) recovers the architecture from the
    # loss fn — deepspeed.initialize only ever sees this callable
    loss_fn.model_config = config
    return loss_fn


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))


def flops_per_token(config: TransformerConfig, seq_len: Optional[int] = None) -> float:
    """Approximate training FLOPs per token (6ND rule + attention term)."""
    c = config
    s = seq_len or c.max_seq_len
    # vocab term counts the lm_head matmul once; the input embedding is a
    # gather, not a matmul, so tying does not change matmul FLOPs.
    n_dense = (
        c.hidden_size * (c.n_heads + 2 * c.kv_heads) * c.head_dim  # qkv
        + c.n_heads * c.head_dim * c.hidden_size  # out proj
        + c.hidden_size * c.ffn_dim * (3 if c.activation in ("swiglu", "geglu") else 2)
    ) * c.n_layers + c.vocab_size * c.hidden_size
    attn = 2 * c.n_layers * s * c.hidden_size
    return 6.0 * (n_dense + attn / 2)
