"""deepspeed_tpu: a TPU-native training & inference framework with the
capabilities of DeepSpeed (reference: zhengchenyu/DeepSpeed v0.18.3), rebuilt
idiomatically on JAX/XLA/pjit/Pallas.

Public API mirrors the reference ``deepspeed/__init__.py``:
``initialize`` (:78), ``init_inference`` (:302), ``init_distributed``,
``add_config_arguments`` (:279), ``zero``, ``comm``.
"""

from typing import Any, Callable, Optional, Union

from deepspeed_tpu import _jax_compat  # noqa: F401  — must run before jax users below
from deepspeed_tpu.version import __version__
from deepspeed_tpu import comm
from deepspeed_tpu.runtime import zero
from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.comm.comm import init_distributed
from deepspeed_tpu.parallel.topology import Topology, get_topology, set_topology
from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.utils.logging import log_dist, logger


def initialize(
    args=None,
    model: Optional[Callable] = None,
    optimizer=None,
    model_parameters: Any = None,
    training_data=None,
    lr_scheduler=None,
    distributed_port: int = 29500,
    mpu=None,
    dist_init_required: Optional[bool] = None,
    collate_fn=None,
    config: Union[str, dict, None] = None,
    mesh_param=None,
    config_params=None,
    param_specs=None,
):
    """Create a training engine (reference ``deepspeed.initialize``
    __init__.py:78). Returns ``(engine, optimizer, dataloader, lr_scheduler)``.

    TPU adaptation: ``model`` is a pure loss function
    ``loss_fn(params, batch[, rng]) -> loss | (loss, aux)`` and
    ``model_parameters`` is the params pytree — or a ``zero.Init``/callable
    for deferred construction (params materialize under jit with the ZeRO
    plan's shardings; the full pytree never exists on one host). A flax
    ``nn.Module`` can be adapted via ``deepspeed_tpu.models.flax_loss_fn``. ``mesh_param`` (the
    reference's DeviceMesh knob, __init__.py:163-171) or the config's
    ``mesh`` section sizes the parallelism grid.
    """
    log_dist(f"DeepSpeedTPU info: version={__version__}", ranks=[0])
    if model is None:
        raise ValueError("deepspeed_tpu.initialize: model (loss function) is required")
    if model_parameters is None:
        raise ValueError("deepspeed_tpu.initialize: model_parameters (params pytree) is required")

    config = config if config is not None else config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config") and args.deepspeed_config:
        config = args.deepspeed_config

    # 1. mesh/topology (reference: comm.init_distributed + groups from mpu)
    mesh_cfg = None
    if mesh_param is not None:
        mesh_cfg = (
            {"data": mesh_param[0], "sequence": mesh_param[1]}
            if isinstance(mesh_param, (tuple, list))
            else dict(mesh_param)
        )
    # parse once (with duplicate-key rejection) so mesh extraction and the
    # typed config read the same dict
    if isinstance(config, str):
        import json

        from deepspeed_tpu.runtime.config_utils import dict_raise_error_on_duplicate_keys

        with open(config) as f:
            config = json.load(f, object_pairs_hook=dict_raise_error_on_duplicate_keys)
    raw = config if isinstance(config, dict) else {}

    if mpu is not None and not isinstance(mpu, Topology):
        logger.warning(
            f"mpu of type {type(mpu).__name__} is not a Topology and will be ignored; "
            "pass a deepspeed_tpu.Topology to control the mesh"
        )
        mpu = None
    if mpu is not None:
        # still bootstrap multi-host jax.distributed before adopting the mesh
        init_distributed(distributed_port=distributed_port)
        topo = mpu
        set_topology(topo)
    else:
        mc = dict(raw.get("mesh", {}) or {})
        if mesh_cfg:
            mc.update(mesh_cfg)
        tp = raw.get("tensor_parallel", {}).get("autotp_size", 0) or raw.get("tensor_parallel", {}).get("tp_size", 1)
        if tp and tp > 1 and "model" not in mc:
            mc["model"] = tp
        pp = raw.get("pipeline", {}).get("stages", 1)
        if pp > 1 and "pipe" not in mc:
            mc["pipe"] = pp
        # MiCS/hpZ shard-group axis: factorize data parallelism into
        # (data=groups, zero=shard-group) so ZeRO can partition within a group
        zc = raw.get("zero_optimization", {}) or {}
        mics = int(zc.get("mics_shard_size", -1) or -1)
        hpz = int(zc.get("zero_hpz_partition_size", 1) or 1)
        if mics > 0 and hpz > 1 and mics != hpz:
            raise ValueError(
                f"mics_shard_size={mics} and zero_hpz_partition_size={hpz} conflict: "
                "they would need different shard-group sizes — configure one"
            )
        shard = mics if mics > 0 else hpz
        if shard > 1:
            if "zero" in mc and mc["zero"] != shard:
                raise ValueError(
                    f"mesh zero={mc['zero']} does not match the configured "
                    f"shard-group size {shard}"
                )
            mc["zero"] = shard
            if mc.get("data"):
                if mc["data"] % shard:
                    raise ValueError(
                        f"mesh data={mc['data']} not divisible by shard-group size {shard}"
                    )
                mc["data"] = mc["data"] // shard
        init_distributed(distributed_port=distributed_port, mesh_config=mc or None)
        topo = get_topology()

    # 2. typed config with batch arithmetic against the real dp world
    ds_config = DeepSpeedConfig.load(raw, dp_world_size=topo.dp_world_size)

    # 3. engine
    engine = DeepSpeedEngine(
        loss_fn=model,
        params=model_parameters,
        config=ds_config,
        topology=topo,
        optimizer=optimizer,
        lr_scheduler=lr_scheduler,
        training_data=training_data,
        collate_fn=collate_fn,
        param_specs=param_specs,
    )

    # hybrid engine (reference __init__.py:190): train↔generate on one copy
    hy = raw.get("hybrid_engine", {}) or {}
    if hy.get("enabled"):
        model_config = getattr(model, "model_config", None)
        if model_config is None:
            raise ValueError(
                "hybrid_engine requires a model with a known architecture: use "
                "make_loss_fn(config) (which carries .model_config) or build "
                "DeepSpeedHybridEngine directly with your TransformerConfig"
            )
        from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

        engine = DeepSpeedHybridEngine(engine, model_config, hy)
        return engine, engine.engine.optimizer, engine.engine.training_dataloader, engine.engine.lr_scheduler
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Reference ``init_inference`` (__init__.py:302)."""
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine

    if isinstance(config, dict):
        config = DeepSpeedInferenceConfig.from_dict(config)
    elif config is None:
        config = DeepSpeedInferenceConfig.from_dict(kwargs)
    return InferenceEngine(model, config)


def add_config_arguments(parser):
    """Reference ``add_config_arguments`` (__init__.py:279)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true")
    group.add_argument("--deepspeed_config", default=None, type=str)
    group.add_argument("--deepscale", default=False, action="store_true")
    group.add_argument("--deepscale_config", default=None, type=str)
    return parser


def _add_core_arguments(parser):
    from deepspeed_tpu.runtime.lr_schedules import add_tuning_arguments

    parser = add_config_arguments(parser)
    parser = add_tuning_arguments(parser)
    return parser
