"""AutoTP: policy-free tensor-parallel spec inference for arbitrary pytrees.

TPU-native re-design of the reference AutoTP (``module_inject/auto_tp.py:193``
— module-graph scan classifying Linears into column-parallel vs
all-reduce/row-parallel, then ``ReplaceWithTensorSlicing`` :32). On TPU no
module surgery happens: the result of classification is a *PartitionSpec
pytree* handed to ``initialize(param_specs=...)``; GSPMD does the slicing and
inserts the collectives the reference's ``LinearAllreduce`` layers issue by
hand.

Classification mirrors the reference's name heuristics:
  * row-parallel (input-dim sharded, output psum'd): projections that close
    a parallel block — o_proj/out_proj/wo/down_proj/w2/fc2/dense_4h_to_h...
    (reference ``tp_parser`` collects these as the "allreduce linears")
  * column-parallel (output-dim sharded): every other 2-D weight —
    q/k/v/gate/up/fc1/w1/w3/query_key_value... (reference default)
  * replicated: norms, biases of row-parallel layers, scalars, small leaves
  * embeddings: vocab-dim sharded when divisible (reference
    ``ReplaceWithTensorSlicing`` embedding path)

Weights are assumed ``[in, out]`` (JAX convention). Leaves whose candidate
dim does not divide the axis size stay replicated — same fallback as the
reference's ``require_tp_fused_qkvw`` divisibility guards.
"""

from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.topology import MODEL_AXIS, get_topology

# reference auto_tp.py: the "allreduce linears" — output projections whose
# INPUT dim carries the parallel slices (row parallel)
ROW_PATTERNS = (
    "o_proj", "out_proj", "wo", "down_proj", "w2", "fc2", "dense_4h_to_h",
    "attention/dense", "self_attention/dense", "proj_out", "c_proj",
)
# column-parallel producers (output dim sharded)
COL_PATTERNS = (
    "q_proj", "k_proj", "v_proj", "wq", "wk", "wv", "query", "key", "value",
    "query_key_value", "gate_proj", "up_proj", "w1", "w3", "fc1",
    "dense_h_to_4h", "c_attn", "c_fc", "in_proj",
)
EMBED_PATTERNS = ("embed", "wte", "wpe", "word_embeddings", "lm_head", "embed_tokens")
NORM_PATTERNS = ("norm", "ln_", "layernorm", "layer_norm", "rmsnorm")


from deepspeed_tpu.utils.pytree import path_str as _path_str  # shared renderer


def _matches(name: str, patterns: Sequence[str]) -> bool:
    return any(p in name for p in patterns)


def classify(name: str) -> str:
    """'row' | 'col' | 'embed' | 'replicate' from a parameter path name."""
    if name.endswith("/bias") or name.endswith("/b"):
        # biases follow their kernel's sharding; resolved by the caller
        name = name.rsplit("/", 1)[0] + "/kernel"
    if _matches(name, NORM_PATTERNS):
        return "replicate"
    if _matches(name, ROW_PATTERNS):
        return "row"
    if _matches(name, COL_PATTERNS):
        return "col"
    if _matches(name, EMBED_PATTERNS):
        return "embed"
    return "default"


def _spec_for(kind: str, shape: Tuple[int, ...], tp: int, axis: str, shard_default: bool) -> P:
    nd = len(shape)
    if nd < 1 or tp <= 1:
        return P()

    def ok(dim):
        return shape[dim] % tp == 0

    if nd == 1:
        # bias vector: column-parallel bias shards with the output; handled
        # by the caller pairing. Standalone vectors (norms) replicate.
        return P()
    lead = (None,) * (nd - 2)  # stacked-layer / expert leading dims untouched
    if kind == "row" and ok(nd - 2):
        return P(*lead, axis, None)
    if kind == "col" and ok(nd - 1):
        return P(*lead, None, axis)
    if kind == "embed":
        # [vocab, hidden] → vocab-dim sharding (reference embedding slicing)
        if shape[0] % tp == 0:
            return P(axis, *((None,) * (nd - 1)))
        return P()
    if kind == "default" and shard_default and ok(nd - 1):
        # reference default: unmatched linears become column-parallel
        return P(*lead, None, axis)
    return P()


def infer_partition_specs(
    params: Any,
    tp_size: Optional[int] = None,
    axis: str = MODEL_AXIS,
    shard_default: bool = True,
    min_size: int = 1024,
) -> Any:
    """Infer a tensor-parallel PartitionSpec pytree for an arbitrary model.

    params:        the model's parameter pytree (arrays or ShapeDtypeStructs)
    tp_size:       model-axis size (default: current topology's)
    shard_default: column-shard unmatched 2-D weights (the reference AutoTP
                   default); False = only shard recognized names
    min_size:      leaves with fewer elements stay replicated

    Returns a pytree of PartitionSpec matching ``params``, for
    ``deepspeed_tpu.initialize(param_specs=...)``.
    """
    if tp_size is None:
        tp_size = get_topology().model_parallel_size

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    names = [_path_str(path) for path, _ in flat]
    kinds = [classify(n) for n in names]

    # pair biases with their kernel's classification (flax: ".../kernel" +
    # ".../bias"; column-parallel bias shards on its only dim)
    specs = []
    for (path, leaf), name, kind in zip(flat, names, kinds):
        shape = tuple(getattr(leaf, "shape", ()))
        n = 1
        for d in shape:
            n *= d
        if n < min_size:
            specs.append(P())
            continue
        if name.endswith("/bias") and len(shape) == 1:
            if kind == "col" or (kind == "default" and shard_default):
                specs.append(P(axis) if shape[0] % tp_size == 0 else P())
            else:
                specs.append(P())  # row-parallel bias is added post-psum once
            continue
        specs.append(_spec_for(kind, shape, tp_size, axis, shard_default))
    return jax.tree_util.tree_unflatten(treedef, specs)


def describe(params: Any, specs: Any) -> str:
    """Human-readable classification table (ds_report-style debugging aid)."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    flat_s = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    lines = []
    for (path, leaf), spec in zip(flat, flat_s):
        shape = tuple(getattr(leaf, "shape", ()))
        lines.append(f"{_path_str(path):<60} {str(shape):<20} {spec}")
    return "\n".join(lines)
