"""Model-transform layer (reference deepspeed/module_inject/): AutoTP spec
inference for arbitrary param pytrees. On TPU there is no module surgery —
classification produces PartitionSpecs and GSPMD does the slicing."""

from deepspeed_tpu.module_inject.auto_tp import (
    classify,
    describe,
    infer_partition_specs,
)

__all__ = ["classify", "describe", "infer_partition_specs"]
