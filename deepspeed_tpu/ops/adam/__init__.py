"""Fused Adam ops (reference csrc/adam/ fused_adam multi_tensor kernel +
cpu_adam AVX implementation, wrapped by ops/adam/{FusedAdam,DeepSpeedCPUAdam}).

TPU-native: one Pallas kernel applies the whole Adam update (m, v, bias
correction, weight decay, param write) in a single VMEM pass over each
parameter shard — the multi-tensor-apply equivalent is the engine updating
all leaves inside one jitted step, letting XLA batch the kernel launches.
"""

from deepspeed_tpu.ops.adam.fused_adam import FusedAdam, fused_adam_step, fused_adam_transform
from deepspeed_tpu.ops.adam.cpu_adam import (
    DeepSpeedCPUAdam,
    cpu_adagrad_step,
    cpu_lion_step,
)

__all__ = [
    "FusedAdam",
    "fused_adam_step",
    "fused_adam_transform",
    "DeepSpeedCPUAdam",
    "cpu_adagrad_step",
    "cpu_lion_step",
]
