"""Host-side (CPU) optimizer kernels for the offload tiers.

Reference: ``csrc/adam/cpu_adam.cpp`` (`adam_update` binding cpu_adam.cpp:10-13,
AVX loops cpu_adam_impl.cpp), ``csrc/adagrad/``, ``csrc/lion/`` — wrapped by
``ops/adam/DeepSpeedCPUAdam``. The native engine here is
``csrc/adam/cpu_adam.cpp`` (this repo): autovectorized OpenMP loops over flat
fp32 arrays, JIT-built by ``NativeOpBuilder``; a numpy fallback keeps parity
when no toolchain exists.

Used by the ZeRO-Offload/SuperOffload path: grads stream D2H, the step runs
here against host-resident master weights + moments, updated params stream
H2D — the device never holds optimizer state.
"""

import ctypes
import itertools

import numpy as np

from deepspeed_tpu.ops.op_builder import NativeOpBuilder, register_op


@register_op
class CPUAdamBuilder(NativeOpBuilder):
    NAME = "cpu_adam"
    SOURCES = ("adam/cpu_adam.cpp",)

    def _bind(self, lib):
        f32, i32, i64 = ctypes.c_float, ctypes.c_int, ctypes.c_int64
        fp = ctypes.POINTER(ctypes.c_float)
        u16p = ctypes.POINTER(ctypes.c_uint16)
        lib.dstpu_create_adam.restype = i32
        lib.dstpu_create_adam.argtypes = [i32, f32, f32, f32, f32, f32, i32]
        lib.dstpu_destroy_adam.restype = i32
        lib.dstpu_destroy_adam.argtypes = [i32]
        lib.dstpu_adam_update.restype = i32
        lib.dstpu_adam_update.argtypes = [i32, i64, f32, fp, fp, fp, fp, i64]
        lib.dstpu_adagrad_update.restype = i32
        lib.dstpu_adagrad_update.argtypes = [f32, f32, f32, fp, fp, fp, i64]
        lib.dstpu_lion_update.restype = i32
        lib.dstpu_lion_update.argtypes = [f32, f32, f32, f32, fp, fp, fp, i64]
        lib.dstpu_bf16_to_fp32.restype = i32
        lib.dstpu_bf16_to_fp32.argtypes = [u16p, fp, i64]
        lib.dstpu_fp32_to_bf16.restype = i32
        lib.dstpu_fp32_to_bf16.argtypes = [fp, u16p, i64]


_IDS = itertools.count(1)


def _native_lib():
    return CPUAdamBuilder.lib()


def _fp(a):
    if a.dtype != np.float32 or not a.flags["C_CONTIGUOUS"]:
        raise ValueError("cpu adam buffers must be C-contiguous float32 arrays")
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


class DeepSpeedCPUAdam:
    """Host Adam/AdamW over flat numpy fp32 state (reference
    ops/adam/cpu_adam.py:DeepSpeedCPUAdam).

    ``step(params, grads, exp_avg, exp_avg_sq, lr=...)`` mutates the numpy
    arrays in place and returns the step count. All arrays must be fp32,
    C-contiguous, same length.
    """

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adamw_mode=True):
        self.lr = lr
        self.beta1, self.beta2 = betas
        self.eps = eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.steps = 0
        self._id = next(_IDS)
        self._lib = _native_lib()
        if self._lib is not None:
            self._lib.dstpu_create_adam(
                self._id, lr, self.beta1, self.beta2, eps, weight_decay,
                int(adamw_mode))

    @property
    def is_native(self):
        return self._lib is not None

    def step(self, params, grads, exp_avg, exp_avg_sq, lr=None, step=None):
        lr = self.lr if lr is None else float(lr)
        self.steps = int(step) if step is not None else self.steps + 1
        n = params.size
        if not (grads.size == n and exp_avg.size == n and exp_avg_sq.size == n):
            raise ValueError(
                f"param size {n} != grads {grads.size} / exp_avg {exp_avg.size} "
                f"/ exp_avg_sq {exp_avg_sq.size}")
        if self._lib is not None:
            rc = self._lib.dstpu_adam_update(
                self._id, self.steps, lr, _fp(params), _fp(grads), _fp(exp_avg),
                _fp(exp_avg_sq), n)
            if rc != 0:
                raise RuntimeError(f"cpu adam_update failed rc={rc}")
            return self.steps
        # numpy fallback — bit-for-bit same math as the C++ loop
        b1, b2, eps, wd = self.beta1, self.beta2, self.eps, self.weight_decay
        t = self.steps
        g = grads
        if not self.adamw_mode and wd != 0.0:
            g = grads + wd * params
        np.multiply(exp_avg, b1, out=exp_avg)
        exp_avg += (1.0 - b1) * g
        np.multiply(exp_avg_sq, b2, out=exp_avg_sq)
        exp_avg_sq += (1.0 - b2) * np.square(g)
        bc1 = 1.0 - b1 ** t
        bc2 = 1.0 - b2 ** t
        denom = np.sqrt(exp_avg_sq) / np.sqrt(bc2) + eps
        if self.adamw_mode and wd != 0.0:
            params *= 1.0 - lr * wd
        params -= (lr / bc1) * (exp_avg / denom)
        return self.steps

    def __del__(self):
        try:
            if self._lib is not None:
                self._lib.dstpu_destroy_adam(self._id)
        except Exception:
            pass


def cpu_adagrad_step(params, grads, exp_avg_sq, lr, eps=1e-8, weight_decay=0.0):
    """Host Adagrad step (reference csrc/adagrad/cpu_adagrad.cpp)."""
    lib = _native_lib()
    if lib is not None:
        rc = lib.dstpu_adagrad_update(lr, eps, weight_decay, _fp(params),
                                      _fp(grads), _fp(exp_avg_sq), params.size)
        if rc != 0:
            raise RuntimeError(f"cpu adagrad_update failed rc={rc}")
        return
    g = grads + weight_decay * params if weight_decay else grads
    exp_avg_sq += np.square(g)
    params -= lr * g / (np.sqrt(exp_avg_sq) + eps)


def cpu_lion_step(params, grads, exp_avg, lr, betas=(0.9, 0.99), weight_decay=0.0):
    """Host Lion step (reference csrc/lion/cpu_lion.cpp)."""
    lib = _native_lib()
    if lib is not None:
        rc = lib.dstpu_lion_update(lr, betas[0], betas[1], weight_decay,
                                   _fp(params), _fp(grads), _fp(exp_avg), params.size)
        if rc != 0:
            raise RuntimeError(f"cpu lion_update failed rc={rc}")
        return
    c = betas[0] * exp_avg + (1.0 - betas[0]) * grads
    params -= lr * (np.sign(c) + weight_decay * params)
    exp_avg *= betas[1]
    exp_avg += (1.0 - betas[1]) * grads


def bf16_to_fp32(src_u16, dst_f32=None):
    """Widen a bf16-as-uint16 view into fp32 (native round trip helper)."""
    lib = _native_lib()
    if dst_f32 is None:
        dst_f32 = np.empty(src_u16.size, dtype=np.float32)
    if lib is not None and src_u16.flags["C_CONTIGUOUS"]:
        lib.dstpu_bf16_to_fp32(
            src_u16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)), _fp(dst_f32),
            src_u16.size)
    else:
        dst_f32[:] = (src_u16.astype(np.uint32) << 16).view(np.float32)
    return dst_f32


def fp32_to_bf16(src_f32, dst_u16=None):
    """Round fp32 to bf16-as-uint16 with round-to-nearest-even."""
    lib = _native_lib()
    if dst_u16 is None:
        dst_u16 = np.empty(src_f32.size, dtype=np.uint16)
    if lib is not None and src_f32.flags["C_CONTIGUOUS"]:
        lib.dstpu_fp32_to_bf16(
            _fp(src_f32), dst_u16.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            src_f32.size)
    else:
        bits = src_f32.view(np.uint32)
        rounding = np.uint32(0x7FFF) + ((bits >> 16) & 1)
        dst_u16[:] = ((bits + rounding) >> 16).astype(np.uint16)
    return dst_u16
