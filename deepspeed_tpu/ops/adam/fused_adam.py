"""Fused Adam: single-pass m/v/param update as a Pallas kernel.

Reference: ``multi_tensor_adam.cu`` (csrc/adam/fused_adam_frontend.cpp:22) —
one fused CUDA kernel updating many tensors; and ``cpu_adam_impl.cpp`` for
the offloaded variant. On TPU the fused update is one VMEM pass; XLA already
fuses the optax elementwise chain into comparable code, so the Pallas kernel
exists for the op_builder parity surface and as the building block for the
offload tier's host-batched updates; numerics are bit-compatible with the
jnp path (tests/unit/ops/test_fused_adam.py).
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamParams(NamedTuple):
    lr: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    adam_w_mode: bool = True
    bias_correction: bool = True


def _adam_math(p, g, m, v, step, hp: AdamParams, lr):
    """The update shared by every path (matches reference Adam semantics:
    adam_w_mode=True → AdamW decoupled decay, else L2-into-grad)."""
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not hp.adam_w_mode and hp.weight_decay:
        g = g + hp.weight_decay * p32
    m_new = hp.beta1 * m + (1 - hp.beta1) * g
    v_new = hp.beta2 * v + (1 - hp.beta2) * jnp.square(g)
    if hp.bias_correction:
        c1 = 1 - hp.beta1 ** step
        c2 = 1 - hp.beta2 ** step
        update = (m_new / c1) / (jnp.sqrt(v_new / c2) + hp.eps)
    else:
        update = m_new / (jnp.sqrt(v_new) + hp.eps)
    if hp.adam_w_mode and hp.weight_decay:
        update = update + hp.weight_decay * p32
    return (p32 - lr * update).astype(p.dtype), m_new, v_new


def _fused_kernel(step_ref, lr_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref, *, hp):
    step = step_ref[0, 0].astype(jnp.float32)
    lr = lr_ref[0, 0]
    p_new, m_new, v_new = _adam_math(p_ref[:], g_ref[:], m_ref[:], v_ref[:], step, hp, lr)
    po_ref[:] = p_new
    mo_ref[:] = m_new
    vo_ref[:] = v_new


def fused_adam_step(
    params: jax.Array,
    grads: jax.Array,
    m: jax.Array,
    v: jax.Array,
    step,
    hp: AdamParams = AdamParams(),
    lr=None,
    block: int = 2048,
    interpret: bool = False,
):
    """Pallas fused update over ONE flat shard. Returns (params, m, v)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lr = jnp.asarray(hp.lr if lr is None else lr, jnp.float32).reshape((1, 1))
    step = jnp.asarray(step, jnp.int32).reshape((1, 1))
    orig_shape = params.shape
    n = params.size
    flat = lambda a, dt: a.reshape(-1).astype(dt)
    p, g = flat(params, params.dtype), flat(grads, jnp.float32)
    mm, vv = flat(m, jnp.float32), flat(v, jnp.float32)
    pad = (-n) % (block * 8)
    if pad:
        zpad = lambda a: jnp.concatenate([a, jnp.zeros((pad,), a.dtype)])
        p, g, mm, vv = zpad(p), zpad(g), zpad(mm), zpad(vv)
    rows = p.shape[0] // block
    shape2 = (rows, block)
    p, g, mm, vv = (a.reshape(shape2) for a in (p, g, mm, vv))

    p_new, m_new, v_new = pl.pallas_call(
        functools.partial(_fused_kernel, hp=hp),
        grid=(rows // 8,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape2, params.dtype),
            jax.ShapeDtypeStruct(shape2, jnp.float32),
            jax.ShapeDtypeStruct(shape2, jnp.float32),
        ],
        interpret=interpret,
    )(step, lr, p, g, mm, vv)
    unflat = lambda a: a.reshape(-1)[:n].reshape(orig_shape)
    return unflat(p_new), unflat(m_new), unflat(v_new)


class FusedAdamState(NamedTuple):
    m: any
    v: any
    count: jnp.ndarray


def fused_adam_transform(hp: AdamParams = AdamParams(), use_pallas: bool = None):
    """optax-contract transformation: ``update(grads, state, params, lr) ->
    (updates, new_state)`` where ``params + updates`` is the fused-Adam
    result — pluggable into DeepSpeedOptimizer.step's ``apply_updates`` flow.
    The Pallas kernel handles large flat leaves on TPU; the jnp path (XLA-
    fused) defines the semantics elsewhere."""
    import optax

    if use_pallas is None:
        # pallas_call is opaque to GSPMD — under a multi-device mesh the
        # jnp path keeps ZeRO-sharded optimizer state partitioned; the
        # kernel serves single-chip and the host-offload tier
        from deepspeed_tpu.parallel.topology import get_topology

        use_pallas = jax.default_backend() == "tpu" and get_topology().world_size == 1

    def init(params):
        z = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdamState(m=z, v=jax.tree.map(jnp.copy, z), count=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None, *, lr):
        assert params is not None, "fused adam needs params"
        count = state.count + 1
        stepf = count.astype(jnp.float32)

        def leaf(p, g, m, v):
            if use_pallas and p.size >= 1 << 16:
                p_new, m_new, v_new = fused_adam_step(p, g, m, v, count, hp, lr)
            else:
                p_new, m_new, v_new = _adam_math(p, g.astype(jnp.float32), m, v, stepf, hp, lr)
            return (p_new - p).astype(p.dtype), m_new, v_new

        out = jax.tree.map(leaf, params, grads, state.m, state.v)
        treedef = jax.tree_util.tree_structure(params)
        flat = treedef.flatten_up_to(out)
        updates = treedef.unflatten([o[0] for o in flat])
        new_m = treedef.unflatten([o[1] for o in flat])
        new_v = treedef.unflatten([o[2] for o in flat])
        return updates, FusedAdamState(m=new_m, v=new_v, count=count)

    return optax.GradientTransformation(init, update)


class FusedAdam:
    """API-parity wrapper (reference ops/adam/FusedAdam): hyperparams + the
    optax-contract transform, consumed by runtime/optimizers.build_optimizer
    for config ``{"optimizer": {"type": "FusedAdam"}}``."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adam_w_mode=True, bias_correction=True):
        self.hp = AdamParams(
            lr=lr, beta1=betas[0], beta2=betas[1], eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
            bias_correction=bias_correction,
        )
        tx = fused_adam_transform(self.hp)
        self.init, self.update = tx.init, tx.update
