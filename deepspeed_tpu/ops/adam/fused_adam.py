"""Fused Adam: single-pass m/v/param update as a Pallas kernel.

Reference: ``multi_tensor_adam.cu`` (csrc/adam/fused_adam_frontend.cpp:22) —
one fused CUDA kernel updating many tensors; and ``cpu_adam_impl.cpp`` for
the offloaded variant. On TPU the fused update is one VMEM pass; XLA already
fuses the optax elementwise chain into comparable code, so the Pallas kernel
exists for the op_builder parity surface and as the building block for the
offload tier's host-batched updates; numerics are bit-compatible with the
jnp path (tests/unit/ops/test_fused_adam.py).
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp


class AdamParams(NamedTuple):
    lr: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    adam_w_mode: bool = True
    bias_correction: bool = True


def _adam_math(p, g, m, v, step, hp: AdamParams, lr, c1=None, c2=None):
    """The update shared by every path (matches reference Adam semantics:
    adam_w_mode=True → AdamW decoupled decay, else L2-into-grad).

    ``c1``/``c2`` optionally carry precomputed bias corrections — the Pallas
    kernel passes them in because Mosaic cannot lower a traced-exponent
    ``pow`` inside the kernel body."""
    g = g.astype(jnp.float32)
    p32 = p.astype(jnp.float32)
    if not hp.adam_w_mode and hp.weight_decay:
        g = g + hp.weight_decay * p32
    m_new = hp.beta1 * m + (1 - hp.beta1) * g
    v_new = hp.beta2 * v + (1 - hp.beta2) * jnp.square(g)
    if hp.bias_correction:
        if c1 is None:
            c1 = 1 - hp.beta1 ** step
        if c2 is None:
            c2 = 1 - hp.beta2 ** step
        update = (m_new / c1) / (jnp.sqrt(v_new / c2) + hp.eps)
    else:
        update = m_new / (jnp.sqrt(v_new) + hp.eps)
    if hp.adam_w_mode and hp.weight_decay:
        update = update + hp.weight_decay * p32
    return (p32 - lr * update).astype(p.dtype), m_new, v_new


def _fused_kernel(lr_ref, c1_ref, c2_ref, p_ref, g_ref, m_ref, v_ref, po_ref, mo_ref, vo_ref, *, hp):
    lr = lr_ref[0, 0]
    p_new, m_new, v_new = _adam_math(
        p_ref[:], g_ref[:], m_ref[:], v_ref[:], None, hp, lr,
        c1=c1_ref[0, 0], c2=c2_ref[0, 0],
    )
    po_ref[:] = p_new
    mo_ref[:] = m_new
    vo_ref[:] = v_new


def fused_adam_step(
    params: jax.Array,
    grads: jax.Array,
    m: jax.Array,
    v: jax.Array,
    step,
    hp: AdamParams = AdamParams(),
    lr=None,
    block: int = 2048,
    interpret: bool = False,
):
    """Pallas fused update over ONE flat shard. Returns (params, m, v)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    lr = jnp.asarray(hp.lr if lr is None else lr, jnp.float32).reshape((1, 1))
    stepf = jnp.asarray(step, jnp.float32).reshape((1, 1))
    c1 = 1.0 - hp.beta1 ** stepf  # bias corrections computed outside the
    c2 = 1.0 - hp.beta2 ** stepf  # kernel (Mosaic can't lower traced pow)
    orig_shape = params.shape
    n = params.size
    flat = lambda a, dt: a.reshape(-1).astype(dt)
    p, g = flat(params, params.dtype), flat(grads, jnp.float32)
    mm, vv = flat(m, jnp.float32), flat(v, jnp.float32)
    pad = (-n) % (block * 8)
    if pad:
        zpad = lambda a: jnp.concatenate([a, jnp.zeros((pad,), a.dtype)])
        p, g, mm, vv = zpad(p), zpad(g), zpad(mm), zpad(vv)
    rows = p.shape[0] // block
    shape2 = (rows, block)
    p, g, mm, vv = (a.reshape(shape2) for a in (p, g, mm, vv))

    p_new, m_new, v_new = pl.pallas_call(
        functools.partial(_fused_kernel, hp=hp),
        grid=(rows // 8,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
            pl.BlockSpec((8, block), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(shape2, params.dtype),
            jax.ShapeDtypeStruct(shape2, jnp.float32),
            jax.ShapeDtypeStruct(shape2, jnp.float32),
        ],
        interpret=interpret,
    )(lr, c1, c2, p, g, mm, vv)
    unflat = lambda a: a.reshape(-1)[:n].reshape(orig_shape)
    return unflat(p_new), unflat(m_new), unflat(v_new)


class FusedAdamState(NamedTuple):
    m: any
    v: any
    count: jnp.ndarray


def _spec_axes(spec):
    """Flat tuple of mesh axis names appearing in a PartitionSpec."""
    axes = []
    for entry in tuple(spec or ()):
        if entry is None:
            continue
        axes.extend(entry if isinstance(entry, (tuple, list)) else (entry,))
    return tuple(axes)


def _shardable(shape, spec, mesh) -> bool:
    """Every sharded dim must divide evenly for shard_map."""
    for i, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        k = 1
        for a in entry if isinstance(entry, (tuple, list)) else (entry,):
            k *= mesh.shape[a]
        if i >= len(shape) or shape[i] % k:
            return False
    return True


def _sharded_adam_step(p, g, m, v, count, hp, lr, spec, mesh, interpret):
    """Per-shard Pallas update under partial-manual shard_map: each device
    runs the fused kernel on its local slice of the ZeRO-partitioned
    p/g/m/v (the TPU form of the reference's per-partition multi_tensor
    update, stage_1_and_2.py step). Axes not in ``spec`` stay automatic,
    so this composes with the surrounding GSPMD program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, spec)
    p, g, m, v = (jax.lax.with_sharding_constraint(x, sharding) for x in (p, g, m, v))
    fn = jax.shard_map(
        lambda p_, g_, m_, v_, c_, lr_: fused_adam_step(
            p_, g_, m_, v_, c_, hp, lr_, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, P(), P()),
        out_specs=(spec, spec, spec),
        axis_names=set(_spec_axes(spec)),
        check_vma=False,
    )
    return fn(p, g, m, v, count, jnp.asarray(lr, jnp.float32))


def fused_adam_transform(
    hp: AdamParams = AdamParams(),
    use_pallas: bool = None,
    master_specs=None,
    mesh=None,
    interpret: bool = False,
):
    """optax-contract transformation: ``update(grads, state, params, lr) ->
    (updates, new_state)`` where ``params + updates`` is the fused-Adam
    result — pluggable into DeepSpeedOptimizer.step's ``apply_updates`` flow.

    Single device: the Pallas kernel runs on whole leaves. Multi-device mesh
    with ``master_specs``/``mesh`` provided (the engine plumbs its ZeRO
    plan): the kernel runs per-shard under shard_map on each leaf's own
    partition layout — no gather, optimizer state stays ZeRO-partitioned.
    The jnp path (XLA-fused) defines the semantics everywhere else."""
    import optax

    if use_pallas is None:
        use_pallas = interpret or jax.default_backend() == "tpu"
    single_device = True
    if mesh is not None:
        single_device = mesh.size == 1
    else:
        from deepspeed_tpu.parallel.topology import get_topology

        single_device = get_topology().world_size == 1
    sharded = use_pallas and not single_device and master_specs is not None and mesh is not None

    flat_specs = None
    if sharded:
        from jax.sharding import PartitionSpec

        is_spec = lambda x: x is None or isinstance(x, PartitionSpec)
        flat_specs = jax.tree_util.tree_leaves(master_specs, is_leaf=is_spec)

    def init(params):
        z = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return FusedAdamState(m=z, v=jax.tree.map(jnp.copy, z), count=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None, *, lr):
        if params is None:
            raise ValueError("fused adam needs params")
        count = state.count + 1
        stepf = count.astype(jnp.float32)

        def leaf(p, g, m, v, spec=None):
            if use_pallas and p.size >= 1 << 16:
                if (
                    sharded
                    and spec is not None
                    and _spec_axes(spec)
                    and _shardable(p.shape, spec, mesh)
                ):
                    p_new, m_new, v_new = _sharded_adam_step(
                        p, g, m, v, count, hp, lr, spec, mesh, interpret
                    )
                elif single_device:
                    p_new, m_new, v_new = fused_adam_step(
                        p, g, m, v, count, hp, lr, interpret=interpret
                    )
                else:  # multi-device but this leaf has no usable spec
                    p_new, m_new, v_new = _adam_math(p, g.astype(jnp.float32), m, v, stepf, hp, lr)
            else:
                p_new, m_new, v_new = _adam_math(p, g.astype(jnp.float32), m, v, stepf, hp, lr)
            return (p_new - p).astype(p.dtype), m_new, v_new

        treedef = jax.tree_util.tree_structure(params)
        flat_p = treedef.flatten_up_to(params)
        flat_g = treedef.flatten_up_to(grads)
        flat_m = treedef.flatten_up_to(state.m)
        flat_v = treedef.flatten_up_to(state.v)
        specs = flat_specs if flat_specs is not None else [None] * len(flat_p)
        flat = [
            leaf(p, g, m, v, s)
            for p, g, m, v, s in zip(flat_p, flat_g, flat_m, flat_v, specs)
        ]
        updates = treedef.unflatten([o[0] for o in flat])
        new_m = treedef.unflatten([o[1] for o in flat])
        new_v = treedef.unflatten([o[2] for o in flat])
        return updates, FusedAdamState(m=new_m, v=new_v, count=count)

    return optax.GradientTransformation(init, update)


class FusedAdam:
    """API-parity wrapper (reference ops/adam/FusedAdam): hyperparams + the
    optax-contract transform, consumed by runtime/optimizers.build_optimizer
    for config ``{"optimizer": {"type": "FusedAdam"}}``."""

    def __init__(self, lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                 adam_w_mode=True, bias_correction=True, master_specs=None,
                 mesh=None, interpret=False):
        self.hp = AdamParams(
            lr=lr, beta1=betas[0], beta2=betas[1], eps=eps,
            weight_decay=weight_decay, adam_w_mode=adam_w_mode,
            bias_correction=bias_correction,
        )
        tx = fused_adam_transform(
            self.hp, master_specs=master_specs, mesh=mesh, interpret=interpret
        )
        self.init, self.update = tx.init, tx.update
