"""Op registry + native JIT build layer.

TPU-native analogue of the reference ``op_builder/`` system (``OpBuilder`` ABC
builder.py:116, JIT compile ``OpBuilder.jit_load`` builder.py:544, reflection
enumeration all_ops.py:22-32). Device ops are Pallas kernels (or fused XLA
subgraphs); *host* ops — async file I/O for the NVMe tier, CPU optimizers for
offload — are C++ shared libraries under ``csrc/`` JIT-compiled with g++ on
first load (the reference uses ninja+pybind11; this image has neither, so we
drive g++ directly and bind via ctypes).
"""

import hashlib
import os
import platform
import shutil
import subprocess
import threading

from deepspeed_tpu.utils.logging import logger

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CSRC_DIR = os.path.join(_REPO_ROOT, "csrc")
_BUILD_DIR = os.environ.get(
    "DSTPU_BUILD_DIR", os.path.join(_REPO_ROOT, "build", "dstpu_ops")
)
_BUILD_LOCK = threading.Lock()


class OpBuilder:
    """Base class: a named, lazily-loaded op implementation."""

    NAME = "base_op"

    def __init__(self):
        self._loaded = None

    def is_compatible(self, verbose=False):
        return True

    def load(self, verbose=True):
        if self._loaded is None:
            self._loaded = self._build()
            if verbose:
                logger.info(f"Loaded TPU op: {self.NAME}")
        return self._loaded

    def _build(self):
        raise NotImplementedError


class PallasOpBuilder(OpBuilder):
    """An op backed by a Pallas TPU kernel with a jnp reference fallback on CPU."""

    def _build(self):
        raise NotImplementedError


def jit_native(name, sources, extra_flags=(), verbose=False):
    """Compile ``csrc/`` sources into ``build/dstpu_ops/<name>.so`` and return
    the .so path, rebuilding only when a source is newer than the artifact
    (reference ``OpBuilder.jit_load`` builder.py:544, minus ninja).

    Returns None (with a logged warning) when the toolchain or compile fails —
    callers fall back to their pure-Python path.
    """
    srcs = [s if os.path.isabs(s) else os.path.join(_CSRC_DIR, s) for s in sources]

    def artifact(flags):
        # -march=native bakes in this host's ISA: artifacts must be per-host
        # when the build dir may be shared (repo on NFS in multi-host jobs).
        host = [platform.machine()]
        if any("native" in f for f in flags):
            host.append(platform.node())
        tag = hashlib.sha1("|".join(srcs + list(flags) + host).encode()).hexdigest()[:8]
        return os.path.join(_BUILD_DIR, f"{name}-{tag}.so")

    def fresh(path):
        return os.path.exists(path) and all(
            os.path.getmtime(path) >= os.path.getmtime(s) for s in srcs
        )

    def compile_to(out, flags):
        # Compile to a process-unique temp path and os.replace into place:
        # concurrent processes (pytest-xdist, multi-host launches) never see a
        # half-written .so, and the loser of the race just overwrites with an
        # identical artifact.
        tmp = f"{out}.tmp.{os.getpid()}"
        cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread"]
               + list(flags) + srcs + ["-o", tmp])
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
        except (OSError, subprocess.TimeoutExpired) as e:  # no g++ / hung compile
            logger.warning(f"native build of {name} unavailable: {e}")
            return None
        if proc.returncode != 0:
            logger.warning(f"native build of {name} with {list(flags)} failed:\n"
                           f"{proc.stderr[-2000:]}")
            return None
        os.replace(tmp, out)
        return out

    base_flags = ()
    with _BUILD_LOCK:
        out_full = artifact(extra_flags)
        out_base = artifact(base_flags)
        # Degraded (no-extra-flags) builds are cached under their OWN tag, and
        # the full-flags compile is ALWAYS retried first when its artifact is
        # missing/stale — a cached degraded build never pins a capable host to
        # the slow path.
        if fresh(out_full):
            return out_full
        os.makedirs(_BUILD_DIR, exist_ok=True)
        out = compile_to(out_full, extra_flags)
        if out is None and extra_flags:
            out = out_base if fresh(out_base) else compile_to(out_base, base_flags)
        if out is not None and verbose:
            logger.info(f"built native op {name} -> {out}")
        return out


class NativeOpBuilder(OpBuilder):
    """An op backed by a g++-compiled C++ shared library bound via ctypes.

    Subclasses set ``SOURCES`` (paths relative to ``csrc/``) and implement
    ``_bind(lib)`` to declare ctypes signatures on the loaded CDLL.
    ``cls.lib()`` is the shared once-per-process accessor (honoring the
    ``DSTPU_DISABLE_NATIVE_<NAME>`` kill switch); modules use it instead of
    hand-rolled globals.
    """

    SOURCES = ()
    EXTRA_FLAGS = ("-fopenmp", "-march=native", "-funroll-loops")
    _lib_cache = {}  # per-class: NAME -> CDLL or None

    def is_compatible(self, verbose=False):
        # Cheap capability probe (reference ds_report semantics): do NOT
        # compile as a side effect — a toolchain or an already-built artifact
        # means the op can load. A cached None means a FAILED build (or the
        # kill switch): report incompatible, not available.
        if self.NAME in self._lib_cache:
            return self._lib_cache[self.NAME] is not None
        return shutil.which("g++") is not None

    @classmethod
    def lib(cls):
        """Load (building if needed) and cache the CDLL; None => fallback."""
        if cls.NAME not in NativeOpBuilder._lib_cache:
            if os.environ.get(f"DSTPU_DISABLE_NATIVE_{cls.NAME.upper()}") == "1":
                NativeOpBuilder._lib_cache[cls.NAME] = None
            else:
                NativeOpBuilder._lib_cache[cls.NAME] = cls()._build()
        return NativeOpBuilder._lib_cache[cls.NAME]

    def _build(self):
        import ctypes

        so = jit_native(self.NAME, self.SOURCES, self.EXTRA_FLAGS)
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
            self._bind(lib)
        except OSError as e:  # corrupt artifact — fall back to pure Python
            logger.warning(f"native op {self.NAME} failed to load ({e}); using fallback")
            return None
        return lib

    def _bind(self, lib):
        raise NotImplementedError


# Populated by the @register_op decorators in deepspeed_tpu/ops/__init__.py.
ALL_OPS = {}


def register_op(builder_cls):
    ALL_OPS[builder_cls.NAME] = builder_cls
    return builder_cls


def build_all_ops(verbose=True):
    """AOT-build every native op now (reference ``DS_BUILD_OPS=1`` setup.py
    path — pre-compiling instead of JIT on first use). Pallas ops have no
    build step; native ones compile their .so. Returns {name: ok}."""
    import deepspeed_tpu.ops  # noqa: F401 — populate the registry

    results = {}
    for name, cls in sorted(ALL_OPS.items()):
        builder = cls()
        if isinstance(builder, NativeOpBuilder):
            results[name] = cls.lib() is not None
        else:
            try:
                builder.load(verbose=False)
                results[name] = True
            except Exception as e:
                logger.warning(f"build_all_ops: {name} failed: {e!r}")
                results[name] = False
        if verbose:
            logger.info(f"build_all_ops: {name} -> {'ok' if results[name] else 'FAILED'}")
    return results


if __name__ == "__main__":  # python -m deepspeed_tpu.ops.op_builder
    import sys

    ok = build_all_ops()
    sys.exit(0 if all(ok.values()) else 1)
