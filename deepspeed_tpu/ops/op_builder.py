"""Op registry.

TPU-native analogue of the reference ``op_builder/`` system (``OpBuilder`` ABC
builder.py:116, reflection enumeration all_ops.py:22-32). There is no JIT
C++ compilation step on TPU — "ops" are Pallas kernels (or fused XLA
subgraphs) registered here and loaded lazily via
``get_accelerator().create_op_builder(name)``.
"""

from deepspeed_tpu.utils.logging import logger


class OpBuilder:
    """Base class: a named, lazily-loaded op implementation."""

    NAME = "base_op"

    def __init__(self):
        self._loaded = None

    def is_compatible(self, verbose=False):
        return True

    def load(self, verbose=True):
        if self._loaded is None:
            self._loaded = self._build()
            if verbose:
                logger.info(f"Loaded TPU op: {self.NAME}")
        return self._loaded

    def _build(self):
        raise NotImplementedError


class PallasOpBuilder(OpBuilder):
    """An op backed by a Pallas TPU kernel with a jnp reference fallback on CPU."""

    def _build(self):
        raise NotImplementedError


# Populated by the @register_op decorators in deepspeed_tpu/ops/__init__.py.
ALL_OPS = {}


def register_op(builder_cls):
    ALL_OPS[builder_cls.NAME] = builder_cls
    return builder_cls
