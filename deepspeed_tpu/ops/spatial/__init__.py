"""Spatial (diffusion) ops.

Reference: ``csrc/spatial/`` (opt_bias_add / opt_bias_add_add kernels) and
the diffusers attention/groupnorm fusions used by stable-diffusion
inference. On TPU these are XLA-fusable elementwise chains — the value of
the module is the parity surface plus NHWC-layout discipline (channels-last
keeps the lane dimension dense on the VPU)."""

import jax
import jax.numpy as jnp


def nhwc_bias_add(activation: jax.Array, bias: jax.Array) -> jax.Array:
    """act [n, h, w, c] + bias [c] (reference opt_bias_add)."""
    return activation + bias.astype(activation.dtype)


def bias_add_add(activation: jax.Array, other: jax.Array, bias: jax.Array) -> jax.Array:
    """act + other + bias (reference opt_bias_add_add — the residual form)."""
    return activation + other + bias.astype(activation.dtype)


def nhwc_group_norm(x: jax.Array, gamma: jax.Array, beta: jax.Array,
                    num_groups: int, eps: float = 1e-5) -> jax.Array:
    """GroupNorm over NHWC (channels last; diffusion UNet blocks)."""
    n, h, w, c = x.shape
    if c % num_groups != 0:
        raise ValueError(f"channels {c} not divisible by num_groups {num_groups}")
    g = x.astype(jnp.float32).reshape(n, h, w, num_groups, c // num_groups)
    mean = jnp.mean(g, axis=(1, 2, 4), keepdims=True)
    var = jnp.mean(jnp.square(g - mean), axis=(1, 2, 4), keepdims=True)
    out = (g - mean) * jax.lax.rsqrt(var + eps)
    out = out.reshape(n, h, w, c)
    return (out * gamma.astype(jnp.float32) + beta.astype(jnp.float32)).astype(x.dtype)
