"""Fused cross-entropy over a tiled vocabulary projection (Pallas).

The lm-head + loss is HBM-bound: materializing [b*s, V] logits (V=32k) costs
~6 GB of traffic per step at the bench config (PERF.md item 3). This kernel
fuses the head matmul with an online log-softmax, flash-attention style:
the grid walks (row-block, vocab-tile) with the vocab dimension minor, so
only one [h, bv] weight tile is VMEM-resident at a time while the running
max / sum-exp / target-logit accumulators live in the output blocks (which
Pallas keeps resident across the inner vocab iterations).

Reference analogue: the fused softmax/CE losses in the reference's training
kernels (csrc/transformer/ softmax + the ALST TiledFusedLogitsLoss
runtime/sequence_parallel/ulysses_sp.py:960, which tiles at the jnp level;
this is the kernel-level version).

fwd:  loss_i = lse_i - logit_i[label_i]   (per row; caller masks/means)
bwd:  dx = (softmax - onehot) @ Wᵀ · dloss ; dW = xᵀ (softmax - onehot)·dloss
      — recomputed tile-by-tile from the saved lse, two passes like flash.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128


def _pick(n, target, multiple=1):
    """Largest divisor of n that is <= target and a multiple of ``multiple``
    (Pallas TPU wants block dims divisible by (8, 128)); falls back to the
    largest plain divisor (== n covers the 'whole array' escape hatch)."""
    best = 0
    d = 1
    while d * d <= n:
        if n % d == 0:
            for c in (d, n // d):
                if c <= target and c % multiple == 0:
                    best = max(best, c)
        d += 1
    if best:
        return best
    # no aligned divisor: largest divisor <= target (tiny/odd test shapes)
    best = 1
    d = 1
    while d * d <= n:
        if n % d == 0:
            for c in (d, n // d):
                if c <= target:
                    best = max(best, c)
        d += 1
    return best


def _fwd_kernel(x_ref, w_ref, lbl_ref, loss_ref, lse_ref, acc_ref, *, bn, bv, nv):
    # grid (rows, vocab); vocab minor. x_ref: [bn, h]; w_ref: [h, bv] (tile j)
    # lbl_ref: [1, bn]; acc_ref (scratch, persists over j): [bn, 3*LANES]
    # holding [m | l | tgt] in its three LANES-wide columns.
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:, :LANES] = jnp.full((bn, LANES), -1e30, jnp.float32)
        acc_ref[:, LANES:] = jnp.zeros((bn, 2 * LANES), jnp.float32)

    # feed the MXU the native (bf16) operands with an fp32 accumulator —
    # fp32 VMEM copies of x/w would blow the scoped-vmem budget
    x = x_ref[:]
    w = w_ref[:]
    lbl = lbl_ref[0, :]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )  # [bn, bv] fp32
    m = acc_ref[:, 0]
    l = acc_ref[:, LANES]
    tgt = acc_ref[:, 2 * LANES]
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=-1)
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    hit = cols == lbl[:, None]
    tgt_new = tgt + jnp.sum(jnp.where(hit, logits, 0.0), axis=-1)
    acc_ref[:, :LANES] = jnp.broadcast_to(m_new[:, None], (bn, LANES))
    acc_ref[:, LANES:2 * LANES] = jnp.broadcast_to(l_new[:, None], (bn, LANES))
    acc_ref[:, 2 * LANES:] = jnp.broadcast_to(tgt_new[:, None], (bn, LANES))

    @pl.when(j == nv - 1)
    def _done():
        lse = m_new + jnp.log(jnp.maximum(l_new, 1e-30))
        loss_ref[:] = jnp.broadcast_to((lse - tgt_new)[:, None], (bn, LANES))
        lse_ref[:] = jnp.broadcast_to(lse[:, None], (bn, LANES))


def _bwd_dx_kernel(x_ref, w_ref, lbl_ref, lse_ref, g_ref, dx_ref, acc_ref, *, bn, bv, nv):
    # grid (rows, vocab); fp32 scratch accumulates across vocab tiles — a
    # bf16 += per tile would round 100+ times and corrupt the gradient
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    w = w_ref[:]
    lbl = lbl_ref[0, :]
    lse = lse_ref[:, 0]
    g = g_ref[:, 0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    p = jnp.exp(logits - lse[:, None])
    cols = j * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    d = (p - (cols == lbl[:, None]).astype(jnp.float32)) * g[:, None]
    # d in the operand dtype (matches what XLA autodiff of a bf16 matmul
    # feeds its transpose); accumulation stays fp32 in scratch
    acc_ref[:] += jax.lax.dot_general(
        d.astype(w.dtype), w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == nv - 1)
    def _done():
        dx_ref[:] = acc_ref[:].astype(dx_ref.dtype)


def _bwd_dw_kernel(x_ref, w_ref, lbl_ref, lse_ref, g_ref, dw_ref, acc_ref, *, bn, bv, nr):
    # grid (vocab, rows); fp32 scratch accumulates across row blocks
    vj = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]
    w = w_ref[:]
    lbl = lbl_ref[0, :]
    lse = lse_ref[:, 0]
    g = g_ref[:, 0]
    logits = jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )
    p = jnp.exp(logits - lse[:, None])
    cols = vj * bv + jax.lax.broadcasted_iota(jnp.int32, (bn, bv), 1)
    d = (p - (cols == lbl[:, None]).astype(jnp.float32)) * g[:, None]
    acc_ref[:] += jax.lax.dot_general(
        x, d.astype(x.dtype), (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == nr - 1)
    def _done():
        dw_ref[:] = acc_ref[:].astype(dw_ref.dtype)


def fused_ce_loss(x: jax.Array, w: jax.Array, labels: jax.Array,
                  interpret: bool = False) -> jax.Array:
    """Per-row cross-entropy of ``softmax(x @ w)`` against ``labels`` without
    materializing the [n, V] logits. x: [n, h]; w: [h, V]; labels: [n] int32
    → loss [n] fp32. Differentiable in x and w."""
    return _ce_core(x, w, labels, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ce_core(x, w, labels, interpret):
    out, _ = _ce_fwd(x, w, labels, interpret)
    return out


def _ce_call(x, w, labels, interpret):
    from jax.experimental.pallas import tpu as pltpu

    n, h = x.shape
    V = w.shape[1]
    bn = _pick(n, 256, multiple=8)
    bv = _pick(V, 2048, multiple=128)
    nv = V // bv
    kernel = functools.partial(_fwd_kernel, bn=bn, bv=bv, nv=nv)
    loss, lse = pl.pallas_call(
        kernel,
        grid=(n // bn, nv),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, bv), lambda i, j: (0, j)),
            # [1, n] layout: 1-D int32 blocks trip Mosaic's tiling; a
            # lanes-minor 2-D block matches the XLA layout
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, LANES), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((bn, 3 * LANES), jnp.float32)],
        interpret=interpret,
    )(x, w, labels.astype(jnp.int32).reshape(1, -1))
    return loss[:, 0], lse


def _ce_fwd(x, w, labels, interpret):
    loss, lse = _ce_call(x, w, labels, interpret)
    return loss, (x, w, labels, lse)


def _ce_bwd(interpret, res, g):
    from jax.experimental.pallas import tpu as pltpu

    x, w, labels, lse = res
    n, h = x.shape
    V = w.shape[1]
    bn = _pick(n, 256, multiple=8)
    bv = _pick(V, 2048, multiple=128)
    nv = V // bv
    # the dW pass holds an [h, bv] fp32 scratch accumulator — cap its vocab
    # tile so scratch + weight tile fit scoped VMEM
    bv_w = _pick(V, 512, multiple=128)
    nv_w = V // bv_w
    nr = n // bn
    g2 = jnp.broadcast_to(g.astype(jnp.float32)[:, None], (n, LANES))
    lbl2 = labels.astype(jnp.int32).reshape(1, -1)

    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, bn=bn, bv=bv, nv=nv),
        grid=(nr, nv),
        in_specs=[
            pl.BlockSpec((bn, h), lambda i, j: (i, 0)),
            pl.BlockSpec((h, bv), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, i)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bn, h), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[pltpu.VMEM((bn, h), jnp.float32)],
        interpret=interpret,
    )(x, w, lbl2, lse, g2)

    dw = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, bn=bn, bv=bv_w, nr=nr),
        grid=(nv_w, nr),
        in_specs=[
            pl.BlockSpec((bn, h), lambda j, i: (i, 0)),
            pl.BlockSpec((h, bv_w), lambda j, i: (0, j)),
            pl.BlockSpec((1, bn), lambda j, i: (0, i)),
            pl.BlockSpec((bn, LANES), lambda j, i: (i, 0)),
            pl.BlockSpec((bn, LANES), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((h, bv_w), lambda j, i: (0, j)),
        out_shape=jax.ShapeDtypeStruct(w.shape, w.dtype),
        scratch_shapes=[pltpu.VMEM((h, bv_w), jnp.float32)],
        interpret=interpret,
    )(x, w, lbl2, lse, g2)
    return dx, dw, None  # labels get no cotangent


_ce_core.defvjp(_ce_fwd, _ce_bwd)


def fused_ce_reference(x, w, labels):
    """Dense jnp reference for numerics tests."""
    logits = (x.astype(jnp.float32) @ w.astype(jnp.float32))
    lse = jax.nn.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    return lse - tgt
