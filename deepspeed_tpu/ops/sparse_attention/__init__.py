"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``).

Public surface parity: the sparsity configs, a ``SparseSelfAttention``
module-equivalent, and the functional kernel entry. The Triton blocksparse
matmul/softmax of the reference become one fused Pallas kernel
(sparse_pallas.py) whose kv loop skips inactive blocks.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)
from deepspeed_tpu.ops.sparse_attention.sparse_pallas import (
    sparse_attention,
    sparse_attention_reference,
)


class SparseSelfAttention:
    """Functional analogue of the reference ``SparseSelfAttention`` module
    (``sparse_self_attention.py``): holds a sparsity config, builds/caches
    the block layout per sequence length, and applies the sparse kernel.

    ``__call__(q, k, v)`` with [b, h, s, d] tensors; GQA kv is expanded to
    the q head count first (the layout is per q head).
    """

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add", attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048, interpret: bool = False):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.interpret = interpret
        self._layouts = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        b, h, s, d = query.shape
        if h != self.sparsity_config.num_heads:
            raise ValueError(f"query has {h} heads, sparsity config expects "
                             f"{self.sparsity_config.num_heads}")
        h_kv = key.shape[1]
        if h_kv != h:
            rep = h // h_kv
            key = jnp.repeat(key, rep, axis=1)
            value = jnp.repeat(value, rep, axis=1)
        layout = self.get_layout(s)
        causal = self.sparsity_config.attention == "unidirectional" if hasattr(
            self.sparsity_config, "attention") else False
        if rpe is not None or key_padding_mask is not None or attn_mask is not None:
            # masked variants fall back to the dense reference with the block
            # mask applied (reference applies these inside the softmax kernel:
            # softmax.py rpe/key_padding_mask/attn_mask args)
            bias = jnp.zeros((1, 1, s, s), jnp.float32)
            if rpe is not None:
                bias = bias + rpe.astype(jnp.float32)
            if key_padding_mask is not None:  # [b, s] over keys
                kpm = key_padding_mask.astype(jnp.float32)
                if self.key_padding_mask_mode == "add":
                    bias = bias + kpm[:, None, None, :]
                else:  # "mul": 0 = masked
                    bias = bias + jnp.where(kpm[:, None, None, :] != 0, 0.0, -1e30)
            if attn_mask is not None:  # [s, s] (or broadcastable)
                am = attn_mask.astype(jnp.float32)
                am = am[None, None] if am.ndim == 2 else am
                if self.attn_mask_mode == "add":
                    bias = bias + am
                else:
                    bias = bias + jnp.where(am != 0, 0.0, -1e30)
            return sparse_attention_reference(
                query, key, value, jnp.asarray(layout), self.sparsity_config.block,
                causal=causal, bias=bias,
            )
        return sparse_attention(
            query, key, value, layout, self.sparsity_config.block, causal=causal,
            interpret=self.interpret,
        )


__all__ = [
    "SparsityConfig",
    "DenseSparsityConfig",
    "FixedSparsityConfig",
    "BSLongformerSparsityConfig",
    "BigBirdSparsityConfig",
    "VariableSparsityConfig",
    "SparseSelfAttention",
    "sparse_attention",
    "sparse_attention_reference",
]
