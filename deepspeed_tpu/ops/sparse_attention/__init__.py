"""Block-sparse attention (reference ``deepspeed/ops/sparse_attention/``).

Public surface parity: the sparsity configs, a ``SparseSelfAttention``
module-equivalent, and the functional kernel entries. The Triton
blocksparse matmul/softmax of the reference become Pallas kernels:

  * splash_pallas.py — the production path: masks (mask.py) compile into
    compacted per-q-block schedules (schedule.py) of active kv blocks and
    the kernel's grid covers ONLY those, via scalar prefetch;
  * sparse_pallas.py — the older layout-predicate kernel, kept as the
    ``reference`` oracle for parity tests (it visits every block and
    skips inactive ones under a cond).
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.ops.sparse_attention.config import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    SparsityConfig,
    VariableSparsityConfig,
)
from deepspeed_tpu.ops.sparse_attention.mask import (
    CausalMask,
    DocumentMask,
    FullMask,
    LayoutMask,
    LocalMask,
    Mask,
    MultiHeadMask,
)
from deepspeed_tpu.ops.sparse_attention.schedule import (
    BlockSchedule,
    build_schedule,
    schedule_from_layout,
    schedule_from_mask,
)
from deepspeed_tpu.ops.sparse_attention.sparse_pallas import (
    sparse_attention,
    sparse_attention_reference,
    sparse_attention_with_bias,
)
from deepspeed_tpu.ops.sparse_attention.splash_pallas import (
    splash_attention,
    splash_prefill_attention,
)


class SparseSelfAttention:
    """Functional analogue of the reference ``SparseSelfAttention`` module
    (``sparse_self_attention.py``): holds a sparsity config, builds/caches
    the compacted block schedule per sequence length, and applies the
    scheduled splash kernel (``use_splash=False`` drops back to the
    layout-predicate oracle kernel).

    ``__call__(q, k, v)`` with [b, h, s, d] tensors; GQA kv runs natively
    in the splash kernel (index maps fold the head group — kv is never
    replicated), and is expanded only on the oracle path.
    """

    def __init__(self, sparsity_config: Optional[SparsityConfig] = None,
                 key_padding_mask_mode: str = "add", attn_mask_mode: str = "mul",
                 max_seq_length: int = 2048, interpret: bool = False,
                 use_splash: bool = True):
        self.sparsity_config = sparsity_config or FixedSparsityConfig(num_heads=4)
        self.key_padding_mask_mode = key_padding_mask_mode
        self.attn_mask_mode = attn_mask_mode
        self.max_seq_length = max_seq_length
        self.interpret = interpret
        self.use_splash = use_splash
        self._layouts = {}
        self._schedules = {}

    def get_layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.sparsity_config.make_layout(seq_len)
        return self._layouts[seq_len]

    def get_schedule(self, seq_len: int) -> BlockSchedule:
        # cached: the schedule is a trace-time constant, rebuilt only per
        # new sequence length — never per step
        if seq_len not in self._schedules:
            self._schedules[seq_len] = self.sparsity_config.make_schedule(seq_len)
        return self._schedules[seq_len]

    def __call__(self, query, key, value, rpe=None, key_padding_mask=None, attn_mask=None):
        b, h, s, d = query.shape
        if h != self.sparsity_config.num_heads:
            raise ValueError(f"query has {h} heads, sparsity config expects "
                             f"{self.sparsity_config.num_heads}")
        causal = getattr(self.sparsity_config, "attention",
                         "bidirectional") == "unidirectional"
        if rpe is not None or key_padding_mask is not None or attn_mask is not None:
            # masked variants fall back to the dense biased path (reference
            # applies these inside the softmax kernel: softmax.py rpe/
            # key_padding_mask/attn_mask args)
            h_kv = key.shape[1]
            if h_kv != h:
                key = jnp.repeat(key, h // h_kv, axis=1)
                value = jnp.repeat(value, h // h_kv, axis=1)
            bias = jnp.zeros((1, 1, s, s), jnp.float32)
            if rpe is not None:
                bias = bias + rpe.astype(jnp.float32)
            if key_padding_mask is not None:  # [b, s] over keys
                kpm = key_padding_mask.astype(jnp.float32)
                if self.key_padding_mask_mode == "add":
                    bias = bias + kpm[:, None, None, :]
                else:  # "mul": 0 = masked
                    bias = bias + jnp.where(kpm[:, None, None, :] != 0, 0.0, -1e30)
            if attn_mask is not None:  # [s, s] (or broadcastable)
                am = attn_mask.astype(jnp.float32)
                am = am[None, None] if am.ndim == 2 else am
                if self.attn_mask_mode == "add":
                    bias = bias + am
                else:
                    bias = bias + jnp.where(am != 0, 0.0, -1e30)
            return sparse_attention_with_bias(
                query, key, value, jnp.asarray(self.get_layout(s)),
                self.sparsity_config.block, causal=causal, bias=bias,
            )
        if self.use_splash:
            return splash_attention(
                query, key, value, self.get_schedule(s),
                interpret=self.interpret or None,
            )
        h_kv = key.shape[1]
        if h_kv != h:
            key = jnp.repeat(key, h // h_kv, axis=1)
            value = jnp.repeat(value, h // h_kv, axis=1)
        return sparse_attention(
            query, key, value, self.get_layout(s), self.sparsity_config.block,
            causal=causal, interpret=self.interpret,
        )


__all__ = [
    "SparsityConfig",
    "DenseSparsityConfig",
    "FixedSparsityConfig",
    "BSLongformerSparsityConfig",
    "BigBirdSparsityConfig",
    "VariableSparsityConfig",
    "SparseSelfAttention",
    "Mask",
    "FullMask",
    "CausalMask",
    "LocalMask",
    "DocumentMask",
    "LayoutMask",
    "MultiHeadMask",
    "BlockSchedule",
    "build_schedule",
    "schedule_from_mask",
    "schedule_from_layout",
    "sparse_attention",
    "sparse_attention_reference",
    "sparse_attention_with_bias",
    "splash_attention",
    "splash_prefill_attention",
]
