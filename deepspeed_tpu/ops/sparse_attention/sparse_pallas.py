"""Block-sparse flash attention as a Pallas TPU kernel (fwd + bwd).

TPU-native replacement for the reference Triton blocksparse kernels
(``deepspeed/ops/sparse_attention/matmul.py`` sdd/dsd + ``softmax.py``,
backing ``SparseSelfAttention``). Same online-softmax structure as
``ops/attention/flash_pallas.py``, but the kv loop is guarded by a STATIC
per-head block layout: inactive (q-block, k-block) pairs take a
``lax.cond`` branch that skips both MXU matmuls, so sparsity is skipped
work — the compute cost scales with the number of active blocks, not s².

Layout: int32 [h, nq, nk] (see config.py). Causal masking (within-block)
composes with the layout; configs with attention="unidirectional" already
zero the upper-triangular blocks so those are skipped entirely.

NOTE: this is now the ``reference`` oracle. Inactive blocks here still
cost a grid step and K/V streaming (full [s, d] VMEM residency); the
production path is splash_pallas.py, whose compacted schedule never
visits them at all. Parity tests pin the two against each other.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANES = 128


def _sparse_fwd_kernel(q_ref, k_ref, v_ref, lay_ref, o_ref, lse_ref, *, scale, causal, bq, bk):
    # q_ref: [bq, d]; k/v_ref: [s, d]; lay_ref: [nk] int32 (this q-block's row)
    qi = pl.program_id(2)
    s = k_ref.shape[0]
    d = q_ref.shape[1]
    nk = s // bk

    q = q_ref[:].astype(jnp.float32) * scale
    row = lay_ref[:]  # [nk]
    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def compute(ki, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        v = v_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # guard: when every visited logit is still NEG_INF, logits - m_new
        # is 0 and exp() would emit 1s — a fully-masked row would then
        # average the masked V instead of producing zeros
        p = jnp.where(logits > NEG_INF / 2, jnp.exp(logits - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    def body(ki, carry):
        active = jax.lax.dynamic_index_in_dim(row, ki, keepdims=False) != 0
        return jax.lax.cond(active, lambda c: compute(ki, c), lambda c: c, carry)

    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to((m + jnp.log(l_safe))[:, None], (bq, LANES))


def _sparse_bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, lay_ref, dq_ref,
                          *, scale, causal, bq, bk):
    qi = pl.program_id(2)
    s = k_ref.shape[0]
    d = q_ref.shape[1]
    nk = s // bk

    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, 0]
    delta = jnp.sum(do * o_ref[:].astype(jnp.float32), axis=-1)
    row = lay_ref[:]
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def compute(ki, dq):
        k = k_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        v = v_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        p = jnp.where(logits > NEG_INF / 2, jnp.exp(logits - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    def body(ki, dq):
        active = jax.lax.dynamic_index_in_dim(row, ki, keepdims=False) != 0
        return jax.lax.cond(active, lambda c: compute(ki, c), lambda c: c, dq)

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _sparse_bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, col_ref,
                           dk_ref, dv_ref, *, scale, causal, bq, bk):
    ki = pl.program_id(2)
    sq = q_ref.shape[0]
    d = k_ref.shape[1]
    nq = sq // bq

    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)
    col = col_ref[:]  # [nq] — which q blocks attend this kv block
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

    def compute(qj, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qj * bq, bq), :].astype(jnp.float32) * scale
        do = do_ref[pl.ds(qj * bq, bq), :].astype(jnp.float32)
        o = o_ref[pl.ds(qj * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qj * bq, bq), 0]
        delta = jnp.sum(do * o, axis=-1)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if causal:
            q_pos = qj * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        p = jnp.where(logits > NEG_INF / 2, jnp.exp(logits - lse[:, None]), 0.0)
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    def body(qj, carry):
        active = jax.lax.dynamic_index_in_dim(col, qj, keepdims=False) != 0
        return jax.lax.cond(active, lambda c: compute(qj, c), lambda c: c, carry)

    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (zeros, zeros))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _reject_bias(bias, where):
    if bias is not None:
        raise NotImplementedError(
            f"{where}: additive bias is not supported on the block-sparse "
            "kernel path, and the oracle must match the kernel exactly — "
            "use sparse_attention_with_bias (dense fallback) for rpe/"
            "padding/attention masks")


def sparse_attention(q, k, v, layout, block: int, causal: bool = False,
                     scale: Optional[float] = None, interpret: bool = False,
                     bias=None):
    """Block-sparse attention. q/k/v: [b, h, s, d]; layout: [h, nq, nk] int32.

    ``block`` is the layout's block size; kernel blocks equal it (the layout
    IS the tiling). Fully-masked q rows (no active block) produce zeros.
    ``bias`` raises: the kernel cannot honor it, and its oracle
    (``sparse_attention_reference``) refuses it for the same reason."""
    _reject_bias(bias, "sparse_attention")
    layout = jnp.asarray(layout, jnp.int32)
    return _sparse_core(q, k, v, layout, block, causal, scale, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _sparse_core(q, k, v, layout, block, causal, scale, interpret):
    out, _ = _sparse_fwd(q, k, v, layout, block, causal, scale, interpret)
    return out


def _sparse_fwd(q, k, v, layout, block, causal, scale, interpret):
    b, h, s, d = q.shape
    if k.shape[1] != h:
        raise ValueError("sparse kernel expects matched head counts (expand GQA first)")
    if layout.shape != (h, s // block, s // block):
        raise ValueError(f"layout shape {layout.shape} != expected "
                         f"{(h, s // block, s // block)}")
    bq = bk = block
    scale_v = scale if scale is not None else d**-0.5
    kernel = functools.partial(_sparse_fwd_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk)

    out, lse = pl.pallas_call(
        lambda qr, kr, vr, lr_, orf, lsr: kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], lr_.at[0, 0], orf.at[0, 0], lsr.at[0, 0]
        ),
        grid=(b, h, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s // bk), lambda b_, h_, i: (h_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, layout)
    return out, (q, k, v, layout, out, lse)


def _sparse_bwd(block, causal, scale, interpret, res, g):
    q, k, v, layout, out, lse = res
    b, h, s, d = q.shape
    bq = bk = block
    scale_v = scale if scale is not None else d**-0.5

    dq_kernel = functools.partial(_sparse_bwd_dq_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk)
    dq = pl.pallas_call(
        lambda qr, kr, vr, orf, dor, lsr, lr_, dqr: dq_kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], dor.at[0, 0],
            lsr.at[0, 0], lr_.at[0, 0], dqr.at[0, 0]
        ),
        grid=(b, h, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s // bk), lambda b_, h_, i: (h_, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, out, g, lse, layout)

    layout_t = jnp.swapaxes(layout, 1, 2)  # [h, nk, nq]
    dkv_kernel = functools.partial(_sparse_bwd_dkv_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk)
    dk, dv = pl.pallas_call(
        lambda qr, kr, vr, orf, dor, lsr, cr, dkr, dvr: dkv_kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], dor.at[0, 0],
            lsr.at[0, 0], cr.at[0, 0], dkr.at[0, 0], dvr.at[0, 0]
        ),
        grid=(b, h, s // bk),
        in_specs=[
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, LANES), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s // bq), lambda b_, h_, i: (h_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(q.shape, q.dtype),
        ],
        interpret=interpret,
    )(q, k, v, out, g, lse, layout_t)
    return dq, dk, dv, None  # layout gets no cotangent


_sparse_core.defvjp(_sparse_fwd, _sparse_bwd)


def sparse_attention_reference(q, k, v, layout, block, causal=False, scale=None, bias=None):
    """Dense jnp oracle for the kernel path: expands the block layout to a
    token mask. ``bias`` raises — the kernel cannot honor it, so accepting
    it here would let oracle and kernel silently diverge; the biased dense
    path lives in ``sparse_attention_with_bias``."""
    _reject_bias(bias, "sparse_attention_reference")
    return _sparse_dense(q, k, v, layout, block, causal, scale, None)


def sparse_attention_with_bias(q, k, v, layout, block, causal=False,
                               scale=None, bias=None):
    """Dense block-masked attention WITH additive bias (broadcastable to
    [b, h, s, s]) — the rpe / key-padding / attention-mask fallback used by
    ``SparseSelfAttention``. Deliberately a separate entry point from the
    kernel oracle so the no-bias pair stays bit-comparable."""
    return _sparse_dense(q, k, v, layout, block, causal, scale, bias)


def _sparse_dense(q, k, v, layout, block, causal, scale, bias):
    h, nq, nk = layout.shape
    mask = jnp.repeat(jnp.repeat(jnp.asarray(layout, bool), block, 1), block, 2)
    d = q.shape[-1]
    scale = scale if scale is not None else d**-0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        s = q.shape[2]
        cm = jnp.tril(jnp.ones((s, s), bool))
        logits = jnp.where(cm[None, None], logits, NEG_INF)
    logits = jnp.where(mask[None], logits, NEG_INF)
    # fully-masked rows: softmax would be uniform garbage; zero them like the kernel
    alive = jnp.any(logits > NEG_INF / 2, axis=-1)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", w.astype(v.dtype), v)
    return jnp.where(alive[..., None], out, 0.0)
