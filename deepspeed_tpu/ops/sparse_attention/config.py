"""Block-sparsity layout configs.

Reference: ``deepspeed/ops/sparse_attention/sparsity_config.py`` —
``SparsityConfig`` base plus Dense / Fixed / BSLongformer / BigBird /
Variable patterns, each producing a per-head block-level layout matrix
``[num_heads, num_blocks, num_blocks]`` (1 = the q-block attends to the
k-block). The layout is STATIC (numpy, built at trace time) — on TPU it
drives which kv blocks each kernel program visits, so sparsity becomes
skipped MXU work, not masked work.
"""

from typing import List, Optional

import numpy as np


class SparsityConfig:
    """Base: dense layout (reference sparsity_config.py:SparsityConfig /
    DenseSparsityConfig)."""

    def __init__(self, num_heads: int, block: int = 128, different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=np.int32)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout

    def make_schedule(self, seq_len: int, block_q: Optional[int] = None,
                      block_kv: Optional[int] = None):
        """Compile this config's layout into a compacted BlockSchedule
        (schedule.py) — the form the scheduled splash kernel consumes.
        ``attention="unidirectional"`` configs get the causal predicate
        composed in (diagonal blocks demote to partial, the strict upper
        triangle is pruned before tril even sees it)."""
        from deepspeed_tpu.ops.sparse_attention.schedule import schedule_from_layout

        causal = getattr(self, "attention", "bidirectional") == "unidirectional"
        return schedule_from_layout(
            self.make_layout(seq_len), self.block, causal=causal,
            block_q=block_q, block_kv=block_kv,
        )


DenseSparsityConfig = SparsityConfig


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer 'fixed' pattern (reference FixedSparsityConfig):
    each q block attends its own local window of ``num_local_blocks`` and to
    the last ``num_global_blocks`` of every preceding window (the summary
    columns)."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_local_blocks=4, num_global_blocks=1, attention="bidirectional",
                 horizontal_global_attention=False, num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = (
            num_different_global_patterns if different_layout_per_head else 1
        )

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        for h in range(self.num_heads):
            shift = (h % self.num_different_global_patterns) * G
            for qi in range(n):
                w0 = (qi // L) * L  # this q block's window start
                # local window
                for ki in range(w0, min(w0 + L, n)):
                    layout[h, qi, ki] = 1
                # global: last G blocks of each earlier window
                for ws in range(0, w0, L):
                    lo = max(ws, min(ws + L - G - shift, ws + L - G))
                    for ki in range(lo, min(lo + G, n)):
                        layout[h, qi, ki] = 1
                if self.horizontal_global_attention:
                    # global rows also attend everywhere
                    if (qi % L) >= L - G:
                        layout[h, qi, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BSLongformerSparsityConfig(SparsityConfig):
    """Longformer block pattern (reference BSLongformerSparsityConfig):
    sliding window of ``num_sliding_window_blocks`` + symmetric global
    attention at ``global_block_indices`` (optionally ranges)."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_sliding_window_blocks=3, global_block_indices=(0,),
                 global_block_end_indices: Optional[List[int]] = None,
                 attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices else None
        )
        self.attention = attention

    def _global_cols(self, n):
        cols = []
        if self.global_block_end_indices is None:
            cols = [i for i in self.global_block_indices if i < n]
        else:
            for s, e in zip(self.global_block_indices, self.global_block_end_indices):
                cols.extend(range(s, min(e, n)))
        return cols

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for qi in range(n):
            lo, hi = max(0, qi - w), min(n, qi + w + 1)
            layout[:, qi, lo:hi] = 1
        for c in self._global_cols(n):
            layout[:, :, c] = 1  # everyone attends the global block
            layout[:, c, :] = 1  # the global block attends everyone
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird block pattern (reference BigBirdSparsityConfig): sliding
    window + ``num_global_blocks`` leading globals + ``num_random_blocks``
    random blocks per row (seeded, static)."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_random_blocks=1, num_sliding_window_blocks=3,
                 num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_heads):
            hh = h if self.different_layout_per_head else 0
            rs = np.random.default_rng(self.seed + hh)
            for qi in range(n):
                lo, hi = max(0, qi - w), min(n, qi + w + 1)
                layout[h, qi, lo:hi] = 1
                layout[h, qi, : min(self.num_global_blocks, n)] = 1
                k = min(self.num_random_blocks, n)
                layout[h, qi, rs.choice(n, size=k, replace=False)] = 1
            layout[h, : min(self.num_global_blocks, n), :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Variable pattern (reference VariableSparsityConfig): custom local
    window sizes and explicit global block indices."""

    def __init__(self, num_heads, block=128, different_layout_per_head=False,
                 num_random_blocks=0, local_window_blocks=(4,),
                 global_block_indices=(0,), global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False,
                 seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)
        self.global_block_end_indices = (
            list(global_block_end_indices) if global_block_end_indices else None
        )
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        # consecutive local windows of the given sizes (last repeats)
        start = 0
        sizes = list(self.local_window_blocks)
        while start < n:
            size = sizes.pop(0) if len(sizes) > 1 else self.local_window_blocks[-1]
            end = min(start + size, n)
            layout[:, start:end, start:end] = 1
            start = end
        if self.global_block_end_indices is None:
            cols = [i for i in self.global_block_indices if i < n]
        else:
            cols = []
            for s, e in zip(self.global_block_indices, self.global_block_end_indices):
                cols.extend(range(s, min(e, n)))
        for c in cols:
            layout[:, :, c] = 1
            if self.horizontal_global_attention:
                layout[:, c, :] = 1
        if self.num_random_blocks:
            rs = np.random.default_rng(self.seed)
            for h in range(self.num_heads):
                for qi in range(n):
                    k = min(self.num_random_blocks, n)
                    layout[h, qi, rs.choice(n, size=k, replace=False)] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return layout
