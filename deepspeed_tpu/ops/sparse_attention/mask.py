"""Splash-style mask abstraction: masks that compile to block schedules.

Mirrors the reference SparsityConfig family (and jax's splash_attention
mask classes) at the granularity the TPU kernel actually consumes: every
mask reduces, at trace time, to a per-(q-block, kv-block) STATUS in
{EMPTY, PARTIAL, FULL}.  EMPTY blocks are never scheduled (no grid step,
no HBM stream), FULL blocks run without any in-kernel mask application,
and PARTIAL blocks re-derive the token-level predicate analytically
inside the kernel (causal edge / window edge / segment boundary) — no
dense [s, s] mask is ever materialized.

Masks compose by intersection (``&``): the status lattice combines as
EMPTY-dominates / FULL-requires-both, and the analytic predicates union.
``MultiHeadMask`` stacks per-head masks into the [h, nq, nk] status the
schedule builder (schedule.py) compacts.
"""

from typing import List, Optional, Sequence, Tuple

import numpy as np

EMPTY = 0
PARTIAL = 1
FULL = 2


def _block_grid(sq: int, sk: int, bq: int, bk: int) -> Tuple[int, int]:
    if sq % bq or sk % bk:
        raise ValueError(f"seq ({sq}, {sk}) not divisible by blocks ({bq}, {bk})")
    return sq // bq, sk // bk


class Mask:
    """Base mask over a [sq, sk] token grid.

    Subclasses implement ``block_status(bq, bk) -> np.ndarray [nq, nk]``
    (values in {EMPTY, PARTIAL, FULL}) and declare which analytic
    predicates the kernel must apply inside PARTIAL blocks via the
    ``causal`` / ``window`` / ``segment_ids`` properties.
    """

    def __init__(self, shape: Tuple[int, int]):
        self.shape = (int(shape[0]), int(shape[1]))

    # -- analytic predicate declaration (kernel-side, PARTIAL blocks only) --
    @property
    def causal(self) -> bool:
        return False

    @property
    def window(self) -> int:  # 0 = no sliding-window band
        return 0

    @property
    def segment_ids(self) -> Optional[np.ndarray]:
        return None

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        raise NotImplementedError

    def token_mask(self) -> np.ndarray:
        """Dense [sq, sk] bool mask (True = attend) — oracle for tests."""
        sq, sk = self.shape
        m = np.ones((sq, sk), bool)
        qp = np.arange(sq)[:, None]
        kp = np.arange(sk)[None, :]
        if self.causal:
            m &= qp >= kp
        if self.window:
            # THE shared band convention (ops/attention/core.window_too_far):
            # key out of band iff q - k >= window
            m &= (qp - kp) < self.window
        if self.segment_ids is not None:
            ids = np.asarray(self.segment_ids)
            m &= ids[:sq, None] == ids[None, :sk]
        return m

    def __and__(self, other: "Mask") -> "Mask":
        return MaskAnd(self, other)


class FullMask(Mask):
    """Dense: every block FULL."""

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        nq, nk = _block_grid(*self.shape, bq, bk)
        return np.full((nq, nk), FULL, np.uint8)


class CausalMask(Mask):
    """q attends k iff q >= k (square grids; the serving prefill path
    handles the offset case with an in-jit schedule, see splash_pallas)."""

    @property
    def causal(self) -> bool:
        return True

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        nq, nk = _block_grid(*self.shape, bq, bk)
        q_lo = np.arange(nq)[:, None] * bq          # min q in block
        q_hi = q_lo + bq - 1                        # max q
        k_lo = np.arange(nk)[None, :] * bk
        k_hi = k_lo + bk - 1
        full = q_lo >= k_hi                         # every pair q >= k
        empty = q_hi < k_lo                         # every pair q < k
        return np.where(full, FULL, np.where(empty, EMPTY, PARTIAL)).astype(np.uint8)


class LocalMask(Mask):
    """Causal sliding-window band: q attends k iff k <= q and q - k < window
    (the repo-wide ``window_too_far`` convention)."""

    def __init__(self, shape: Tuple[int, int], window: int):
        super().__init__(shape)
        if window <= 0:
            raise ValueError("LocalMask needs window > 0")
        self._window = int(window)

    @property
    def causal(self) -> bool:
        return True

    @property
    def window(self) -> int:
        return self._window

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        nq, nk = _block_grid(*self.shape, bq, bk)
        w = self._window
        q_lo = np.arange(nq)[:, None] * bq
        q_hi = q_lo + bq - 1
        k_lo = np.arange(nk)[None, :] * bk
        k_hi = k_lo + bk - 1
        # full: every pair satisfies k <= q AND q - k < window
        full = (k_hi <= q_lo) & ((q_hi - k_lo) < w)
        # empty: every pair in the causal future, or every pair too far back
        empty = (k_lo > q_hi) | ((q_lo - k_hi) >= w)
        return np.where(full, FULL, np.where(empty, EMPTY, PARTIAL)).astype(np.uint8)


class DocumentMask(Mask):
    """Intra-document attention from STATIC per-token segment ids [s]:
    q attends k iff seg[q] == seg[k]. For monotone (packed, contiguous)
    ids the block status is analytic from per-block id ranges; arbitrary
    ids fall back to an exact blockwise comparison."""

    def __init__(self, segment_ids: Sequence[int]):
        ids = np.asarray(segment_ids)
        if ids.ndim != 1:
            raise ValueError(f"DocumentMask wants 1-D segment ids, got {ids.shape}")
        super().__init__((ids.shape[0], ids.shape[0]))
        self._ids = ids.astype(np.int32)

    @property
    def segment_ids(self) -> Optional[np.ndarray]:
        return self._ids

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        nq, nk = _block_grid(*self.shape, bq, bk)
        ids = self._ids
        if np.all(np.diff(ids) >= 0):
            q_min = ids.reshape(nq, bq).min(1)[:, None]
            q_max = ids.reshape(nq, bq).max(1)[:, None]
            k_min = ids.reshape(nk, bk).min(1)[None, :]
            k_max = ids.reshape(nk, bk).max(1)[None, :]
            full = (q_min == q_max) & (k_min == k_max) & (q_min == k_min)
            empty = (q_max < k_min) | (k_max < q_min)
            return np.where(full, FULL, np.where(empty, EMPTY, PARTIAL)).astype(np.uint8)
        # exact fallback, one block row at a time (avoids an s^2 temp)
        status = np.empty((nq, nk), np.uint8)
        ks = ids.reshape(nk, bk)
        for qi in range(nq):
            eq = ids[qi * bq:(qi + 1) * bq][None, :, None] == ks[:, None, :]
            status[qi] = np.where(eq.all((1, 2)), FULL,
                                  np.where(eq.any((1, 2)), PARTIAL, EMPTY))
        return status


class LayoutMask(Mask):
    """Block-granular layout from a SparsityConfig ``make_layout`` matrix
    [nq, nk] (single head). Blocks are all-or-nothing at the layout's own
    block size; the kernel block must equal it or divide it evenly."""

    def __init__(self, layout: np.ndarray, block: int):
        layout = np.asarray(layout)
        if layout.ndim != 2:
            raise ValueError(f"LayoutMask wants a single-head [nq, nk] layout, "
                             f"got {layout.shape}")
        super().__init__((layout.shape[0] * block, layout.shape[1] * block))
        self._layout = (layout != 0)
        self._block = int(block)

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        B = self._block
        if B % bq or B % bk:
            raise ValueError(
                f"kernel blocks ({bq}, {bk}) must divide the layout block {B}: "
                "a layout block is all-or-nothing at token level, so a coarser "
                "kernel block could not be classified full/partial")
        lay = np.repeat(np.repeat(self._layout, B // bq, 0), B // bk, 1)
        return np.where(lay, FULL, EMPTY).astype(np.uint8)

    def token_mask(self) -> np.ndarray:
        return np.repeat(np.repeat(self._layout, self._block, 0), self._block, 1)


class MaskAnd(Mask):
    """Intersection of two masks (same token shape)."""

    def __init__(self, a: Mask, b: Mask):
        if a.shape != b.shape:
            raise ValueError(f"mask shapes differ: {a.shape} vs {b.shape}")
        if a.segment_ids is not None and b.segment_ids is not None:
            raise ValueError("at most one mask in an intersection may carry "
                             "segment ids")
        super().__init__(a.shape)
        self._a, self._b = a, b

    @property
    def causal(self) -> bool:
        return self._a.causal or self._b.causal

    @property
    def window(self) -> int:
        ws = [m.window for m in (self._a, self._b) if m.window]
        return min(ws) if ws else 0

    @property
    def segment_ids(self) -> Optional[np.ndarray]:
        return self._a.segment_ids if self._a.segment_ids is not None else self._b.segment_ids

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        sa = self._a.block_status(bq, bk)
        sb = self._b.block_status(bq, bk)
        empty = (sa == EMPTY) | (sb == EMPTY)
        full = (sa == FULL) & (sb == FULL)
        return np.where(empty, EMPTY, np.where(full, FULL, PARTIAL)).astype(np.uint8)

    def token_mask(self) -> np.ndarray:
        return self._a.token_mask() & self._b.token_mask()


class MultiHeadMask:
    """Stack of per-head masks -> [h, nq, nk] status. All heads must agree
    on the analytic predicates (causal/window/segments are compiled into
    the kernel once); only the block layouts may differ per head."""

    def __init__(self, masks: Sequence[Mask]):
        if not masks:
            raise ValueError("MultiHeadMask needs at least one head mask")
        m0 = masks[0]
        for m in masks[1:]:
            if m.shape != m0.shape:
                raise ValueError("per-head masks must share the token shape")
            if (m.causal, m.window) != (m0.causal, m0.window):
                raise ValueError(
                    "per-head masks must share causal/window predicates (the "
                    "kernel compiles one predicate set; only layouts may vary)")
            sa, sb = m.segment_ids, m0.segment_ids
            if (sa is None) != (sb is None) or (
                    sa is not None and not np.array_equal(sa, sb)):
                raise ValueError("per-head masks must share segment ids")
        self.masks: List[Mask] = list(masks)
        self.shape = m0.shape

    @property
    def causal(self) -> bool:
        return self.masks[0].causal

    @property
    def window(self) -> int:
        return self.masks[0].window

    @property
    def segment_ids(self) -> Optional[np.ndarray]:
        return self.masks[0].segment_ids

    def block_status(self, bq: int, bk: int) -> np.ndarray:
        return np.stack([m.block_status(bq, bk) for m in self.masks])

    def token_mask(self) -> np.ndarray:
        return np.stack([m.token_mask() for m in self.masks])
