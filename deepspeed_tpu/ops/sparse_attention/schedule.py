"""Compacted block schedules: what the splash kernel actually iterates.

``build_schedule`` turns a [h, nq, nk] block-status matrix (mask.py) into
the scalar-prefetch arrays the scheduled kernel consumes:

  * ``kv_index[h, nq, width]``  — for each q block, the kv-block indices it
    visits, compacted left (EMPTY blocks are simply absent — never a grid
    step, never an HBM stream).  ``width`` is the max active count over all
    rows, so the fwd grid is (b, h, nq, width): it scales with the layout's
    densest row, not with nk.
  * ``step_kind[h, nq, width]`` — {0 skip, 1 partial, 2 full} per step.
    Padding steps are ``skip`` and their kv_index REPEATS the row's last
    real index, so the BlockSpec index map emits the same block twice and
    Pallas elides the copy: a padded step costs neither DMA nor FLOPs.
  * the transposed pair ``q_index`` / ``step_kind_t`` [h, nk, width_t] for
    the dk/dv backward (per kv block: which q blocks touch it).

Everything here is numpy at trace time: the schedule is a compile-time
constant of the step program — there is no per-step host rebuild.
"""

from dataclasses import dataclass
from typing import Optional, Tuple, Union

import numpy as np

from deepspeed_tpu.ops.sparse_attention.mask import (
    EMPTY, FULL, PARTIAL, LayoutMask, Mask, MaskAnd, MultiHeadMask,
)


def _compact(status: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[h, rows, cols] status -> (index [h, rows, width], kind [h, rows, width])."""
    h, rows, cols = status.shape
    width = max(1, int((status != EMPTY).sum(-1).max()))
    index = np.zeros((h, rows, width), np.int32)
    kind = np.zeros((h, rows, width), np.int32)
    for hi in range(h):
        for r in range(rows):
            (act,) = np.nonzero(status[hi, r])
            n = act.size
            if n:
                index[hi, r, :n] = act
                kind[hi, r, :n] = status[hi, r, act]
                index[hi, r, n:] = act[-1]  # repeat -> copy elided on pad steps
            # rows with no active block keep index 0 / kind 0: the kernel
            # still inits and flushes, emitting zeros for dead rows
    return index, kind


@dataclass(frozen=True, eq=False)  # identity hash: usable as a nondiff arg
class BlockSchedule:
    """Trace-time-constant schedule for one (mask, block-size) pairing."""

    seq_q: int
    seq_kv: int
    block_q: int
    block_kv: int
    causal: bool
    window: int                       # 0 = no band predicate
    segment_ids: Optional[np.ndarray]  # static ids baked into the schedule
    kv_index: np.ndarray              # [h, nq, width] int32
    step_kind: np.ndarray             # [h, nq, width] int32
    q_index: np.ndarray               # [h, nk, width_t] int32
    step_kind_t: np.ndarray           # [h, nk, width_t] int32

    @property
    def num_heads(self) -> int:
        return self.kv_index.shape[0]

    @property
    def nq(self) -> int:
        return self.kv_index.shape[1]

    @property
    def nk(self) -> int:
        return self.q_index.shape[1]

    @property
    def grid_width(self) -> int:
        """Minor fwd grid dimension: max active kv blocks over any q row."""
        return self.kv_index.shape[2]

    @property
    def grid_width_t(self) -> int:
        return self.q_index.shape[2]

    @property
    def num_active(self) -> int:
        """Total scheduled (non-skip) fwd steps across heads."""
        return int((self.step_kind != EMPTY).sum())

    @property
    def num_partial(self) -> int:
        return int((self.step_kind == PARTIAL).sum())

    @property
    def density(self) -> float:
        return self.num_active / float(self.num_heads * self.nq * self.nk)


def build_schedule(status: np.ndarray, *, seq_q: int, seq_kv: int,
                   block_q: int, block_kv: int, causal: bool = False,
                   window: int = 0,
                   segment_ids: Optional[np.ndarray] = None) -> BlockSchedule:
    """Compact a [h, nq, nk] (or [nq, nk]) status matrix into a schedule."""
    status = np.asarray(status)
    if status.ndim == 2:
        status = status[None]
    h, nq, nk = status.shape
    if nq != seq_q // block_q or nk != seq_kv // block_kv:
        raise ValueError(f"status grid {status.shape[1:]} != "
                         f"{(seq_q // block_q, seq_kv // block_kv)}")
    kv_index, step_kind = _compact(status)
    q_index, step_kind_t = _compact(np.swapaxes(status, 1, 2))
    return BlockSchedule(
        seq_q=seq_q, seq_kv=seq_kv, block_q=block_q, block_kv=block_kv,
        causal=bool(causal), window=int(window), segment_ids=segment_ids,
        kv_index=kv_index, step_kind=step_kind,
        q_index=q_index, step_kind_t=step_kind_t,
    )


def schedule_from_mask(mask: Union[Mask, MultiHeadMask], block_q: int,
                       block_kv: Optional[int] = None) -> BlockSchedule:
    """Compile a mask (mask.py) into its compacted schedule."""
    block_kv = block_kv or block_q
    status = mask.block_status(block_q, block_kv)
    if status.ndim == 2:
        status = status[None]
    sq, sk = mask.shape
    return build_schedule(
        status, seq_q=sq, seq_kv=sk, block_q=block_q, block_kv=block_kv,
        causal=mask.causal, window=mask.window, segment_ids=mask.segment_ids,
    )


def schedule_from_layout(layout: np.ndarray, block: int, causal: bool = False,
                         block_q: Optional[int] = None,
                         block_kv: Optional[int] = None) -> BlockSchedule:
    """Route a SparsityConfig ``make_layout`` matrix [h, nq, nk] through the
    schedule builder: layout blocks become FULL/EMPTY status, optionally
    intersected with the causal predicate (which demotes diagonal blocks to
    PARTIAL and prunes the strict upper triangle entirely)."""
    layout = np.asarray(layout)
    if layout.ndim == 2:
        layout = layout[None]
    bq = block_q or block
    bk = block_kv or block
    heads = []
    for hl in layout:
        m: Mask = LayoutMask(hl, block)
        if causal:
            m = MaskAnd(m, _causal_for(m.shape))
        heads.append(m)
    return schedule_from_mask(MultiHeadMask(heads), bq, bk)


def _causal_for(shape):
    from deepspeed_tpu.ops.sparse_attention.mask import CausalMask

    return CausalMask(shape)
