"""Splash-style scheduled block-sparse flash attention (fwd + bwd).

Where sparse_pallas.py (kept as the ``reference`` oracle) iterates EVERY
kv block and skips inactive ones under ``lax.cond`` — paying a grid step
and an HBM stream per masked block — this kernel iterates a compacted
schedule (schedule.py): the fwd grid is ``(b, h, nq, width)`` with
``width`` = the densest row's active-block count, and a scalar-prefetched
``kv_index`` array drives the K/V BlockSpec index maps. A fully-masked
block is never scheduled, never streamed; cost scales with layout
density, not s².

Per-step ``step_kind`` ∈ {0 skip, 1 partial, 2 full}:
  * skip — padding up to ``width``; kv_index repeats the previous block so
    the index map output is unchanged and Pallas elides the copy;
  * partial — the analytic token predicate (causal edge / window band /
    segment equality) is applied in-kernel;
  * full — no mask application at all. When a schedule has zero partial
    steps the masking code is not even compiled (``has_partial`` is
    static).

K/V are streamed one ``[bk, d]`` block per grid step — there is no
full-K/V VMEM residency, which is also what lets the dense-causal s≥16k
configuration fit (the CausalMask schedule IS the dense long-seq path);
``vmem_limit_bytes`` caps the compiler's scoped-vmem budget per kernel.

Backward runs the same machinery: dq over the row schedule, dk/dv over
the transposed (per-kv-block) schedule, GQA group-reduced like
flash_pallas.
"""

import dataclasses
import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepspeed_tpu.ops.sparse_attention.mask import FULL
from deepspeed_tpu.ops.sparse_attention.schedule import BlockSchedule

NEG_INF = -1e30
LANES = 128


def _default_vmem_limit() -> Optional[int]:
    mb = int(os.environ.get("DSTPU_SPLASH_VMEM_MB", "128"))
    return mb << 20 if mb > 0 else None


@dataclasses.dataclass(frozen=True)
class _SplashParams:
    """Static kernel configuration — hashable, so one compiled program per
    distinct config (custom_vjp nondiff arg)."""

    bq: int
    bk: int
    causal: bool
    window: int
    scale: float
    has_partial: bool   # False -> mask code is not compiled at all
    seg_mode: str       # 'none' | 'schedule' (partial steps) | 'all' (every step)
    interpret: bool
    vmem_limit: Optional[int]


def _compiler_kwargs(params: _SplashParams):
    if params.interpret:
        return {}
    return {
        "compiler_params": pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=params.vmem_limit,
        )
    }


def _partial_mask(logits, kind, q_pos, k_pos, segq_ref, segk_ref, params):
    """Mask for PARTIAL steps. FULL steps pass through untouched at run
    time; when the schedule holds no partial step this is never called."""
    keep = None

    def _and(a, b):
        return b if a is None else jnp.logical_and(a, b)

    if params.causal:
        keep = _and(keep, q_pos >= k_pos)
    if params.window:
        # THE shared band convention (core.window_too_far): out iff q-k >= w
        keep = _and(keep, (q_pos - k_pos) < params.window)
    if params.seg_mode == "schedule":
        keep = _and(keep, segq_ref[:][:, None] == segk_ref[:][None, :])
    if keep is None:
        return logits
    return jnp.where(jnp.logical_or(kind == FULL, keep), logits, NEG_INF)


def _splash_fwd_kernel(kvi_ref, kind_ref, base_ref, *refs, params, hs_shared,
                       width):
    if params.seg_mode != "none":
        q_ref, k_ref, v_ref, segq_ref, segk_ref = refs[:5]
        rest = refs[5:]
        segq_ref, segk_ref = segq_ref.at[0], segk_ref.at[0]
    else:
        q_ref, k_ref, v_ref = refs[:3]
        segq_ref = segk_ref = None
        rest = refs[3:]
    o_ref, lse_ref, m_sc, l_sc, acc_sc = rest
    q_ref, k_ref, v_ref = q_ref.at[0, 0], k_ref.at[0, 0], v_ref.at[0, 0]
    o_ref, lse_ref = o_ref.at[0, 0], lse_ref.at[0, 0]

    h_ = pl.program_id(1)
    i = pl.program_id(2)
    j = pl.program_id(3)
    hs = 0 if hs_shared else h_
    kind = kind_ref[hs, i, j]
    bq, bk = params.bq, params.bk

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    @pl.when(kind > 0)
    def _step():
        q = q_ref[:].astype(jnp.float32) * params.scale
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [bq, bk]
        if params.has_partial:
            q_pos = base_ref[0] + i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = kvi_ref[hs, i, j] * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            logits = _partial_mask(logits, kind, q_pos, k_pos,
                                   segq_ref, segk_ref, params)
        if params.seg_mode == "all":
            # traced ids the schedule knows nothing about: every step masks
            logits = jnp.where(
                segq_ref[:][:, None] == segk_ref[:][None, :], logits, NEG_INF)
        m = m_sc[:, 0]
        l = l_sc[:, 0]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        # guard: a row whose every visited logit is masked must emit zeros,
        # not exp(NEG_INF - NEG_INF) = 1 garbage
        p = jnp.where(logits > NEG_INF / 2,
                      jnp.exp(logits - m_new[:, None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_sc[:] = acc_sc[:] * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_sc[:] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new[:, None], l_sc.shape)

    @pl.when(j == width - 1)
    def _flush():
        l_safe = jnp.maximum(l_sc[:, 0], 1e-30)
        o_ref[:] = (acc_sc[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[:] = jnp.broadcast_to(
            (m_sc[:, 0] + jnp.log(l_safe))[:, None], (bq, LANES))


def _splash_bwd_dq_kernel(kvi_ref, kind_ref, base_ref, *refs, params,
                          hs_shared, width):
    if params.seg_mode != "none":
        q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, segq_ref, segk_ref = refs[:8]
        rest = refs[8:]
        segq_ref, segk_ref = segq_ref.at[0], segk_ref.at[0]
    else:
        q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = refs[:6]
        segq_ref = segk_ref = None
        rest = refs[6:]
    dq_ref, dq_acc, delta_sc = rest
    q_ref, k_ref, v_ref = q_ref.at[0, 0], k_ref.at[0, 0], v_ref.at[0, 0]
    o_ref, do_ref, lse_ref = o_ref.at[0, 0], do_ref.at[0, 0], lse_ref.at[0, 0]
    dq_ref = dq_ref.at[0, 0]

    h_ = pl.program_id(1)
    i = pl.program_id(2)
    j = pl.program_id(3)
    hs = 0 if hs_shared else h_
    kind = kind_ref[hs, i, j]
    bq, bk = params.bq, params.bk

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)
        delta = jnp.sum(
            do_ref[:].astype(jnp.float32) * o_ref[:].astype(jnp.float32),
            axis=-1)
        delta_sc[:] = jnp.broadcast_to(delta[:, None], delta_sc.shape)

    @pl.when(kind > 0)
    def _step():
        q = q_ref[:].astype(jnp.float32) * params.scale
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if params.has_partial:
            q_pos = base_ref[0] + i * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0)
            k_pos = kvi_ref[hs, i, j] * bk + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 1)
            logits = _partial_mask(logits, kind, q_pos, k_pos,
                                   segq_ref, segk_ref, params)
        if params.seg_mode == "all":
            logits = jnp.where(
                segq_ref[:][:, None] == segk_ref[:][None, :], logits, NEG_INF)
        p = jnp.where(logits > NEG_INF / 2,
                      jnp.exp(logits - lse[:, None]), 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_sc[:, 0][:, None])
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == width - 1)
    def _flush():
        dq_ref[:] = (dq_acc[:] * params.scale).astype(dq_ref.dtype)


def _splash_bwd_dkv_kernel(qi_ref, kind_ref, base_ref, *refs, params,
                           hs_shared, width):
    if params.seg_mode != "none":
        q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, segq_ref, segk_ref = refs[:8]
        rest = refs[8:]
        segq_ref, segk_ref = segq_ref.at[0], segk_ref.at[0]
    else:
        q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref = refs[:6]
        segq_ref = segk_ref = None
        rest = refs[6:]
    dk_ref, dv_ref, dk_acc, dv_acc = rest
    q_ref, k_ref, v_ref = q_ref.at[0, 0], k_ref.at[0, 0], v_ref.at[0, 0]
    o_ref, do_ref, lse_ref = o_ref.at[0, 0], do_ref.at[0, 0], lse_ref.at[0, 0]
    dk_ref, dv_ref = dk_ref.at[0, 0], dv_ref.at[0, 0]

    h_ = pl.program_id(1)
    i = pl.program_id(2)   # kv block
    j = pl.program_id(3)   # schedule step over q blocks
    hs = 0 if hs_shared else h_
    kind = kind_ref[hs, i, j]
    bq, bk = params.bq, params.bk

    @pl.when(j == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(kind > 0)
    def _step():
        q = q_ref[:].astype(jnp.float32) * params.scale
        k = k_ref[:].astype(jnp.float32)
        v = v_ref[:].astype(jnp.float32)
        do = do_ref[:].astype(jnp.float32)
        o = o_ref[:].astype(jnp.float32)
        lse = lse_ref[:, 0]
        delta = jnp.sum(do * o, axis=-1)  # [bq]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        if params.has_partial:
            q_pos = base_ref[0] + qi_ref[hs, i, j] * bq + \
                jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = i * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = _partial_mask(logits, kind, q_pos, k_pos,
                                   segq_ref, segk_ref, params)
        if params.seg_mode == "all":
            logits = jnp.where(
                segq_ref[:][:, None] == segk_ref[:][None, :], logits, NEG_INF)
        p = jnp.where(logits > NEG_INF / 2,
                      jnp.exp(logits - lse[:, None]), 0.0)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None])
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(j == width - 1)
    def _flush():
        # q was pre-scaled, so ds already carries one factor of scale; dk
        # needs dlogits/dk = scale * q_raw = the pre-scaled q — nothing more
        dk_ref[:] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[:] = dv_acc[:].astype(dv_ref.dtype)


def _seg_ops_specs(seg, bq, q_map, bk, k_map):
    """Segment-id operands + specs ([b, s] planes, streamed per block)."""
    if seg is None:
        return [], []
    ops = [seg, seg]
    specs = [pl.BlockSpec((1, bq), q_map), pl.BlockSpec((1, bk), k_map)]
    return ops, specs


def _splash_fwd_call(q, k, v, seg, kvi, kind, base, params: _SplashParams):
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    bq, bk = params.bq, params.bk
    nq, width = kvi.shape[1], kvi.shape[2]
    hs_shared = kvi.shape[0] == 1

    def hsi(h_):
        return 0 if hs_shared else h_

    qm = lambda b_, h_, i, j, kvi_, kind_, base_: (b_, h_, i, 0)
    km = lambda b_, h_, i, j, kvi_, kind_, base_: (
        b_, h_ // group, kvi_[hsi(h_), i, j], 0)
    seg_ops, seg_specs = _seg_ops_specs(
        seg, bq, lambda b_, h_, i, j, kvi_, kind_, base_: (b_, i),
        bk, lambda b_, h_, i, j, kvi_, kind_, base_: (b_, kvi_[hsi(h_), i, j]))

    kernel = functools.partial(
        _splash_fwd_kernel, params=params, hs_shared=hs_shared, width=width)
    out, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, h, nq, width),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qm),
                pl.BlockSpec((1, 1, bk, d), km),
                pl.BlockSpec((1, 1, bk, d), km),
                *seg_specs,
            ],
            out_specs=[
                pl.BlockSpec((1, 1, bq, d), qm),
                pl.BlockSpec((1, 1, bq, LANES), qm),
            ],
            scratch_shapes=[
                pltpu.VMEM((bq, LANES), jnp.float32),
                pltpu.VMEM((bq, LANES), jnp.float32),
                pltpu.VMEM((bq, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, LANES), jnp.float32),
        ],
        interpret=params.interpret,
        **_compiler_kwargs(params),
    )(kvi, kind, base, q, k, v, *seg_ops)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(9,))
def _splash_core(q, k, v, seg, kvi, kind, kvi_t, kind_t, base, params):
    out, _ = _splash_vjp_fwd(q, k, v, seg, kvi, kind, kvi_t, kind_t, base,
                             params)
    return out


def _splash_vjp_fwd(q, k, v, seg, kvi, kind, kvi_t, kind_t, base, params):
    out, lse = _splash_fwd_call(q, k, v, seg, kvi, kind, base, params)
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    q = checkpoint_name(q, "flash_qkv")
    k = checkpoint_name(k, "flash_qkv")
    v = checkpoint_name(v, "flash_qkv")
    return out, (q, k, v, seg, kvi, kind, kvi_t, kind_t, base, out, lse)


def _splash_vjp_bwd(params: _SplashParams, res, g):
    q, k, v, seg, kvi, kind, kvi_t, kind_t, base, out, lse = res
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    bq, bk = params.bq, params.bk
    nq, width = kvi.shape[1], kvi.shape[2]
    nk, width_t = kvi_t.shape[1], kvi_t.shape[2]
    hs_shared = kvi.shape[0] == 1

    def hsi(h_):
        return 0 if hs_shared else h_

    # ---- dq: row schedule, same grid as forward
    qm = lambda b_, h_, i, j, kvi_, kind_, base_: (b_, h_, i, 0)
    km = lambda b_, h_, i, j, kvi_, kind_, base_: (
        b_, h_ // group, kvi_[hsi(h_), i, j], 0)
    seg_ops, seg_specs = _seg_ops_specs(
        seg, bq, lambda b_, h_, i, j, kvi_, kind_, base_: (b_, i),
        bk, lambda b_, h_, i, j, kvi_, kind_, base_: (b_, kvi_[hsi(h_), i, j]))
    dq_kernel = functools.partial(
        _splash_bwd_dq_kernel, params=params, hs_shared=hs_shared, width=width)
    dq = pl.pallas_call(
        dq_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, h, nq, width),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qm),
                pl.BlockSpec((1, 1, bk, d), km),
                pl.BlockSpec((1, 1, bk, d), km),
                pl.BlockSpec((1, 1, bq, d), qm),
                pl.BlockSpec((1, 1, bq, d), qm),
                pl.BlockSpec((1, 1, bq, LANES), qm),
                *seg_specs,
            ],
            out_specs=pl.BlockSpec((1, 1, bq, d), qm),
            scratch_shapes=[
                pltpu.VMEM((bq, d), jnp.float32),
                pltpu.VMEM((bq, LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=params.interpret,
        **_compiler_kwargs(params),
    )(kvi, kind, base, q, k, v, out, g, lse, *seg_ops)

    # ---- dk/dv: transposed schedule — per kv block, visit the q blocks
    # that touch it. Output is per q head; GQA group-reduces below.
    qm_t = lambda b_, h_, i, j, qi_, kind_, base_: (
        b_, h_, qi_[hsi(h_), i, j], 0)
    km_t = lambda b_, h_, i, j, qi_, kind_, base_: (b_, h_ // group, i, 0)
    om_t = lambda b_, h_, i, j, qi_, kind_, base_: (b_, h_, i, 0)
    seg_ops_t, seg_specs_t = _seg_ops_specs(
        seg, bq, lambda b_, h_, i, j, qi_, kind_, base_: (b_, qi_[hsi(h_), i, j]),
        bk, lambda b_, h_, i, j, qi_, kind_, base_: (b_, i))
    dkv_kernel = functools.partial(
        _splash_bwd_dkv_kernel, params=params, hs_shared=hs_shared,
        width=width_t)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(b, h, nk, width_t),
            in_specs=[
                pl.BlockSpec((1, 1, bq, d), qm_t),
                pl.BlockSpec((1, 1, bk, d), km_t),
                pl.BlockSpec((1, 1, bk, d), km_t),
                pl.BlockSpec((1, 1, bq, d), qm_t),
                pl.BlockSpec((1, 1, bq, d), qm_t),
                pl.BlockSpec((1, 1, bq, LANES), qm_t),
                *seg_specs_t,
            ],
            out_specs=[
                pl.BlockSpec((1, 1, bk, d), om_t),
                pl.BlockSpec((1, 1, bk, d), om_t),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, d), jnp.float32),
                pltpu.VMEM((bk, d), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), q.dtype),
        ],
        interpret=params.interpret,
        **_compiler_kwargs(params),
    )(kvi_t, kind_t, base, q, k, v, out, g, lse, *seg_ops_t)
    if group > 1:
        dk = dk.reshape(b, h_kv, group, sk, d).sum(2).astype(k.dtype)
        dv = dv.reshape(b, h_kv, group, sk, d).sum(2).astype(v.dtype)
    return dq, dk, dv, None, None, None, None, None, None


_splash_core.defvjp(_splash_vjp_fwd, _splash_vjp_bwd)


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def splash_attention(q, k, v, schedule: BlockSchedule, *,
                     segment_ids=None, scale: Optional[float] = None,
                     interpret: Optional[bool] = None,
                     vmem_limit_bytes: Optional[int] = None):
    """Scheduled block-sparse attention. q: [b, h, sq, d]; k/v:
    [b, h_kv, sk, d] (GQA handled in the index maps — kv is NEVER
    replicated in HBM). ``schedule`` is a trace-time-constant
    BlockSchedule (schedule.py); its arrays become scalar-prefetch
    operands, so the compiled grid is (b, h, nq, width).

    ``segment_ids`` ([b, s] int32, may be traced): when the schedule was
    built WITHOUT segment pruning (DocumentMask absent), the predicate is
    applied on every scheduled step; when the schedule already carries
    static ids, they mask partial steps only. Differentiable (custom_vjp).
    """
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    if h % h_kv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {h_kv}")
    if (schedule.seq_q, schedule.seq_kv) != (sq, sk):
        raise ValueError(f"schedule is for seq {(schedule.seq_q, schedule.seq_kv)}, "
                         f"got {(sq, sk)}")
    if schedule.num_heads not in (1, h):
        raise ValueError(f"schedule has {schedule.num_heads} heads, q has {h}")
    seg_mode = "none"
    seg = None
    if schedule.segment_ids is not None:
        if sq != sk:
            raise ValueError("segment masking requires square attention")
        seg_mode = "schedule"
        seg = jnp.broadcast_to(
            jnp.asarray(schedule.segment_ids, jnp.int32)[None], (b, sq))
        if segment_ids is not None:
            raise ValueError("schedule already carries segment ids; passing "
                             "runtime segment_ids too would silently compose")
    elif segment_ids is not None:
        if sq != sk:
            raise ValueError("segment masking requires square attention")
        seg_mode = "all"
        seg = jnp.asarray(segment_ids, jnp.int32)
    params = _SplashParams(
        bq=schedule.block_q, bk=schedule.block_kv,
        causal=schedule.causal, window=schedule.window,
        scale=float(scale if scale is not None else d ** -0.5),
        has_partial=schedule.num_partial > 0,
        seg_mode=seg_mode,
        interpret=_auto_interpret(interpret),
        vmem_limit=(vmem_limit_bytes if vmem_limit_bytes is not None
                    else _default_vmem_limit()),
    )
    kvi = jnp.asarray(schedule.kv_index)
    kind = jnp.asarray(schedule.step_kind)
    kvi_t = jnp.asarray(schedule.q_index)
    kind_t = jnp.asarray(schedule.step_kind_t)
    base = jnp.zeros((1,), jnp.int32)
    return _splash_core(q, k, v, seg, kvi, kind, kvi_t, kind_t, base, params)


def splash_prefill_attention(q, k, v, start, *, window: int = 0,
                             block_kv: int, scale: Optional[float] = None,
                             interpret: Optional[bool] = None,
                             vmem_limit_bytes: Optional[int] = None):
    """Forward-only scheduled attention for serving chunked prefill.

    ``q`` is one [b, h, t, d] chunk whose rows sit at global positions
    ``start .. start+t-1`` (``start`` a traced int32 scalar); k/v are the
    gathered paged context [b, h_kv, S, d] at positions 0..S-1. Causal,
    plus an optional sliding-window band. The schedule is computed IN-JIT
    from ``start`` — scalar-prefetch operands are ordinary arrays, so one
    compiled program serves every chunk position (no host rebuild) while
    the kernel still visits only ~(window + t)/block_kv blocks instead of
    all S/block_kv.
    """
    b, h, t, d = q.shape
    S = k.shape[2]
    if S % block_kv:
        raise ValueError(f"context length {S} not divisible by block_kv {block_kv}")
    nk = S // block_kv
    if window:
        width = min(nk, (t + window - 2) // block_kv + 2)
    else:
        width = nk
    start = jnp.asarray(start, jnp.int32)
    hi = start + t - 1                    # last q position in the chunk
    last = hi // block_kv                 # last kv block any row attends
    if window:
        first = jnp.maximum(start - (window - 1), 0) // block_kv
    else:
        first = jnp.zeros((), jnp.int32)
    idx = first + jnp.arange(width, dtype=jnp.int32)      # candidate blocks
    k_lo = idx * block_kv
    k_hi = k_lo + block_kv - 1
    in_range = idx <= last
    if window:
        full = (k_hi <= start) & ((hi - k_lo) < window)
        empty = ~in_range | ((start - k_hi) >= window)
    else:
        full = k_hi <= start
        empty = ~in_range
    kind = jnp.where(empty, 0, jnp.where(full, FULL, 1)).astype(jnp.int32)
    # clamp padding steps to the last active block -> copy elided
    kvi = jnp.clip(idx, 0, jnp.maximum(last, 0)).astype(jnp.int32)
    params = _SplashParams(
        bq=t, bk=block_kv, causal=True, window=int(window),
        scale=float(scale if scale is not None else d ** -0.5),
        has_partial=True, seg_mode="none",
        interpret=_auto_interpret(interpret),
        vmem_limit=(vmem_limit_bytes if vmem_limit_bytes is not None
                    else _default_vmem_limit()),
    )
    out, _ = _splash_fwd_call(
        q, k, v, None,
        kvi.reshape(1, 1, width), kind.reshape(1, 1, width),
        start.reshape(1), params)
    return out
