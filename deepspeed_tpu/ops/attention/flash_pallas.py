"""Flash attention as a Pallas TPU kernel (forward + backward), splash-style.

The TPU-native replacement for the reference's fused attention kernels
(csrc/transformer/inference softmax/attention ops, evoformer_attn CUTLASS
kernels, blocked_flash in inference/v2/kernels/ragged_ops/blocked_flash):
online-softmax tiling so the [s, s] score matrix never materializes in HBM.

Design (round 3: kv-pipelined — nothing sequence-length-sized is ever VMEM
resident, lifting the former ~8k dense cap):
  * Layout [b, h, s, d]. Forward grid (b, h, nq, nk) with the kv block index
    minor: each program sees one [bq, d] q block and one [bk, d] k/v block;
    Pallas double-buffers the next kv block's HBM→VMEM copy behind the
    current block's MXU work. Softmax state (m, l) and the output
    accumulator live in VMEM scratch carried across the kv iterations; the
    output block is written once on the last iteration.
  * Causal pruning: masked (q, kv) grid points clamp their kv index map to
    the last active block — Pallas elides the copy when the block index is
    unchanged — and skip compute under ``pl.when``. Cost of a pruned point
    is grid overhead only, preserving the ~2× causal win.
  * fp32 accumulators; the MXU sees bf16 inputs with
    ``preferred_element_type=jnp.float32``.
  * LSE is stored lane-broadcast as [b, h, s, LANES] to satisfy the TPU
    (8, 128) tiling rule for output blocks.
  * Backward: flash recompute — per-block p = exp(qk·scale − lse). dq
    streams kv blocks (grid (b, h, nq, nk)); dk/dv streams q/do/o/lse
    blocks (grid (b, h, nk, nq)); both carry fp32 scratch accumulators.
    delta = Σ do·o is computed in-kernel from the saved output.
  * GQA: kv-head index map h → h // (nh/nkv); no head replication in HBM.

Numerics validated against ops.attention.mha_reference in
tests/unit/ops/test_flash_attention.py (interpret mode on CPU), including a
16k-sequence dense case no longer possible with whole-K/V residency.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _alibi_term(alibi_ref, kpos_ref):
    """ALiBi additive logits term for one block: ``slope_h * key_position``
    (HF bloom's absolute-position convention — softmax-equivalent to the
    relative form under causal masking). alibi_ref: [1, LANES] slope plane
    for this head; kpos_ref: [bk] int32 key positions."""
    return alibi_ref[0, 0] * kpos_ref[:].astype(jnp.float32)[None, :]


def _apply_window(logits, window, wflag_ref, q_pos, k_pos):
    """Sliding-window band mask: query sees keys in (q - window, q]. With a
    ``wflag_ref`` ([1, LANES] int32 plane, traced per layer from
    attn_layer_pattern) the band only applies when the flag is set — the
    layer scan stays uniform while layers alternate local/global (gpt_neo).
    The band convention is the shared ``core.window_too_far``."""
    from deepspeed_tpu.ops.attention.core import window_too_far

    far = window_too_far(
        q_pos, k_pos, window, wflag_ref[0, 0] if wflag_ref is not None else None
    )
    return jnp.where(far, NEG_INF, logits)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                scale, causal, bq, bk, nk, window=0, seg_q_ref=None,
                seg_k_ref=None, alibi_ref=None, kpos_ref=None, wflag_ref=None):
    # q_ref: [bq, d]; k_ref/v_ref: [bk, d] (one streamed block);
    # o_ref: [bq, d]; lse_ref: [bq, LANES]; scratch m/l: [bq, LANES] f32,
    # acc: [bq, d] f32 — carried across the minor (kv) grid dimension.
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    hi = (qi * bq + bq - 1) // bk  # last kv block a causal q block touches
    active = (ki <= hi) if causal else (ki >= 0)
    if window and wflag_ref is None:
        # static window (every layer banded): prune kv blocks fully behind it
        active = jnp.logical_and(active, ki >= jnp.maximum(0, qi * bq - window + 1) // bk)

    @pl.when(active)
    def _step():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk] fp32
        if alibi_ref is not None:
            logits = logits + _alibi_term(alibi_ref, kpos_ref)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
            if window:
                logits = _apply_window(logits, window, wflag_ref, q_pos, k_pos)
        if seg_q_ref is not None:
            logits = jnp.where(
                seg_q_ref[:][:, None] == seg_k_ref[:][None, :], logits, NEG_INF
            )
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == nk - 1)
    def _flush():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[:] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[:] = jnp.broadcast_to(
            (m_ref[:, 0] + jnp.log(l_safe))[:, None], (bq, LANES)
        )


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
                   delta_ref, dq_acc_ref, *, scale, causal, bq, bk, nk,
                   window=0, seg_q_ref=None, seg_k_ref=None, alibi_ref=None,
                   kpos_ref=None, wflag_ref=None):
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)
        delta = jnp.sum(
            do_ref[:].astype(jnp.float32) * o_ref[:].astype(jnp.float32), axis=-1
        )
        delta_ref[:] = jnp.broadcast_to(delta[:, None], delta_ref.shape)

    hi = (qi * bq + bq - 1) // bk
    active = (ki <= hi) if causal else (ki >= 0)
    if window and wflag_ref is None:
        active = jnp.logical_and(active, ki >= jnp.maximum(0, qi * bq - window + 1) // bk)

    @pl.when(active)
    def _step():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if alibi_ref is not None:
            logits = logits + _alibi_term(alibi_ref, kpos_ref)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
            if window:
                logits = _apply_window(logits, window, wflag_ref, q_pos, k_pos)
        if seg_q_ref is not None:
            logits = jnp.where(
                seg_q_ref[:][:, None] == seg_k_ref[:][None, :], logits, NEG_INF
            )
        p = jnp.exp(logits - lse[:, None])  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[:, 0][:, None])  # [bq, bk]
        dq_acc_ref[:] = dq_acc_ref[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _flush():
        dq_ref[:] = (dq_acc_ref[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref,
                    dv_ref, dk_acc_ref, dv_acc_ref, *, scale, causal, bq, bk,
                    nq, window=0, seg_q_ref=None, seg_k_ref=None,
                    alibi_ref=None, kpos_ref=None, wflag_ref=None):
    ki = pl.program_id(2)
    qj = pl.program_id(3)

    @pl.when(qj == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    lo = (ki * bk) // bq  # first q block that sees this kv block
    active = (qj >= lo) if causal else (qj >= 0)
    if window and wflag_ref is None:
        # last q block inside the band for this kv block
        active = jnp.logical_and(active, qj <= (ki * bk + bk - 1 + window - 1) // bq)

    @pl.when(active)
    def _step():
        k = k_ref[:]
        v = v_ref[:]
        q = q_ref[:]
        do = do_ref[:]
        o = o_ref[:]
        lse = lse_ref[:, 0]
        # delta is recomputed per (kv, q) grid point: one [bq, d] VPU reduce
        # (~0.05% of the two MXU matmuls below) — cheaper than a separate
        # preprocess kernel or an HBM round-trip for [b, h, s] deltas.
        delta = jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
        )  # [bq]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if alibi_ref is not None:
            logits = logits + _alibi_term(alibi_ref, kpos_ref)
        if causal:
            q_pos = qj * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
            if window:
                logits = _apply_window(logits, window, wflag_ref, q_pos, k_pos)
        if seg_q_ref is not None:
            logits = jnp.where(
                seg_q_ref[:][:, None] == seg_k_ref[:][None, :], logits, NEG_INF
            )
        p = jnp.exp(logits - lse[:, None])
        dv_acc_ref[:] = dv_acc_ref[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dk_acc_ref[:] = dk_acc_ref[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qj == nq - 1)
    def _flush():
        # scale moved onto the logits, so dk picks it up (dlogits/dk = scale*q)
        dk_ref[:] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
        dv_ref[:] = dv_acc_ref[:].astype(dv_ref.dtype)


def _pick_block(s, target=None):
    """Largest power-of-two block ≤ target dividing s. The default block is
    env-tunable (DSTPU_FLASH_BLOCK) for per-generation retuning; with the
    kv-pipelined kernel 1024 measured best on v5e at s=2048 (fwd+bwd 5.75 ms
    vs 6.93 at 512, 10.7 at 256; 2048 exceeds the 16M scoped-vmem limit)."""
    if target is None:
        import os

        target = int(os.environ.get("DSTPU_FLASH_BLOCK", 1024))
        if target < 128 or target & (target - 1):
            raise ValueError(
                f"DSTPU_FLASH_BLOCK={target} invalid: need a power of two >= 128"
            )
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids=None,
    scale: Optional[float] = None,
    interpret: bool = False,
    alibi_slopes=None,
    alibi_positions=None,
    window: int = 0,
    window_flag=None,
) -> jax.Array:
    """Flash attention. q: [b, h, s, d]; k, v: [b, h_kv, s, d] → [b, h, s, d].

    ``segment_ids``: optional [b, s] int32 — packed-sequence masking happens
    IN the kernel (tokens attend only within their own segment), so packed
    pretraining keeps the flash path.

    ``alibi_slopes``: optional [h] fp32 — bloom-style ALiBi folds into the
    kernel as ``slope_h * key_position`` added to the logits (rank-1, so the
    [s, s] bias never materializes; the review of round 4 found alibi
    silently dropping to the O(s²)-HBM reference path). ``alibi_positions``
    ([b, s] or [s] int32) supplies the key positions; defaults to arange.
    Slopes are constants (non-learned) — no cotangent.

    ``window``: static sliding-window size — query i sees keys in
    (i - window, i] (mistral/starcoder2/gpt_neo). With ``window_flag`` None
    every layer is banded and out-of-band kv BLOCKS are pruned from the grid
    (compute and copies drop to O(s·window)); with ``window_flag`` (a traced
    0/1 scalar from attn_layer_pattern) the band toggles per layer via
    in-kernel masking (full causal grid, flash memory). Requires causal."""
    if window and not causal:
        raise ValueError("flash_attention: window > 0 requires causal=True")
    alibi = None
    if alibi_slopes is not None:
        b, _, s, _ = q.shape
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        pos = (
            jnp.arange(s, dtype=jnp.int32)
            if alibi_positions is None
            else jnp.asarray(alibi_positions, jnp.int32)
        )
        if pos.ndim == 1:
            pos = jnp.broadcast_to(pos[None], (b, s))
        # lane-broadcast plane per head: the kernel reads [1, LANES] blocks
        alibi = (jnp.broadcast_to(slopes[:, None], (slopes.shape[0], LANES)), pos)
    wflag = None
    if window and window_flag is not None:
        wflag = jnp.broadcast_to(
            jnp.asarray(window_flag, jnp.int32).reshape(1, 1), (1, LANES)
        )
    return _flash_core(q, k, v, segment_ids, alibi, wflag, causal, scale,
                       int(window), interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _flash_core(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret):
    out, _ = _flash_fwd(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret)
    return out


def _kv_clamp(causal, bq, bk, window=0, static_window=False):
    """kv-block index map value for grid point (i, j): masked points re-fetch
    the nearest active block (Pallas elides the unchanged copy). A static
    window additionally clamps from below — blocks fully behind the band are
    never fetched."""
    if not causal:
        return lambda i, j: j
    hi = lambda i: (i * bq + bq - 1) // bk
    if window and static_window:
        return lambda i, j: jnp.clip(j, jnp.maximum(0, i * bq - window + 1) // bk, hi(i))
    return lambda i, j: jnp.minimum(j, hi(i))


def _q_clamp(causal, bq, bk, window=0, static_window=False, nq=None):
    """q-block index map for the dk/dv grid (kv major, q minor)."""
    if not causal:
        return lambda i, j: j
    lo = lambda i: (i * bk) // bq
    if window and static_window:
        return lambda i, j: jnp.clip(
            j, lo(i), jnp.minimum(nq - 1, (i * bk + bk - 1 + window - 1) // bq)
        )
    return lambda i, j: jnp.maximum(j, lo(i))


def _seg_specs(segment_ids, q_block, q_map, k_block, k_map):
    """(extra operands, extra in_specs) for the [b, s] segment-id planes.
    ``q_map``/``k_map`` are (i, j) -> block-index functions — the same clamps
    used for the q and k/v tensor specs, so masked grid points re-fetch the
    previous seg block (copy elided) exactly like their tensors."""
    if segment_ids is None:
        return [], []
    seg = segment_ids.astype(jnp.int32)
    return [seg, seg], [
        pl.BlockSpec((1, q_block), lambda b_, h_, i, j: (b_, q_map(i, j))),
        pl.BlockSpec((1, k_block), lambda b_, h_, i, j: (b_, k_map(i, j))),
    ]


def _alibi_specs(alibi, k_block, k_map):
    """(extra operands, extra in_specs) for ALiBi: the per-head slope plane
    [h, LANES] plus the [b, s] key-position plane (k-side blocks only)."""
    if alibi is None:
        return [], []
    slopes_lane, kpos = alibi
    return [slopes_lane, kpos], [
        pl.BlockSpec((1, LANES), lambda b_, h_, i, j: (h_, 0)),
        pl.BlockSpec((1, k_block), lambda b_, h_, i, j: (b_, k_map(i, j))),
    ]


def _wflag_specs(wflag):
    """(extra operands, extra in_specs) for the per-layer window flag plane."""
    if wflag is None:
        return [], []
    return [wflag], [pl.BlockSpec((1, LANES), lambda b_, h_, i, j: (0, 0))]


def _flash_call(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret):
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale = scale if scale is not None else d ** -0.5
    bq = _pick_block(s)
    bk = _pick_block(s)
    nq, nk = s // bq, s // bk
    jc = _kv_clamp(causal, bq, bk, window, static_window=wflag is None)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk, window=window
    )

    seg_ops, seg_specs = _seg_specs(segment_ids, bq, lambda i, j: i, bk, jc)
    alibi_ops, alibi_specs = _alibi_specs(alibi, bk, jc)
    wf_ops, wf_specs = _wflag_specs(wflag)

    def entry(qr, kr, vr, *rest):
        rest = list(rest)
        kw = {}
        if seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        if wf_ops:
            kw["wflag_ref"] = rest.pop(0)
        orf, lr, mref, lref, aref = rest
        kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
               lr.at[0, 0], mref, lref, aref, **kw)

    out, lse = pl.pallas_call(
        # refs arrive with the leading (1, 1) block dims squeezed via .at
        entry,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
        ] + seg_specs + alibi_specs + wf_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # running max m
            pltpu.VMEM((bq, LANES), jnp.float32),  # running sum l
            pltpu.VMEM((bq, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(q, k, v, *seg_ops, *alibi_ops, *wf_ops)
    return out, lse


def _flash_fwd(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret):
    out, lse = _flash_call(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret)
    # Residual LSE is narrowed to one lane (it is lane-broadcast) so saving it
    # costs b·h·s·4 bytes, not ×LANES; the backward re-broadcasts. The names
    # feed the "flash" remat policy (models.transformer.remat_policy): saving
    # out+lse means a remat'd layer skips re-running the attention forward.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse1 = checkpoint_name(lse[..., :1], "flash_lse")
    # Residual q/k/v carry their own tag: the "flash_qkv" policy additionally
    # skips re-running the qkv projections + rope in a remat'd backward.
    q = checkpoint_name(q, "flash_qkv")
    k = checkpoint_name(k, "flash_qkv")
    v = checkpoint_name(v, "flash_qkv")
    return out, (q, k, v, segment_ids, alibi, wflag, out, lse1)


def _flash_bwd(causal, scale, window, interpret, res, g):
    q, k, v, segment_ids, alibi, wflag, out, lse = res
    lse = jnp.broadcast_to(lse, lse.shape[:-1] + (LANES,))
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale_v = scale if scale is not None else d ** -0.5
    bq = _pick_block(s)
    bk = _pick_block(s)
    nq, nk = s // bq, s // bk
    static_w = wflag is None
    jc = _kv_clamp(causal, bq, bk, window, static_window=static_w)
    qc = _q_clamp(causal, bq, bk, window, static_window=static_w, nq=nq)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk, nk=nk,
        window=window,
    )

    seg_ops, seg_specs = _seg_specs(segment_ids, bq, lambda i, j: i, bk, jc)
    alibi_ops, alibi_specs = _alibi_specs(alibi, bk, jc)
    wf_ops, wf_specs = _wflag_specs(wflag)

    def dq_entry(qr, kr, vr, orf, dor, lr, *rest):
        rest = list(rest)
        kw = {}
        if seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        if wf_ops:
            kw["wflag_ref"] = rest.pop(0)
        dqr, dref, aref = rest
        dq_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
                  dor.at[0, 0], lr.at[0, 0], dqr.at[0, 0], dref, aref, **kw)

    dq = pl.pallas_call(
        dq_entry,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ] + seg_specs + alibi_specs + wf_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # delta
            pltpu.VMEM((bq, d), jnp.float32),      # dq accumulator
        ],
        interpret=interpret,
    )(q, k, v, out, g, lse, *seg_ops, *alibi_ops, *wf_ops)

    # dk/dv computed per q-head (reduced over the GQA group after), with the
    # q/do/o/lse stream minor so one [bk, d] kv block stays resident.
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk, nq=nq,
        window=window,
    )
    dkv_seg_ops, dkv_seg_specs = _seg_specs(segment_ids, bq, qc, bk, lambda i, j: i)
    # dk/dv grid is kv-major: the key-position block follows the kv index i
    dkv_alibi_ops, dkv_alibi_specs = _alibi_specs(alibi, bk, lambda i, j: i)

    def dkv_entry(qr, kr, vr, orf, dor, lr, *rest):
        rest = list(rest)
        kw = {}
        if dkv_seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if dkv_alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        if wf_ops:
            kw["wflag_ref"] = rest.pop(0)
        dkr, dvr, dka, dva = rest
        dkv_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
                   dor.at[0, 0], lr.at[0, 0], dkr.at[0, 0], dvr.at[0, 0],
                   dka, dva, **kw)

    dk_h, dv_h = pl.pallas_call(
        dkv_entry,
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_ // group, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_ // group, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
        ] + dkv_seg_specs + dkv_alibi_specs + wf_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),  # dk accumulator
            pltpu.VMEM((bk, d), jnp.float32),  # dv accumulator
        ],
        interpret=interpret,
    )(q, k, v, out, g, lse, *dkv_seg_ops, *dkv_alibi_ops, *wf_ops)

    if group > 1:
        dk = jnp.sum(dk_h.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2).astype(k.dtype)
        dv = jnp.sum(dv_h.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv, None, None, None  # no cotangent for segment_ids / alibi / wflag


_flash_core.defvjp(_flash_fwd, _flash_bwd)
