"""Flash attention as a Pallas TPU kernel (forward + backward), splash-style.

The TPU-native replacement for the reference's fused attention kernels
(csrc/transformer/inference softmax/attention ops, evoformer_attn CUTLASS
kernels, blocked_flash in inference/v2/kernels/ragged_ops/blocked_flash):
online-softmax tiling so the [s, s] score matrix never materializes in HBM.

Design (round 3: kv-pipelined — nothing sequence-length-sized is ever VMEM
resident, lifting the former ~8k dense cap):
  * Layout [b, h, s, d]. Forward grid (b, h, nq, nk) with the kv block index
    minor: each program sees one [bq, d] q block and one [bk, d] k/v block;
    Pallas double-buffers the next kv block's HBM→VMEM copy behind the
    current block's MXU work. Softmax state (m, l) and the output
    accumulator live in VMEM scratch carried across the kv iterations; the
    output block is written once on the last iteration.
  * Causal pruning: masked (q, kv) grid points clamp their kv index map to
    the last active block — Pallas elides the copy when the block index is
    unchanged — and skip compute under ``pl.when``. Cost of a pruned point
    is grid overhead only, preserving the ~2× causal win.
  * fp32 accumulators; the MXU sees bf16 inputs with
    ``preferred_element_type=jnp.float32``.
  * LSE is stored lane-broadcast as [b, h, s, LANES] to satisfy the TPU
    (8, 128) tiling rule for output blocks.
  * Backward: flash recompute — per-block p = exp(qk·scale − lse). dq
    streams kv blocks (grid (b, h, nq, nk)); dk/dv streams q/do/o/lse
    blocks (grid (b, h, nk, nq)); both carry fp32 scratch accumulators.
    delta = Σ do·o is computed in-kernel from the saved output.
  * GQA: kv-head index map h → h // (nh/nkv); no head replication in HBM.

Numerics validated against ops.attention.mha_reference in
tests/unit/ops/test_flash_attention.py (interpret mode on CPU), including a
16k-sequence dense case no longer possible with whole-K/V residency.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _alibi_term(alibi_ref, kpos_ref):
    """ALiBi additive logits term for one block: ``slope_h * key_position``
    (HF bloom's absolute-position convention — softmax-equivalent to the
    relative form under causal masking). alibi_ref: [1, LANES] slope plane
    for this head; kpos_ref: [bk] int32 key positions."""
    return alibi_ref[0, 0] * kpos_ref[:].astype(jnp.float32)[None, :]


def _apply_window(logits, window, wflag_ref, q_pos, k_pos):
    """Sliding-window band mask: query sees keys in (q - window, q]. With a
    ``wflag_ref`` ([1, LANES] int32 plane, traced per layer from
    attn_layer_pattern) the band only applies when the flag is set — the
    layer scan stays uniform while layers alternate local/global (gpt_neo).
    The band convention is the shared ``core.window_too_far``."""
    from deepspeed_tpu.ops.attention.core import window_too_far

    far = window_too_far(
        q_pos, k_pos, window, wflag_ref[0, 0] if wflag_ref is not None else None
    )
    return jnp.where(far, NEG_INF, logits)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                scale, causal, bq, bk, nk, window=0, seg_q_ref=None,
                seg_k_ref=None, alibi_ref=None, kpos_ref=None, wflag_ref=None,
                m_in_ref=None, l_in_ref=None, acc_in_ref=None, l_out_ref=None):
    # q_ref: [bq, d]; k_ref/v_ref: [bk, d] (one streamed block);
    # o_ref: [bq, d]; lse_ref: [bq, LANES]; scratch m/l: [bq, LANES] f32,
    # acc: [bq, d] f32 — carried across the minor (kv) grid dimension.
    #
    # Carry mode (ring attention, ops/attention/sharded.py): ``m_in_ref``/
    # ``l_in_ref``/``acc_in_ref`` seed the softmax state from a previous
    # chunk instead of (-inf, 0, 0), and ``l_out_ref`` switches the flush to
    # RAW state output — (acc, m, l) via (o_ref, lse_ref, l_out_ref), no
    # normalization — so chunked streaming is bit-identical to one long
    # in-kernel stream.
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        if m_in_ref is None:
            m_ref[:] = jnp.full_like(m_ref, NEG_INF)
            l_ref[:] = jnp.zeros_like(l_ref)
            acc_ref[:] = jnp.zeros_like(acc_ref)
        else:
            m_ref[:] = m_in_ref[:]
            l_ref[:] = l_in_ref[:]
            acc_ref[:] = acc_in_ref[:].astype(jnp.float32)

    hi = (qi * bq + bq - 1) // bk  # last kv block a causal q block touches
    active = (ki <= hi) if causal else (ki >= 0)
    if window and wflag_ref is None:
        # static window (every layer banded): prune kv blocks fully behind it
        active = jnp.logical_and(active, ki >= jnp.maximum(0, qi * bq - window + 1) // bk)

    @pl.when(active)
    def _step():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk] fp32
        if alibi_ref is not None:
            logits = logits + _alibi_term(alibi_ref, kpos_ref)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
            if window:
                logits = _apply_window(logits, window, wflag_ref, q_pos, k_pos)
        if seg_q_ref is not None:
            logits = jnp.where(
                seg_q_ref[:][:, None] == seg_k_ref[:][None, :], logits, NEG_INF
            )
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    @pl.when(ki == nk - 1)
    def _flush():
        if l_out_ref is not None:
            # raw-state flush: the caller continues the stream (or finalizes
            # with flash_finalize, whose math mirrors the branch below)
            o_ref[:] = acc_ref[:]
            lse_ref[:] = m_ref[:]
            l_out_ref[:] = l_ref[:]
        else:
            l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
            o_ref[:] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
            lse_ref[:] = jnp.broadcast_to(
                (m_ref[:, 0] + jnp.log(l_safe))[:, None], (bq, LANES)
            )


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
                   delta_ref, dq_acc_ref, *, scale, causal, bq, bk, nk,
                   window=0, seg_q_ref=None, seg_k_ref=None, alibi_ref=None,
                   kpos_ref=None, wflag_ref=None, dq_in_ref=None,
                   raw_out=False):
    # Carry mode (ring bwd): ``dq_in_ref`` seeds the accumulator from the
    # previous chunk's partial and ``raw_out`` flushes it unscaled in f32 —
    # the ring applies `* scale` once after the last chunk, exactly like the
    # single-kernel flush.
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        if dq_in_ref is None:
            dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)
        else:
            dq_acc_ref[:] = dq_in_ref[:]
        delta = jnp.sum(
            do_ref[:].astype(jnp.float32) * o_ref[:].astype(jnp.float32), axis=-1
        )
        delta_ref[:] = jnp.broadcast_to(delta[:, None], delta_ref.shape)

    hi = (qi * bq + bq - 1) // bk
    active = (ki <= hi) if causal else (ki >= 0)
    if window and wflag_ref is None:
        active = jnp.logical_and(active, ki >= jnp.maximum(0, qi * bq - window + 1) // bk)

    @pl.when(active)
    def _step():
        q = q_ref[:]
        k = k_ref[:]
        v = v_ref[:]
        do = do_ref[:]
        lse = lse_ref[:, 0]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if alibi_ref is not None:
            logits = logits + _alibi_term(alibi_ref, kpos_ref)
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
            if window:
                logits = _apply_window(logits, window, wflag_ref, q_pos, k_pos)
        if seg_q_ref is not None:
            logits = jnp.where(
                seg_q_ref[:][:, None] == seg_k_ref[:][None, :], logits, NEG_INF
            )
        p = jnp.exp(logits - lse[:, None])  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[:, 0][:, None])  # [bq, bk]
        dq_acc_ref[:] = dq_acc_ref[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _flush():
        if raw_out:
            dq_ref[:] = dq_acc_ref[:]
        else:
            dq_ref[:] = (dq_acc_ref[:] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref,
                    dv_ref, dk_acc_ref, dv_acc_ref, *, scale, causal, bq, bk,
                    nq, window=0, seg_q_ref=None, seg_k_ref=None,
                    alibi_ref=None, kpos_ref=None, wflag_ref=None,
                    dk_in_ref=None, dv_in_ref=None, raw_out=False):
    # Carry mode mirrors _bwd_dq_kernel: seed accumulators from the previous
    # chunk's partials, flush raw f32 when ``raw_out``.
    ki = pl.program_id(2)
    qj = pl.program_id(3)

    @pl.when(qj == 0)
    def _init():
        if dk_in_ref is None:
            dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
            dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)
        else:
            dk_acc_ref[:] = dk_in_ref[:]
            dv_acc_ref[:] = dv_in_ref[:]

    lo = (ki * bk) // bq  # first q block that sees this kv block
    active = (qj >= lo) if causal else (qj >= 0)
    if window and wflag_ref is None:
        # last q block inside the band for this kv block
        active = jnp.logical_and(active, qj <= (ki * bk + bk - 1 + window - 1) // bq)

    @pl.when(active)
    def _step():
        k = k_ref[:]
        v = v_ref[:]
        q = q_ref[:]
        do = do_ref[:]
        o = o_ref[:]
        lse = lse_ref[:, 0]
        # delta is recomputed per (kv, q) grid point: one [bq, d] VPU reduce
        # (~0.05% of the two MXU matmuls below) — cheaper than a separate
        # preprocess kernel or an HBM round-trip for [b, h, s] deltas.
        delta = jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
        )  # [bq]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if alibi_ref is not None:
            logits = logits + _alibi_term(alibi_ref, kpos_ref)
        if causal:
            q_pos = qj * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
            if window:
                logits = _apply_window(logits, window, wflag_ref, q_pos, k_pos)
        if seg_q_ref is not None:
            logits = jnp.where(
                seg_q_ref[:][:, None] == seg_k_ref[:][None, :], logits, NEG_INF
            )
        p = jnp.exp(logits - lse[:, None])
        dv_acc_ref[:] = dv_acc_ref[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dk_acc_ref[:] = dk_acc_ref[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qj == nq - 1)
    def _flush():
        if raw_out:
            dk_ref[:] = dk_acc_ref[:]
            dv_ref[:] = dv_acc_ref[:]
        else:
            # scale moved onto the logits, so dk picks it up (dlogits/dk = scale*q)
            dk_ref[:] = (dk_acc_ref[:] * scale).astype(dk_ref.dtype)
            dv_ref[:] = dv_acc_ref[:].astype(dv_ref.dtype)


def _pick_block(s, target=None):
    """Largest power-of-two block ≤ target dividing s. The default block is
    env-tunable (DSTPU_FLASH_BLOCK) for per-generation retuning; with the
    kv-pipelined kernel 1024 measured best on v5e at s=2048 (fwd+bwd 5.75 ms
    vs 6.93 at 512, 10.7 at 256; 2048 exceeds the 16M scoped-vmem limit)."""
    if target is None:
        import os

        target = int(os.environ.get("DSTPU_FLASH_BLOCK", 1024))
        if target < 128 or target & (target - 1):
            raise ValueError(
                f"DSTPU_FLASH_BLOCK={target} invalid: need a power of two >= 128"
            )
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids=None,
    scale: Optional[float] = None,
    interpret: bool = False,
    alibi_slopes=None,
    alibi_positions=None,
    window: int = 0,
    window_flag=None,
) -> jax.Array:
    """Flash attention. q: [b, h, s, d]; k, v: [b, h_kv, s, d] → [b, h, s, d].

    ``segment_ids``: optional [b, s] int32 — packed-sequence masking happens
    IN the kernel (tokens attend only within their own segment), so packed
    pretraining keeps the flash path.

    ``alibi_slopes``: optional [h] fp32 — bloom-style ALiBi folds into the
    kernel as ``slope_h * key_position`` added to the logits (rank-1, so the
    [s, s] bias never materializes; the review of round 4 found alibi
    silently dropping to the O(s²)-HBM reference path). ``alibi_positions``
    ([b, s] or [s] int32) supplies the key positions; defaults to arange.
    Slopes are constants (non-learned) — no cotangent.

    ``window``: static sliding-window size — query i sees keys in
    (i - window, i] (mistral/starcoder2/gpt_neo). With ``window_flag`` None
    every layer is banded and out-of-band kv BLOCKS are pruned from the grid
    (compute and copies drop to O(s·window)); with ``window_flag`` (a traced
    0/1 scalar from attn_layer_pattern) the band toggles per layer via
    in-kernel masking (full causal grid, flash memory). Requires causal."""
    if window and not causal:
        raise ValueError("flash_attention: window > 0 requires causal=True")
    alibi = None
    if alibi_slopes is not None:
        b, _, s, _ = q.shape
        alibi = build_alibi_operand(alibi_slopes, alibi_positions, b, s)
    wflag = None
    if window and window_flag is not None:
        wflag = jnp.broadcast_to(
            jnp.asarray(window_flag, jnp.int32).reshape(1, 1), (1, LANES)
        )
    return _flash_core(q, k, v, segment_ids, alibi, wflag, causal, scale,
                       int(window), interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9))
def _flash_core(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret):
    out, _ = _flash_fwd(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret)
    return out


def _kv_clamp(causal, bq, bk, window=0, static_window=False):
    """kv-block index map value for grid point (i, j): masked points re-fetch
    the nearest active block (Pallas elides the unchanged copy). A static
    window additionally clamps from below — blocks fully behind the band are
    never fetched."""
    if not causal:
        return lambda i, j: j
    hi = lambda i: (i * bq + bq - 1) // bk
    if window and static_window:
        return lambda i, j: jnp.clip(j, jnp.maximum(0, i * bq - window + 1) // bk, hi(i))
    return lambda i, j: jnp.minimum(j, hi(i))


def _q_clamp(causal, bq, bk, window=0, static_window=False, nq=None):
    """q-block index map for the dk/dv grid (kv major, q minor)."""
    if not causal:
        return lambda i, j: j
    lo = lambda i: (i * bk) // bq
    if window and static_window:
        return lambda i, j: jnp.clip(
            j, lo(i), jnp.minimum(nq - 1, (i * bk + bk - 1 + window - 1) // bq)
        )
    return lambda i, j: jnp.maximum(j, lo(i))


def _seg_specs(segment_ids, q_block, q_map, k_block, k_map):
    """(extra operands, extra in_specs) for the [b, s] segment-id planes.
    ``q_map``/``k_map`` are (i, j) -> block-index functions — the same clamps
    used for the q and k/v tensor specs, so masked grid points re-fetch the
    previous seg block (copy elided) exactly like their tensors.

    ``segment_ids`` may be one [b, s] plane (self-attention: the same ids
    mask both sides) or a ``(seg_q, seg_k)`` pair of [b, sq]/[b, sk] planes —
    the ring path's chunks carry DIFFERENT q-side and k-side id planes (the
    k chunk rotates, the q chunk stays home)."""
    if segment_ids is None:
        return [], []
    if isinstance(segment_ids, tuple):
        seg_q, seg_k = segment_ids
    else:
        seg_q = seg_k = segment_ids
    seg_q = seg_q.astype(jnp.int32)
    seg_k = seg_k.astype(jnp.int32)
    return [seg_q, seg_k], [
        pl.BlockSpec((1, q_block), lambda b_, h_, i, j: (b_, q_map(i, j))),
        pl.BlockSpec((1, k_block), lambda b_, h_, i, j: (b_, k_map(i, j))),
    ]


def _alibi_specs(alibi, k_block, k_map):
    """(extra operands, extra in_specs) for ALiBi: the per-head slope plane
    [h, LANES] plus the [b, s] key-position plane (k-side blocks only)."""
    if alibi is None:
        return [], []
    slopes_lane, kpos = alibi
    return [slopes_lane, kpos], [
        pl.BlockSpec((1, LANES), lambda b_, h_, i, j: (h_, 0)),
        pl.BlockSpec((1, k_block), lambda b_, h_, i, j: (b_, k_map(i, j))),
    ]


def _wflag_specs(wflag):
    """(extra operands, extra in_specs) for the per-layer window flag plane."""
    if wflag is None:
        return [], []
    return [wflag], [pl.BlockSpec((1, LANES), lambda b_, h_, i, j: (0, 0))]


def _flash_call(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret):
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale = scale if scale is not None else d ** -0.5
    bq = _pick_block(s)
    bk = _pick_block(s)
    nq, nk = s // bq, s // bk
    jc = _kv_clamp(causal, bq, bk, window, static_window=wflag is None)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk, window=window
    )

    seg_ops, seg_specs = _seg_specs(segment_ids, bq, lambda i, j: i, bk, jc)
    alibi_ops, alibi_specs = _alibi_specs(alibi, bk, jc)
    wf_ops, wf_specs = _wflag_specs(wflag)

    def entry(qr, kr, vr, *rest):
        rest = list(rest)
        kw = {}
        if seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        if wf_ops:
            kw["wflag_ref"] = rest.pop(0)
        orf, lr, mref, lref, aref = rest
        kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
               lr.at[0, 0], mref, lref, aref, **kw)

    out, lse = pl.pallas_call(
        # refs arrive with the leading (1, 1) block dims squeezed via .at
        entry,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
        ] + seg_specs + alibi_specs + wf_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # running max m
            pltpu.VMEM((bq, LANES), jnp.float32),  # running sum l
            pltpu.VMEM((bq, d), jnp.float32),      # output accumulator
        ],
        interpret=interpret,
    )(q, k, v, *seg_ops, *alibi_ops, *wf_ops)
    return out, lse


def _flash_fwd(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret):
    out, lse = _flash_call(q, k, v, segment_ids, alibi, wflag, causal, scale, window, interpret)
    # Residual LSE is narrowed to one lane (it is lane-broadcast) so saving it
    # costs b·h·s·4 bytes, not ×LANES; the backward re-broadcasts. The names
    # feed the "flash" remat policy (models.transformer.remat_policy): saving
    # out+lse means a remat'd layer skips re-running the attention forward.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse1 = checkpoint_name(lse[..., :1], "flash_lse")
    # Residual q/k/v carry their own tag: the "flash_qkv" policy additionally
    # skips re-running the qkv projections + rope in a remat'd backward.
    q = checkpoint_name(q, "flash_qkv")
    k = checkpoint_name(k, "flash_qkv")
    v = checkpoint_name(v, "flash_qkv")
    return out, (q, k, v, segment_ids, alibi, wflag, out, lse1)


def _flash_bwd(causal, scale, window, interpret, res, g):
    q, k, v, segment_ids, alibi, wflag, out, lse = res
    lse = jnp.broadcast_to(lse, lse.shape[:-1] + (LANES,))
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale_v = scale if scale is not None else d ** -0.5
    bq = _pick_block(s)
    bk = _pick_block(s)
    nq, nk = s // bq, s // bk
    static_w = wflag is None
    jc = _kv_clamp(causal, bq, bk, window, static_window=static_w)
    qc = _q_clamp(causal, bq, bk, window, static_window=static_w, nq=nq)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk, nk=nk,
        window=window,
    )

    seg_ops, seg_specs = _seg_specs(segment_ids, bq, lambda i, j: i, bk, jc)
    alibi_ops, alibi_specs = _alibi_specs(alibi, bk, jc)
    wf_ops, wf_specs = _wflag_specs(wflag)

    def dq_entry(qr, kr, vr, orf, dor, lr, *rest):
        rest = list(rest)
        kw = {}
        if seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        if wf_ops:
            kw["wflag_ref"] = rest.pop(0)
        dqr, dref, aref = rest
        dq_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
                  dor.at[0, 0], lr.at[0, 0], dqr.at[0, 0], dref, aref, **kw)

    dq = pl.pallas_call(
        dq_entry,
        grid=(b, h, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ] + seg_specs + alibi_specs + wf_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # delta
            pltpu.VMEM((bq, d), jnp.float32),      # dq accumulator
        ],
        interpret=interpret,
    )(q, k, v, out, g, lse, *seg_ops, *alibi_ops, *wf_ops)

    # dk/dv computed per q-head (reduced over the GQA group after), with the
    # q/do/o/lse stream minor so one [bk, d] kv block stays resident.
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk, nq=nq,
        window=window,
    )
    dkv_seg_ops, dkv_seg_specs = _seg_specs(segment_ids, bq, qc, bk, lambda i, j: i)
    # dk/dv grid is kv-major: the key-position block follows the kv index i
    dkv_alibi_ops, dkv_alibi_specs = _alibi_specs(alibi, bk, lambda i, j: i)

    def dkv_entry(qr, kr, vr, orf, dor, lr, *rest):
        rest = list(rest)
        kw = {}
        if dkv_seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if dkv_alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        if wf_ops:
            kw["wflag_ref"] = rest.pop(0)
        dkr, dvr, dka, dva = rest
        dkv_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
                   dor.at[0, 0], lr.at[0, 0], dkr.at[0, 0], dvr.at[0, 0],
                   dka, dva, **kw)

    dk_h, dv_h = pl.pallas_call(
        dkv_entry,
        grid=(b, h, nk, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_ // group, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_ // group, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
            pl.BlockSpec((1, 1, bq, LANES),
                         lambda b_, h_, i, j: (b_, h_, qc(i, j), 0)),
        ] + dkv_seg_specs + dkv_alibi_specs + wf_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),  # dk accumulator
            pltpu.VMEM((bk, d), jnp.float32),  # dv accumulator
        ],
        interpret=interpret,
    )(q, k, v, out, g, lse, *dkv_seg_ops, *dkv_alibi_ops, *wf_ops)

    if group > 1:
        dk = jnp.sum(dk_h.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2).astype(k.dtype)
        dv = jnp.sum(dv_h.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv, None, None, None  # no cotangent for segment_ids / alibi / wflag


_flash_core.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# Chunked (ring) entry points — ops/attention/sharded.py
#
# The ring context-parallel layer streams k/v (forward, dq) or q/do (dk/dv)
# CHUNKS through the same kernels above, threading the raw softmax state /
# gradient accumulators between pallas_calls instead of carrying them in VMEM
# scratch across one long grid. Because chunk arrival order is arranged to
# match the single-kernel streaming order (ascending global blocks) and the
# block size matches, the chunked stream is BIT-IDENTICAL to one
# flash_attention call over the gathered sequence — the acceptance bar for
# the ring path (tests/unit/ops/test_sharded_attention.py, atol 0).
# ---------------------------------------------------------------------------


def build_alibi_operand(alibi_slopes, alibi_positions, b, s):
    """Kernel-ready ALiBi operand: ([h, LANES] lane-broadcast slope plane,
    [b, s] int32 key positions). ``alibi_positions`` defaults to arange —
    ring chunks pass their GLOBAL key positions so slope·kpos matches the
    unsharded kernel exactly."""
    slopes = jnp.asarray(alibi_slopes, jnp.float32)
    pos = (
        jnp.arange(s, dtype=jnp.int32)
        if alibi_positions is None
        else jnp.asarray(alibi_positions, jnp.int32)
    )
    if pos.ndim == 1:
        pos = jnp.broadcast_to(pos[None], (b, s))
    return (jnp.broadcast_to(slopes[:, None], (slopes.shape[0], LANES)), pos)


def flash_carry_init(b, h, s, d):
    """Initial (m, l, acc) softmax carry — identical to the kernel's ki==0
    seed (NEG_INF, not -inf: matches ``_fwd_kernel._init`` bitwise)."""
    return (
        jnp.full((b, h, s, LANES), NEG_INF, jnp.float32),
        jnp.zeros((b, h, s, LANES), jnp.float32),
        jnp.zeros((b, h, s, d), jnp.float32),
    )


def flash_finalize(carry, dtype):
    """Normalize a streamed carry into (out, lse[..., :1]) with math identical
    to the kernel's non-raw flush (``_fwd_kernel._flush``):
    ``out = acc / max(l, 1e-30)``; ``lse = m + log(max(l, 1e-30))``."""
    m, l, acc = carry
    l_safe = jnp.maximum(l[..., :1], 1e-30)
    out = (acc / l_safe).astype(dtype)
    lse = m[..., :1] + jnp.log(l_safe)
    return out, lse


def flash_fwd_chunk(q, k, v, carry, segment_ids=None, alibi=None,
                    causal=False, scale=None, block=None, interpret=False):
    """Stream ONE k/v chunk into a carried flash softmax state.

    q: [b, h, sq, d] (the home query shard); k, v: [b, h_kv, sk, d] (the
    chunk currently held by this ring step). ``carry`` is ``(m, l, acc)``
    from :func:`flash_carry_init` or a previous chunk. ``causal=True`` marks
    the DIAGONAL chunk (sq == sk, local positions — the global offset cancels
    on both sides of the mask). ``segment_ids`` is a ``(seg_q, seg_k)`` pair;
    ``alibi`` a :func:`build_alibi_operand` tuple whose kpos plane holds this
    chunk's GLOBAL key positions. ``block`` must equal the block size the
    equivalent single-device call would pick for bitwise parity.

    Returns the updated ``(m, l, acc)``.
    """
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    scale = scale if scale is not None else d ** -0.5
    if causal and sq != sk:
        raise ValueError("flash_fwd_chunk: causal=True is the diagonal chunk; needs sq == sk")
    bq = _pick_block(sq, target=block)
    bk = _pick_block(sk, target=block)
    nq, nk = sq // bq, sk // bk
    jc = _kv_clamp(causal, bq, bk)
    m, l, acc = carry

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk
    )
    seg_ops, seg_specs = _seg_specs(segment_ids, bq, lambda i, j: i, bk, jc)
    alibi_ops, alibi_specs = _alibi_specs(alibi, bk, jc)

    def entry(qr, kr, vr, mir, lir, air, *rest):
        rest = list(rest)
        kw = {
            "m_in_ref": mir.at[0, 0],
            "l_in_ref": lir.at[0, 0],
            "acc_in_ref": air.at[0, 0],
        }
        if seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        aor, mor, lor, mref, lref, aref = rest
        kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], aor.at[0, 0],
               mor.at[0, 0], mref, lref, aref, l_out_ref=lor.at[0, 0], **kw)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    lane_spec = pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i, j: (b_, h_, i, 0))
    acc_out, m_out, l_out = pl.pallas_call(
        entry,
        grid=(b, h, nq, nk),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0)),
            lane_spec,  # m carry-in
            lane_spec,  # l carry-in
            q_spec,     # acc carry-in
        ] + seg_specs + alibi_specs,
        out_specs=[q_spec, lane_spec, lane_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq, LANES), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, LANES), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, m, l, acc, *seg_ops, *alibi_ops)
    return m_out, l_out, acc_out


def flash_dq_chunk(q, k, v, out, do, lse, dq_acc, segment_ids=None,
                   alibi=None, causal=False, scale=None, block=None,
                   interpret=False):
    """One ring hop of the dq backward: fold this k/v chunk's contribution
    into ``dq_acc`` ([b, h, sq, d] f32, UNSCALED). ``lse`` is the GLOBAL
    log-sum-exp ([..., 1] or lane-broadcast) — the flash recompute
    p = exp(qk·scale − lse) is exact per chunk, so chunk order only affects
    the dq sum, which :func:`flash_dq_finalize` scales/casts once at the end
    exactly like the single-kernel flush."""
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    scale = scale if scale is not None else d ** -0.5
    if causal and sq != sk:
        raise ValueError("flash_dq_chunk: causal=True is the diagonal chunk; needs sq == sk")
    bq = _pick_block(sq, target=block)
    bk = _pick_block(sk, target=block)
    nq, nk = sq // bq, sk // bk
    jc = _kv_clamp(causal, bq, bk)
    lse = jnp.broadcast_to(lse, lse.shape[:-1] + (LANES,))

    kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk,
        raw_out=True,
    )
    seg_ops, seg_specs = _seg_specs(segment_ids, bq, lambda i, j: i, bk, jc)
    alibi_ops, alibi_specs = _alibi_specs(alibi, bk, jc)

    def entry(qr, kr, vr, orf, dor, lr, dqi, *rest):
        rest = list(rest)
        kw = {"dq_in_ref": dqi.at[0, 0]}
        if seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        dqr, dref, aref = rest
        kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
               dor.at[0, 0], lr.at[0, 0], dqr.at[0, 0], dref, aref, **kw)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d),
                           lambda b_, h_, i, j: (b_, h_ // group, jc(i, j), 0))
    lane_spec = pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i, j: (b_, h_, i, 0))
    return pl.pallas_call(
        entry,
        grid=(b, h, nq, nk),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, lane_spec, q_spec]
        + seg_specs + alibi_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bq, LANES), jnp.float32),  # delta
            pltpu.VMEM((bq, d), jnp.float32),      # dq accumulator
        ],
        interpret=interpret,
    )(q, k, v, out, do, lse, dq_acc, *seg_ops, *alibi_ops)


def flash_dkv_chunk(q, k, v, out, do, lse, dk_acc, dv_acc, segment_ids=None,
                    alibi=None, causal=False, scale=None, block=None,
                    interpret=False):
    """One ring hop of the dk/dv backward: the HOME k/v chunk absorbs the
    contribution of a visiting q-side chunk (q/out/do/lse rotate; the
    accumulators stay put). ``dk_acc``/``dv_acc`` are [b, h, sk, d] f32
    PER-Q-HEAD partials (unscaled); :func:`flash_dkv_finalize` applies the
    scale/cast and GQA group reduction after the last chunk."""
    b, h, sq, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    group = h // h_kv
    scale = scale if scale is not None else d ** -0.5
    if causal and sq != sk:
        raise ValueError("flash_dkv_chunk: causal=True is the diagonal chunk; needs sq == sk")
    bq = _pick_block(sq, target=block)
    bk = _pick_block(sk, target=block)
    nq, nk = sq // bq, sk // bk
    qc = _q_clamp(causal, bq, bk, nq=nq)
    lse = jnp.broadcast_to(lse, lse.shape[:-1] + (LANES,))

    kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nq=nq,
        raw_out=True,
    )
    seg_ops, seg_specs = _seg_specs(segment_ids, bq, qc, bk, lambda i, j: i)
    alibi_ops, alibi_specs = _alibi_specs(alibi, bk, lambda i, j: i)

    def entry(qr, kr, vr, orf, dor, lr, dki, dvi, *rest):
        rest = list(rest)
        kw = {"dk_in_ref": dki.at[0, 0], "dv_in_ref": dvi.at[0, 0]}
        if seg_ops:
            kw["seg_q_ref"] = rest.pop(0).at[0]
            kw["seg_k_ref"] = rest.pop(0).at[0]
        if alibi_ops:
            kw["alibi_ref"] = rest.pop(0)
            kw["kpos_ref"] = rest.pop(0).at[0]
        dkr, dvr, dka, dva = rest
        kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0],
               dor.at[0, 0], lr.at[0, 0], dkr.at[0, 0], dvr.at[0, 0],
               dka, dva, **kw)

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i, j: (b_, h_, qc(i, j), 0))
    kv_in_spec = pl.BlockSpec((1, 1, bk, d),
                              lambda b_, h_, i, j: (b_, h_ // group, i, 0))
    kv_spec = pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    lane_spec = pl.BlockSpec((1, 1, bq, LANES),
                             lambda b_, h_, i, j: (b_, h_, qc(i, j), 0))
    return pl.pallas_call(
        entry,
        grid=(b, h, nk, nq),
        in_specs=[q_spec, kv_in_spec, kv_in_spec, q_spec, q_spec, lane_spec,
                  kv_spec, kv_spec] + seg_specs + alibi_specs,
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, out, do, lse, dk_acc, dv_acc, *seg_ops, *alibi_ops)


def flash_dq_finalize(dq_acc, scale, dtype):
    """Scale + cast the streamed dq accumulator — identical to the
    single-kernel flush (``_bwd_dq_kernel._flush``, raw_out=False)."""
    return (dq_acc * scale).astype(dtype)


def flash_dkv_finalize(dk_acc, dv_acc, scale, dtype, h_kv):
    """Scale/cast the streamed per-q-head dk/dv partials and reduce the GQA
    group — the exact cast-then-f32-sum order of ``_flash_bwd``."""
    b, h, s, d = dk_acc.shape
    dk = (dk_acc * scale).astype(dtype)
    dv = dv_acc.astype(dtype)
    if h != h_kv:
        group = h // h_kv
        dk = jnp.sum(
            dk.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2
        ).astype(dtype)
        dv = jnp.sum(
            dv.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2
        ).astype(dtype)
    return dk, dv
