"""Flash attention as a Pallas TPU kernel (forward + backward).

The TPU-native replacement for the reference's fused attention kernels
(csrc/transformer/inference softmax/attention ops, evoformer_attn CUTLASS
kernels, blocked_flash in inference/v2/kernels/ragged_ops): online-softmax
tiling so the [s, s] score matrix never materializes in HBM.

Design:
  * Layout [b, h, s, d]; grid (b, h, q_blocks). Each program holds one q
    block in VMEM plus the full k/v for its (batch, kv-head) — fine to ~8k
    sequence at d=128 in bf16 (≈4 MB VMEM); longer sequences shard over the
    ``sequence`` mesh axis (Ulysses) before reaching the kernel.
  * Causal pruning: the kv-block loop's trip count is derived from the q
    block index, so programs skip fully-masked blocks (the 2× win).
  * fp32 accumulators; the MXU sees bf16 inputs with
    ``preferred_element_type=jnp.float32``.
  * LSE is stored lane-broadcast as [b, h, s, LANES] to satisfy the TPU
    (8, 128) tiling rule for output blocks.
  * Backward: standard flash recompute — per-block p = exp(qk·scale − lse),
    two passes (dq over q blocks; dk/dv over kv blocks); delta = Σ do·o is
    computed in-kernel from the saved output.
  * GQA: kv-head index map h → h // (nh/nkv); no head replication in HBM.

Numerics validated against ops.attention.mha_reference in
tests/unit/ops/test_flash_attention.py (interpret mode on CPU).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANES = 128


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal, bq, bk,
                seg_q_ref=None, seg_k_ref=None):
    # q_ref: [bq, d]; k_ref/v_ref: [s, d]; o_ref: [bq, d]; lse_ref: [bq, LANES]
    # seg_q_ref: [bq] / seg_k_ref: [s] int32 segment ids (packed sequences)
    qi = pl.program_id(2)
    s = k_ref.shape[0]
    d = q_ref.shape[1]
    nk = s // bk

    # operands stay in their storage dtype (bf16 on TPU): the MXU reads them
    # natively with an fp32 accumulator; fp32 VMEM copies of q/k/v would
    # double the kernel's working set. The softmax scale moves onto the fp32
    # logits (same value as pre-scaling q).
    q = q_ref[:]
    seg_q = seg_q_ref[:] if seg_q_ref is not None else None

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(ki * bk, bk), :]
        v = v_ref[pl.ds(ki * bk, bk), :]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk] fp32
        if causal:
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        if seg_q is not None:
            seg_k = seg_k_ref[pl.ds(ki * bk, bk)]
            logits = jnp.where(seg_q[:, None] == seg_k[None, :], logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    if causal:
        # only blocks whose start <= last q position
        hi = jnp.minimum((qi * bq + bq + bk - 1) // bk, nk)
    else:
        hi = nk
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to((m + jnp.log(l_safe))[:, None], (bq, LANES))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *, scale, causal, bq, bk,
                   seg_q_ref=None, seg_k_ref=None):
    qi = pl.program_id(2)
    s = k_ref.shape[0]
    d = q_ref.shape[1]
    nk = s // bk

    q = q_ref[:]
    do = do_ref[:]
    lse = lse_ref[:, 0]
    delta = jnp.sum(do.astype(jnp.float32) * o_ref[:].astype(jnp.float32), axis=-1)  # [bq]
    q_pos = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    seg_q = seg_q_ref[:] if seg_q_ref is not None else None

    def body(ki, dq):
        k = k_ref[pl.ds(ki * bk, bk), :]
        v = v_ref[pl.ds(ki * bk, bk), :]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        if causal:
            k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        if seg_q is not None:
            seg_k = seg_k_ref[pl.ds(ki * bk, bk)]
            logits = jnp.where(seg_q[:, None] == seg_k[None, :], logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])  # [bq, bk]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])  # [bq, bk]
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    hi = jnp.minimum((qi * bq + bq + bk - 1) // bk, nk) if causal else nk
    dq = jax.lax.fori_loop(0, hi, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref, *, scale, causal, bq, bk,
    seg_q_ref=None, seg_k_ref=None
):
    ki = pl.program_id(2)
    sq = q_ref.shape[0]
    d = k_ref.shape[1]
    nq = sq // bq

    k = k_ref[:]
    v = v_ref[:]
    k_pos = ki * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    seg_k = seg_k_ref[:] if seg_k_ref is not None else None

    def body(qj, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qj * bq, bq), :]
        do = do_ref[pl.ds(qj * bq, bq), :]
        o = o_ref[pl.ds(qj * bq, bq), :]
        lse = lse_ref[pl.ds(qj * bq, bq), 0]
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [bq]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [bq, bk]
        if causal:
            q_pos = qj * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        if seg_k is not None:
            seg_q = seg_q_ref[pl.ds(qj * bq, bq)]
            logits = jnp.where(seg_q[:, None] == seg_k[None, :], logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])
        dv_new = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk_new, dv_new

    if causal:
        lo = (ki * bk) // bq  # first q block that sees this kv block
    else:
        lo = 0
    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(lo, nq, body, (zeros, zeros))
    # scale moved onto the logits, so dk picks it up here (dlogits/dk = scale*q)
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _pick_block(s, target=None):
    """Largest power-of-two block ≤ target dividing s. The default block is
    env-tunable (DSTPU_FLASH_BLOCK) for per-generation retuning; 512 measured
    best on v5e at s=2048 (256 costs ~5pp MFU end-to-end, 128 ~15pp; 1024 is
    a wash; 2048 exceeds VMEM)."""
    if target is None:
        import os

        target = int(os.environ.get("DSTPU_FLASH_BLOCK", 512))
        if target < 128 or target & (target - 1):
            raise ValueError(
                f"DSTPU_FLASH_BLOCK={target} invalid: need a power of two >= 128"
            )
    b = min(target, s)
    while s % b:
        b //= 2
    return max(b, 1)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids=None,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention. q: [b, h, s, d]; k, v: [b, h_kv, s, d] → [b, h, s, d].

    ``segment_ids``: optional [b, s] int32 — packed-sequence masking happens
    IN the kernel (tokens attend only within their own segment), so packed
    pretraining keeps the flash path."""
    return _flash_core(q, k, v, segment_ids, causal, scale, interpret)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash_core(q, k, v, segment_ids, causal, scale, interpret):
    out, _ = _flash_fwd(q, k, v, segment_ids, causal, scale, interpret)
    return out


def _seg_specs(segment_ids, bq, s):
    """(extra operands, extra in_specs) for the [b, s] segment-id planes:
    a [bq] block aligned with the q block and the full [s] row."""
    if segment_ids is None:
        return [], []
    seg = segment_ids.astype(jnp.int32)
    return [seg, seg], [
        pl.BlockSpec((1, bq), lambda b_, h_, i: (b_, i)),
        pl.BlockSpec((1, s), lambda b_, h_, i: (b_, 0)),
    ]


def _flash_call(q, k, v, segment_ids, causal, scale, interpret):
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale = scale if scale is not None else d ** -0.5
    bq = _pick_block(s)
    bk = _pick_block(s)

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk)
    seg_ops, seg_specs = _seg_specs(segment_ids, bq, s)

    def entry(qr, kr, vr, *rest):
        if seg_ops:
            sq_r, sk_r, orf, lr = rest
            kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], lr.at[0, 0],
                   seg_q_ref=sq_r.at[0], seg_k_ref=sk_r.at[0])
        else:
            orf, lr = rest
            kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], lr.at[0, 0])

    out, lse = pl.pallas_call(
        # refs arrive with the leading (1, 1) block dims squeezed via .at
        entry,
        grid=(b, h, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_ // group, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_ // group, 0, 0)),
        ] + seg_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, *seg_ops)
    return out, lse


def _flash_fwd(q, k, v, segment_ids, causal, scale, interpret):
    out, lse = _flash_call(q, k, v, segment_ids, causal, scale, interpret)
    return out, (q, k, v, segment_ids, out, lse)


def _flash_bwd(causal, scale, interpret, res, g):
    q, k, v, segment_ids, out, lse = res
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    group = h // h_kv
    scale_v = scale if scale is not None else d ** -0.5
    bq = _pick_block(s)
    bk = _pick_block(s)

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk)
    seg_ops, seg_specs = _seg_specs(segment_ids, bq, s)

    def dq_entry(qr, kr, vr, orf, dor, lr, *rest):
        if seg_ops:
            sq_r, sk_r, dqr = rest
            dq_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], dor.at[0, 0],
                      lr.at[0, 0], dqr.at[0, 0], seg_q_ref=sq_r.at[0], seg_k_ref=sk_r.at[0])
        else:
            (dqr,) = rest
            dq_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], dor.at[0, 0],
                      lr.at[0, 0], dqr.at[0, 0])

    dq = pl.pallas_call(
        dq_entry,
        grid=(b, h, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_ // group, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_ // group, 0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i: (b_, h_, i, 0)),
        ] + seg_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v, out, g, lse, *seg_ops)

    # dk/dv computed per q-head then reduced over the GQA group
    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale_v, causal=causal, bq=bq, bk=bk)
    if segment_ids is None:
        dkv_seg_ops, dkv_seg_specs = [], []
    else:
        seg = segment_ids.astype(jnp.int32)
        dkv_seg_ops = [seg, seg]
        dkv_seg_specs = [
            pl.BlockSpec((1, s), lambda b_, h_, i: (b_, 0)),  # full q row
            pl.BlockSpec((1, bk), lambda b_, h_, i: (b_, i)),  # this kv block
        ]

    def dkv_entry(qr, kr, vr, orf, dor, lr, *rest):
        if dkv_seg_ops:
            sq_r, sk_r, dkr, dvr = rest
            dkv_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], dor.at[0, 0],
                       lr.at[0, 0], dkr.at[0, 0], dvr.at[0, 0],
                       seg_q_ref=sq_r.at[0], seg_k_ref=sk_r.at[0])
        else:
            dkr, dvr = rest
            dkv_kernel(qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], orf.at[0, 0], dor.at[0, 0],
                       lr.at[0, 0], dkr.at[0, 0], dvr.at[0, 0])

    dk_h, dv_h = pl.pallas_call(
        dkv_entry,
        grid=(b, h, s // bk),
        in_specs=[
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_ // group, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_ // group, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, LANES), lambda b_, h_, i: (b_, h_, 0, 0)),
        ] + dkv_seg_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        ],
        interpret=interpret,
    )(q, k, v, out, g, lse, *dkv_seg_ops)

    if group > 1:
        dk = jnp.sum(dk_h.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2).astype(k.dtype)
        dv = jnp.sum(dv_h.reshape(b, h_kv, group, s, d).astype(jnp.float32), axis=2).astype(v.dtype)
    else:
        dk, dv = dk_h, dv_h
    return dq, dk, dv, None  # no cotangent for segment_ids


_flash_core.defvjp(_flash_fwd, _flash_bwd)
