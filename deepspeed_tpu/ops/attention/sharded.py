"""Sharded long-context flash attention: head-sharded + ring (context) paths.

Two ways to run the Pallas flash kernel (ops/attention/flash_pallas.py) on a
multi-device mesh — pallas_call is opaque to the GSPMD partitioner, so both
wrap it in a fully-manual ``shard_map``:

  * :func:`head_sharded_flash` — splash-style: batch and heads are
    embarrassingly parallel for self-attention, so each device runs the
    kernel over its local (batch, head) slab and the FULL sequence. Per-device
    activations stay O(s). ALiBi slopes shard along the head axes with the
    heads they belong to.

  * :func:`ring_flash_attention` — context parallel: the SEQUENCE dimension
    itself is sharded over the ``context`` mesh axis. Each device holds a
    [b, h, s/N, d] q/k/v shard and k/v chunks rotate around the ring via
    ``jax.lax.ppermute`` (next hop issued before the current chunk's kernel,
    so the copy overlaps compute). Per-device activations drop to O(s/N) —
    the long-context enabler.

Ring numerics are BIT-IDENTICAL to one unsharded ``flash_attention`` call
(same block size), not merely close: the raw softmax state (m, l, acc) and
the raw gradient accumulators thread through the ring hops via the kernel's
carry refs (``flash_fwd_chunk``/``flash_dq_chunk``/``flash_dkv_chunk``), and
the ring schedule arranges chunk arrival in ASCENDING global order — the
same streaming order as the single kernel's grid — so every accumulation
happens in the same order on the same values:

  * forward + dq (ring A): k/v pre-rotate one hop, then device ``i`` at step
    ``t`` holds chunk ``(i + t + 1) % N`` — active causal chunks arrive
    ``0, 1, …, i`` with the diagonal LAST (statically at step N−1, so the
    causal diagonal kernel call needs no traced branch);
  * dk/dv (ring B): the q-side payload (q, out, do, lse) rotates the same
    direction, compute-before-rotate, so the home k/v chunk sees q chunks
    ``i, i+1, …, N−1`` ascending with the diagonal FIRST (step 0) — the
    single kernel's q-minor grid order.

Inactive hops skip compute under ``lax.cond`` while the ppermute stays
unconditional (collectives must be uniform across the axis). Causal-only:
a uniform rotation cannot produce ascending arrival for the non-causal
all-pairs schedule, and bitwise parity is the contract here.
"""

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.attention.flash_pallas import (
    LANES,
    flash_attention,
    flash_carry_init,
    flash_dkv_chunk,
    flash_dkv_finalize,
    flash_dq_chunk,
    flash_dq_finalize,
    flash_finalize,
    flash_fwd_chunk,
)
from deepspeed_tpu.parallel.topology import (
    BATCH_AXES,
    CONTEXT_AXIS,
    MODEL_AXIS,
    SEQUENCE_AXIS,
    get_topology,
)

HEAD_AXES = (MODEL_AXIS, SEQUENCE_AXIS)


def _divisible(topo, b, h, h_kv, s=None):
    """Whether the canonical layout divides over the mesh (batch over
    data/expert, heads over model+sequence, optionally seq over context)."""
    batch_div = topo.data_parallel_size * topo.expert_parallel_size
    head_div = topo.model_parallel_size * topo.sequence_parallel_size
    if b % batch_div or h % head_div or h_kv % head_div:
        return False
    if (h // h_kv) > 1 and (h // head_div) % (h // h_kv) != 0:
        return False  # GQA group would straddle a head shard
    if s is not None and s % topo.context_parallel_size:
        return False
    return True


def head_sharded_flash(q, k, v, causal=True, segment_ids=None, scale=None,
                       alibi_slopes=None, alibi_positions=None, window=0,
                       window_flag=None, interpret=False):
    """Flash attention with batch/head sharding under ``shard_map``.

    Pins the canonical layout (batch over data/expert, heads over
    model+sequence — the TP and post-Ulysses placements) and runs the kernel
    manually per device. ALiBi slopes ride along SHARDED over the head axes,
    so each device's kernel sees exactly its local heads' slopes. Returns
    ``None`` when the shapes don't divide over the mesh (caller falls back).
    """
    topo = get_topology()
    if topo.world_size == 1:
        return flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, scale=scale,
            alibi_slopes=alibi_slopes, alibi_positions=alibi_positions,
            window=window, window_flag=window_flag, interpret=interpret,
        )
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    if not _divisible(topo, b, h, h_kv):
        return None

    spec = P(BATCH_AXES, HEAD_AXES, None, None)
    sharding = NamedSharding(topo.mesh, spec)
    q, k, v = (jax.lax.with_sharding_constraint(x, sharding) for x in (q, k, v))

    # optional extra operands, each pinned to its manual-region placement
    extra_ops, extra_specs = [], []
    has_seg = segment_ids is not None
    if has_seg:
        seg_spec = P(BATCH_AXES, None)
        extra_ops.append(jax.lax.with_sharding_constraint(
            segment_ids, NamedSharding(topo.mesh, seg_spec)))
        extra_specs.append(seg_spec)
    has_alibi = alibi_slopes is not None
    if has_alibi:
        # the slope vector shards WITH the heads it biases
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        extra_ops.append(jax.lax.with_sharding_constraint(
            slopes, NamedSharding(topo.mesh, P(HEAD_AXES))))
        extra_specs.append(P(HEAD_AXES))
    has_pos = has_alibi and alibi_positions is not None
    if has_pos:
        pos = jnp.asarray(alibi_positions, jnp.int32)
        pos_spec = P(BATCH_AXES, None) if pos.ndim == 2 else P(None)
        extra_ops.append(pos)
        extra_specs.append(pos_spec)
    has_wf = window > 0 and window_flag is not None
    if has_wf:
        extra_ops.append(jnp.asarray(window_flag, jnp.int32))
        extra_specs.append(P())

    def body(q_, k_, v_, *rest):
        rest = list(rest)
        seg = rest.pop(0) if has_seg else None
        sl = rest.pop(0) if has_alibi else None
        pos = rest.pop(0) if has_pos else None
        wf = rest.pop(0) if has_wf else None
        return flash_attention(q_, k_, v_, causal=causal, segment_ids=seg,
                               scale=scale, alibi_slopes=sl,
                               alibi_positions=pos, window=window,
                               window_flag=wf, interpret=interpret)

    fn = jax.shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(spec, spec, spec, *extra_specs),
        out_specs=spec,
        axis_names={*BATCH_AXES, *HEAD_AXES},
        check_vma=False,
    )
    return fn(q, k, v, *extra_ops)


def head_sharded_splash(q, k, v, schedule, segment_ids=None, scale=None,
                        interpret=False):
    """Scheduled block-sparse (splash) attention with batch/head sharding.

    Same placement contract as :func:`head_sharded_flash`. The schedule's
    scalar-prefetch arrays ride INTO the manual region as operands: a
    per-head schedule ([h, nq, w]) shards over the head axes with the
    heads it drives, a shared one ([1, nq, w]) replicates. Returns ``None``
    when the shapes don't divide the mesh (caller falls back).
    """
    from deepspeed_tpu.ops.sparse_attention.splash_pallas import (
        _SplashParams, _splash_core, splash_attention,
    )

    topo = get_topology()
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    if topo.world_size == 1:
        return splash_attention(q, k, v, schedule, segment_ids=segment_ids,
                                scale=scale, interpret=interpret)
    if not _divisible(topo, b, h, h_kv):
        return None
    head_div = topo.model_parallel_size * topo.sequence_parallel_size
    per_head = schedule.num_heads > 1
    if per_head and schedule.num_heads % head_div:
        return None

    spec = P(BATCH_AXES, HEAD_AXES, None, None)
    sharding = NamedSharding(topo.mesh, spec)
    q, k, v = (jax.lax.with_sharding_constraint(x, sharding) for x in (q, k, v))

    seg_mode = "none"
    seg = None
    if schedule.segment_ids is not None:
        if segment_ids is not None:
            raise ValueError("schedule already carries segment ids")
        seg_mode = "schedule"
        seg = jnp.broadcast_to(
            jnp.asarray(schedule.segment_ids, jnp.int32)[None], (b, s))
    elif segment_ids is not None:
        seg_mode = "all"
        seg = jnp.asarray(segment_ids, jnp.int32)
    params = _SplashParams(
        bq=schedule.block_q, bk=schedule.block_kv,
        causal=schedule.causal, window=schedule.window,
        scale=float(scale if scale is not None else d ** -0.5),
        has_partial=schedule.num_partial > 0, seg_mode=seg_mode,
        interpret=interpret, vmem_limit=None,
    )
    sched_spec = P(HEAD_AXES, None, None) if per_head else P(None, None, None)
    sched_ops = [jnp.asarray(a) for a in (
        schedule.kv_index, schedule.step_kind,
        schedule.q_index, schedule.step_kind_t)]
    base = jnp.zeros((1,), jnp.int32)

    has_seg = seg is not None
    seg_specs = [P(BATCH_AXES, None)] if has_seg else []
    seg_ops = [seg] if has_seg else []

    def body(q_, k_, v_, kvi_, kind_, kvi_t_, kind_t_, base_, *rest):
        seg_ = rest[0] if has_seg else None
        return _splash_core(q_, k_, v_, seg_, kvi_, kind_, kvi_t_, kind_t_,
                            base_, params)

    fn = jax.shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(spec, spec, spec, sched_spec, sched_spec, sched_spec,
                  sched_spec, P(None), *seg_specs),
        out_specs=spec,
        axis_names={*BATCH_AXES, *HEAD_AXES},
        check_vma=False,
    )
    return fn(q, k, v, *sched_ops, base, *seg_ops)


# ---------------------------------------------------------------------------
# Ring (context-parallel) flash attention
# ---------------------------------------------------------------------------


def _rotate(payload, axis_name, perm):
    """One ring hop: every leaf moves to the previous device (so each device
    RECEIVES the next chunk index). Uniform — never under a cond."""
    return jax.tree_util.tree_map(
        lambda x: jax.lax.ppermute(x, axis_name, perm), payload
    )


def _kv_payload(k, v, seg_k, kpos):
    p = {"k": k, "v": v}
    if seg_k is not None:
        p["seg"] = seg_k
    if kpos is not None:
        p["kpos"] = kpos
    return p


def _lane_slopes(slopes, h):
    if slopes is None:
        return None
    return jnp.broadcast_to(
        jnp.asarray(slopes, jnp.float32)[:, None], (h, LANES)
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _ring_core(q, k, v, segment_ids, slopes, axis_name, n, scale, block,
               interpret):
    out, _ = _ring_fwd(q, k, v, segment_ids, slopes, axis_name, n, scale,
                       block, interpret)
    return out


def _ring_fwd(q, k, v, segment_ids, slopes, axis_name, n, scale, block,
              interpret):
    b, h, sc, d = q.shape
    i = jax.lax.axis_index(axis_name)
    perm = [(r, (r - 1) % n) for r in range(n)]
    kpos = None
    if slopes is not None:
        # global key positions rotate with their chunk: slope·kpos must see
        # the same absolute positions as the unsharded kernel
        kpos = jnp.broadcast_to(
            (i * sc + jnp.arange(sc, dtype=jnp.int32))[None], (b, sc)
        )
    slopes_lane = _lane_slopes(slopes, h)
    carry = flash_carry_init(b, h, sc, d)
    payload = _rotate(_kv_payload(k, v, segment_ids, kpos), axis_name, perm)
    for t in range(n):
        src = (i + t + 1) % n  # chunk index this hop delivered
        nxt = _rotate(payload, axis_name, perm) if t < n - 1 else payload
        seg_pair = ((segment_ids, payload["seg"])
                    if segment_ids is not None else None)
        al = (slopes_lane, payload["kpos"]) if slopes is not None else None
        if t == n - 1:
            # the diagonal lands at the LAST step for every device —
            # statically, so the causal kernel call needs no traced branch
            carry = flash_fwd_chunk(
                q, payload["k"], payload["v"], carry, segment_ids=seg_pair,
                alibi=al, causal=True, scale=scale, block=block,
                interpret=interpret,
            )
        else:
            kc, vc = payload["k"], payload["v"]

            def _step(c, kc=kc, vc=vc, seg_pair=seg_pair, al=al):
                return flash_fwd_chunk(
                    q, kc, vc, c, segment_ids=seg_pair, alibi=al,
                    causal=False, scale=scale, block=block,
                    interpret=interpret,
                )

            carry = jax.lax.cond(src < i, _step, lambda c: c, carry)
        payload = nxt
    out, lse = flash_finalize(carry, q.dtype)

    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    q = checkpoint_name(q, "flash_qkv")
    k = checkpoint_name(k, "flash_qkv")
    v = checkpoint_name(v, "flash_qkv")
    return out, (q, k, v, segment_ids, slopes, out, lse)


def _ring_bwd(axis_name, n, scale, block, interpret, res, g):
    q, k, v, segment_ids, slopes, out, lse = res
    b, h, sc, d = q.shape
    h_kv = k.shape[1]
    scale_v = scale if scale is not None else d ** -0.5
    i = jax.lax.axis_index(axis_name)
    perm = [(r, (r - 1) % n) for r in range(n)]
    kpos_home = None
    if slopes is not None:
        kpos_home = jnp.broadcast_to(
            (i * sc + jnp.arange(sc, dtype=jnp.int32))[None], (b, sc)
        )
    slopes_lane = _lane_slopes(slopes, h)

    # ---- ring A: dq. Same schedule as forward — k/v rotate, the raw f32 dq
    # accumulator stays home and sees chunks 0..i ascending, diagonal last.
    dq_acc = jnp.zeros((b, h, sc, d), jnp.float32)
    payload = _rotate(
        _kv_payload(k, v, segment_ids, kpos_home), axis_name, perm
    )
    for t in range(n):
        src = (i + t + 1) % n
        nxt = _rotate(payload, axis_name, perm) if t < n - 1 else payload
        seg_pair = ((segment_ids, payload["seg"])
                    if segment_ids is not None else None)
        al = (slopes_lane, payload["kpos"]) if slopes is not None else None
        if t == n - 1:
            dq_acc = flash_dq_chunk(
                q, payload["k"], payload["v"], out, g, lse, dq_acc,
                segment_ids=seg_pair, alibi=al, causal=True, scale=scale,
                block=block, interpret=interpret,
            )
        else:
            kc, vc = payload["k"], payload["v"]

            def _step(acc, kc=kc, vc=vc, seg_pair=seg_pair, al=al):
                return flash_dq_chunk(
                    q, kc, vc, out, g, lse, acc, segment_ids=seg_pair,
                    alibi=al, causal=False, scale=scale, block=block,
                    interpret=interpret,
                )

            dq_acc = jax.lax.cond(src < i, _step, lambda acc: acc, dq_acc)
        payload = nxt
    dq = flash_dq_finalize(dq_acc, scale_v, q.dtype)

    # ---- ring B: dk/dv. The q side (q, out, do, lse, seg_q) rotates the
    # SAME direction, compute-before-rotate: the home kv chunk sees q chunks
    # i..N-1 ascending, diagonal first (step 0, static) — the kernel's
    # q-minor grid order. ALiBi kpos is the home chunk's — it never moves.
    dk_acc = jnp.zeros((b, h, sc, d), jnp.float32)
    dv_acc = jnp.zeros((b, h, sc, d), jnp.float32)
    al_home = (slopes_lane, kpos_home) if slopes is not None else None
    qpay = {"q": q, "o": out, "do": g, "lse": lse}
    if segment_ids is not None:
        qpay["seg"] = segment_ids
    for t in range(n):
        nxt = _rotate(qpay, axis_name, perm) if t < n - 1 else qpay
        seg_pair = ((qpay["seg"], segment_ids)
                    if segment_ids is not None else None)
        if t == 0:
            dk_acc, dv_acc = flash_dkv_chunk(
                qpay["q"], k, v, qpay["o"], qpay["do"], qpay["lse"],
                dk_acc, dv_acc, segment_ids=seg_pair, alibi=al_home,
                causal=True, scale=scale, block=block, interpret=interpret,
            )
        else:
            src = (i + t) % n  # q chunk visiting this hop
            qc, oc, doc, lsec = qpay["q"], qpay["o"], qpay["do"], qpay["lse"]

            def _step(accs, qc=qc, oc=oc, doc=doc, lsec=lsec,
                      seg_pair=seg_pair):
                return flash_dkv_chunk(
                    qc, k, v, oc, doc, lsec, accs[0], accs[1],
                    segment_ids=seg_pair, alibi=al_home, causal=False,
                    scale=scale, block=block, interpret=interpret,
                )

            dk_acc, dv_acc = jax.lax.cond(
                src > i, _step, lambda accs: accs, (dk_acc, dv_acc)
            )
        qpay = nxt
    dk, dv = flash_dkv_finalize(dk_acc, dv_acc, scale_v, k.dtype, h_kv)
    return dq, dk, dv, None, None


_ring_core.defvjp(_ring_fwd, _ring_bwd)


def ring_attention_local(q, k, v, segment_ids=None, scale=None,
                         alibi_slopes=None, axis_name=CONTEXT_AXIS,
                         axis_size=None, block=None, interpret=False):
    """The per-device ring body — call INSIDE an enclosing ``shard_map``
    whose ``axis_name`` axis shards the sequence dimension of q/k/v
    ([b, h, s/N, d] locals). Causal only. ``segment_ids`` is the local
    [b, s/N] id plane; ``alibi_slopes`` the full (local-head) slope vector.
    Differentiable (custom_vjp: two gradient rings)."""
    n = axis_size if axis_size is not None else jax.lax.axis_size(axis_name)
    if n == 1:
        return flash_attention(q, k, v, causal=True, segment_ids=segment_ids,
                               scale=scale, alibi_slopes=alibi_slopes,
                               interpret=interpret)
    slopes = (jnp.asarray(alibi_slopes, jnp.float32)
              if alibi_slopes is not None else None)
    return _ring_core(q, k, v, segment_ids, slopes, axis_name, int(n), scale,
                      block, interpret)


def ring_flash_attention(q, k, v, causal=True, segment_ids=None, scale=None,
                         alibi_slopes=None, window=0, block=None,
                         interpret=False):
    """Context-parallel flash attention over the ``context`` mesh axis.

    q: [b, h, s, d] GLOBAL arrays (inside jit, GSPMD-placed); the wrapper
    pins sequence over ``context`` (plus the canonical batch/head axes) and
    runs the ring manually per device. Per-device activation footprint is
    O(s/N). Bitwise-identical to the unsharded kernel when the block size
    matches (``block`` ≤ s/N; the default env/1024 pick applies per chunk,
    so pin DSTPU_FLASH_BLOCK ≤ s/N when comparing).

    Raises on the structurally-unsupported cases rather than silently
    falling back: non-causal (a uniform ring rotation cannot visit chunks in
    ascending order bidirectionally), sliding windows (local-position band
    masks are wrong across chunks), and shapes that don't divide the mesh.
    """
    if not causal:
        raise NotImplementedError(
            "ring_flash_attention: causal=False not supported — the ring "
            "schedule needs ascending chunk arrival, which a uniform "
            "rotation only yields for the causal triangle"
        )
    if window:
        raise NotImplementedError(
            "ring_flash_attention: sliding window not supported on the ring "
            "path (band masks are global-position; use head sharding)"
        )
    topo = get_topology()
    n = topo.context_parallel_size
    if n == 1:
        out = head_sharded_flash(
            q, k, v, causal=True, segment_ids=segment_ids, scale=scale,
            alibi_slopes=alibi_slopes, interpret=interpret,
        )
        if out is None:
            raise ValueError(
                "ring_flash_attention: context=1 and batch/head shapes "
                f"{q.shape} do not divide the mesh {topo.mesh.shape}"
            )
        return out
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    if not _divisible(topo, b, h, h_kv, s=s):
        raise ValueError(
            f"ring_flash_attention: shapes b={b} h={h} h_kv={h_kv} s={s} do "
            f"not divide mesh {dict(topo.mesh.shape)}"
        )

    spec = P(BATCH_AXES, HEAD_AXES, CONTEXT_AXIS, None)
    sharding = NamedSharding(topo.mesh, spec)
    q, k, v = (jax.lax.with_sharding_constraint(x, sharding) for x in (q, k, v))

    extra_ops, extra_specs = [], []
    has_seg = segment_ids is not None
    if has_seg:
        seg_spec = P(BATCH_AXES, CONTEXT_AXIS)
        extra_ops.append(jax.lax.with_sharding_constraint(
            segment_ids, NamedSharding(topo.mesh, seg_spec)))
        extra_specs.append(seg_spec)
    has_alibi = alibi_slopes is not None
    if has_alibi:
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        extra_ops.append(jax.lax.with_sharding_constraint(
            slopes, NamedSharding(topo.mesh, P(HEAD_AXES))))
        extra_specs.append(P(HEAD_AXES))

    def body(q_, k_, v_, *rest):
        rest = list(rest)
        seg = rest.pop(0) if has_seg else None
        sl = rest.pop(0) if has_alibi else None
        return ring_attention_local(
            q_, k_, v_, segment_ids=seg, scale=scale, alibi_slopes=sl,
            axis_name=CONTEXT_AXIS, axis_size=n, block=block,
            interpret=interpret,
        )

    fn = jax.shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(spec, spec, spec, *extra_specs),
        out_specs=spec,
        axis_names={*BATCH_AXES, *HEAD_AXES, CONTEXT_AXIS},
        check_vma=False,
    )
    return fn(q, k, v, *extra_ops)
