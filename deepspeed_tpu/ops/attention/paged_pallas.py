"""Paged (block-table) attention for the ragged inference batch.

TPU-native analogue of the reference blocked-flash ragged kernels
(``inference/v2/kernels/ragged_ops/blocked_flash``, ``linear_blocked_kv_rotary``):
every query token carries its own block table and context length, so one
call serves a fused batch of decode tokens and prompt chunks from different
sequences (the Dynamic SplitFuse execution model).

Layout:
  q            [T, nh, d]       — packed new-token queries
  k/v pool     [NB, bs, nkv, d] — paged block pool (token-major). The engine
                 passes a FLAT multi-layer view ([L*NBp, bs, nkv, d]) with
                 layer-offset block tables, so the pool never needs a
                 per-layer slice (slicing a scan-carried cache copied 200 MB
                 per layer-step — the round-4 serving bottleneck, PERF.md)
  block_tables per token [T, B] or per row [R, B]
  q_pos        global position of each query in its sequence

Implementations:
  * ``paged_decode_attention_dense`` / ``paged_chunk_attention`` — plain XLA
    (block gather + masked einsum). Profiled fastest on the bench shapes:
    per-Pallas-program launch overhead (~9 us) dominates grid kernels at
    serving grids, while the gather is one fused op.
  * ``paged_attention`` — the (T, B)-grid Pallas kernel (one program per
    (token, context-block), scalar-prefetched DMA). Kept for the per-token
    fused path and as the ``kernel`` impl option.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def paged_attention_reference(q, k_cache, v_cache, block_tables, q_pos, trash_block,
                              window: int = 0, scale=None, k_scale=None,
                              v_scale=None):
    """jnp reference: per-token context gather + masked softmax, mapped over
    tokens so peak memory is one context window ([S, nkv, d]) rather than T
    of them. Shapes as module docstring; returns [T, nh, d]. ``window``:
    static sliding-window band over sequence positions (mistral/starcoder2;
    band convention shared via core.window_too_far). ``k_scale``/``v_scale``
    [NB, bs, nkv]: per-vector fp32 dequant planes for an int8 pool
    (block_quant.quantize_kv)."""
    T, nh, d = q.shape
    NB, bs, nkv, _ = k_cache.shape
    B = block_tables.shape[1]
    S = B * bs
    group = nh // nkv
    kpos = jnp.arange(S, dtype=jnp.int32)

    def one_token(args):
        qt, bt, pos = args  # [nh, d], [B], scalar
        k_ctx = k_cache[bt].reshape(S, nkv, d).astype(jnp.float32)
        v_ctx = v_cache[bt].reshape(S, nkv, d).astype(jnp.float32)
        if k_scale is not None:
            k_ctx = k_ctx * k_scale[bt].reshape(S, nkv)[..., None]
            v_ctx = v_ctx * v_scale[bt].reshape(S, nkv)[..., None]
        blk_valid = jnp.repeat(bt != trash_block, bs)
        mask = (kpos <= pos) & blk_valid  # [S]
        if window:
            from deepspeed_tpu.ops.attention.core import window_too_far

            mask = mask & jnp.logical_not(window_too_far(pos, kpos, window))
        qg = qt.reshape(nkv, group, d).astype(jnp.float32)
        scores = jnp.einsum("ngd,snd->ngs", qg, k_ctx) * (scale if scale is not None else d**-0.5)
        scores = jnp.where(mask[None, None], scores, NEG_INF)
        w = jax.nn.softmax(scores, axis=-1)
        # fully-masked token (all-trash padding): return 0 like the kernel
        # does, not the uniform-softmax mean of trash V
        w = jnp.where(jnp.any(mask), w, 0.0)
        return jnp.einsum("ngs,snd->ngd", w, v_ctx).reshape(nh, d)

    out = jax.lax.map(one_token, (q, block_tables, q_pos), batch_size=min(T, 32))
    return out.astype(q.dtype)


def _paged_kernel(
    *refs, bs, nh, nkv, d, B, E=0, window=0, scale=None, int8=False,
    has_limit=False
):
    """(T, B [+1])-grid kernel body. ``refs`` layout — scalar prefetch
    (SMEM): bt [T, B], qpos [T], trash [1], limit [T] if ``has_limit`` —
    then tensor blocks (VMEM): epos (1, E) if ``E``, q (1, nh, d),
    k (1, bs, nkv, d), v, ks/vs scale planes (1, bs, nkv) if ``int8``,
    ke/ve (1, E, nkv, d) if ``E`` — then o (1, nh, d) and the m/l/acc
    flash scratch.

    ``trash`` rides as a prefetch operand (not a static kwarg) because the
    engine's flat multi-layer views use layer-offset trash ids — traced
    values inside the fori_loop layer driver. ``E`` extra columns are this
    step/round's NOT-YET-CACHED K/V (the write-after-read protocol), kept
    in compute dtype — only the pool payload is int8; dequant happens here
    in fp32 right after the halved-HBM block DMA, so the VPU multiply
    hides under the transfer (the EQuARX argument applied to HBM)."""
    it = iter(refs)
    bt_ref, qpos_ref, trash_ref = next(it), next(it), next(it)
    limit_ref = next(it) if has_limit else None
    epos_ref = next(it) if E else None
    q_ref, k_ref, v_ref = next(it), next(it), next(it)
    ks_ref = next(it) if int8 else None
    vs_ref = next(it) if int8 else None
    ke_ref = next(it) if E else None
    ve_ref = next(it) if E else None
    o_ref = next(it)
    m_scr, l_scr, acc_scr = next(it), next(it), next(it)

    t = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    group = nh // nkv
    scale = scale if scale is not None else d**-0.5
    trash = trash_ref[0]
    qpos = qpos_ref[t]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32) * scale  # [nh, d]

    def flash_accum(k, v, valid):
        """One online-softmax accumulation sweep: k/v [nk, nkv, d] fp32,
        valid [1, nk]. Disjoint per-kv-head scratch slices, so reading
        m/l once up front is safe."""
        m_prev = m_scr[...]  # [nh, 128] (col 0 meaningful)
        l_prev = l_scr[...]
        for n in range(nkv):
            qn = q[n * group : (n + 1) * group]  # [group, d]
            kn = k[:, n, :]  # [nk, d]
            vn = v[:, n, :]
            s = jax.lax.dot_general(
                qn, kn, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )  # [group, nk]
            s = jnp.where(valid, s, NEG_INF)
            m_p = m_prev[n * group : (n + 1) * group, :1]  # [group, 1]
            m_new = jnp.maximum(m_p, jnp.max(s, axis=1, keepdims=True))
            alpha = jnp.exp(m_p - m_new)
            p = jnp.exp(s - m_new)  # [group, nk]
            l_p = l_prev[n * group : (n + 1) * group, :1]
            l_new = l_p * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc = acc_scr[n * group : (n + 1) * group, :]  # [group, d]
            acc_scr[n * group : (n + 1) * group, :] = acc * alpha + jax.lax.dot_general(
                p, vn, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
            )
            m_scr[n * group : (n + 1) * group, :1] = m_new
            l_scr[n * group : (n + 1) * group, :1] = l_new

    def pool_block():
        jb = jnp.minimum(j, B - 1)  # clamped: the j == B step is the extras
        blk = bt_ref[t, jb]
        kpos = jb * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)  # [1, bs]
        if has_limit:
            # explicit pool window (write-after-read: the pool holds only
            # positions below the step/round start); qpos < 0 marks padded
            # query slots that must see nothing
            valid = (kpos < limit_ref[t]) & (qpos >= 0)
        else:
            valid = kpos <= qpos
        valid = valid & (blk != trash)
        if window:
            from deepspeed_tpu.ops.attention.core import window_too_far

            valid = valid & jnp.logical_not(window_too_far(qpos, kpos, window))
        k = k_ref[0].astype(jnp.float32)  # [bs, nkv, d]
        v = v_ref[0].astype(jnp.float32)
        if int8:
            k = k * ks_ref[0][..., None]
            v = v * vs_ref[0][..., None]
        flash_accum(k, v, valid)

    if E:
        @pl.when(j < B)
        def _pool():
            pool_block()

        @pl.when(j == B)
        def _extra():
            epos = epos_ref[...]  # [1, E]
            valid = (epos >= 0) & (epos <= qpos)
            if window:
                from deepspeed_tpu.ops.attention.core import window_too_far

                valid = valid & jnp.logical_not(window_too_far(qpos, epos, window))
            flash_accum(
                ke_ref[0].astype(jnp.float32), ve_ref[0].astype(jnp.float32), valid
            )
    else:
        pool_block()

    @pl.when(j == nj - 1)
    def _finish():
        l = l_scr[:, :1]
        # fully-masked token (all-trash padding): m never left NEG_INF and
        # every p degenerated to exp(0) — emit 0, matching the reference
        any_valid = m_scr[:, :1] > NEG_INF * 0.5
        out = jnp.where(any_valid, acc_scr[...] / jnp.maximum(l, 1e-30), 0.0)
        o_ref[0] = out.astype(o_ref.dtype)


PAGED_ATTENTION_IMPLS = ("auto", "kernel", "dense", "reference")


def paged_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_pos: jax.Array,
    trash_block,
    impl: Optional[str] = None,
    interpret: bool = False,
    window: int = 0,
    scale: Optional[float] = None,
    k_scale=None,
    v_scale=None,
    extra_kv=None,
    pool_limit=None,
) -> jax.Array:
    """Dispatching entry point for paged decode attention — the engine's
    decode hot paths (single-step, fused rounds, spec verify) all call this.

    ``impl``: "auto" (None) resolves to the Pallas kernel on TPU for
    kernel-tiled head dims and the dense XLA gather elsewhere; "kernel",
    "dense", "reference" force a path; anything else raises (a typo must
    not silently fall back — the seam that kept the kernel unreachable).
    ``trash_block`` may be a traced scalar (layer-offset trash ids).
    ``k_scale``/``v_scale`` [NB, bs, nkv] fp32: dequant planes, required
    iff the pool payload is int8 (block_quant.quantize_kv) — the kernel
    dequantizes in-VMEM after the halved block DMA. ``extra_kv`` =
    (ke [T, E, nkv, d], ve, epos [T, E]) and ``pool_limit`` [T]: the
    write-after-read protocol (see paged_decode_attention_dense); extras
    stay in compute dtype. ``window``: static sliding-window band;
    ``scale``: softmax scale override (gpt_neo's unscaled logits)."""
    T, nh, d = q.shape
    NB, bs, nkv, _ = k_cache.shape
    int8_pool = k_cache.dtype == jnp.int8
    if int8_pool and (k_scale is None or v_scale is None):
        raise ValueError(
            "paged_attention: int8 k/v pools need k_scale and v_scale planes"
        )
    if not int8_pool and (k_scale is not None or v_scale is not None):
        raise ValueError(
            "paged_attention: k_scale/v_scale given but the pool payload is "
            f"{k_cache.dtype}, not int8"
        )
    if impl is None or impl == "auto":
        impl = "kernel" if (
            jax.default_backend() == "tpu" and d in (64, 128, 256)
        ) else "dense"
    if impl == "reference":
        if extra_kv is not None or pool_limit is not None:
            raise ValueError(
                "paged_attention: impl='reference' serves the plain parity "
                "form only (no extra_kv/pool_limit)"
            )
        return paged_attention_reference(
            q, k_cache, v_cache, block_tables, q_pos, trash_block,
            window=window, scale=scale, k_scale=k_scale, v_scale=v_scale,
        )
    if impl == "dense":
        return paged_decode_attention_dense(
            q, k_cache, v_cache, block_tables, q_pos, trash_block,
            window=window, scale=scale, extra_kv=extra_kv,
            pool_limit=pool_limit, k_scale=k_scale, v_scale=v_scale,
        )
    if impl != "kernel":
        raise ValueError(
            f"paged_attention: unknown impl {impl!r} "
            f"(expected one of {PAGED_ATTENTION_IMPLS})"
        )

    # kernel path; off-TPU it only runs interpreted (CPU tests)
    interpret = bool(interpret) or jax.default_backend() != "tpu"
    B = block_tables.shape[1]
    has_limit = pool_limit is not None
    E = 0 if extra_kv is None else int(extra_kv[0].shape[1])
    num_scalar = 3 + (1 if has_limit else 0)

    if E:
        blk_idx = lambda t, j, *s: (s[0][t, jnp.minimum(j, B - 1)], 0, 0, 0)
    else:
        blk_idx = lambda t, j, *s: (s[0][t, j], 0, 0, 0)
    in_specs = []
    if E:
        in_specs.append(pl.BlockSpec((1, E), lambda t, j, *s: (t, 0)))
    in_specs.append(pl.BlockSpec((1, nh, d), lambda t, j, *s: (t, 0, 0)))
    in_specs.append(pl.BlockSpec((1, bs, nkv, d), blk_idx))
    in_specs.append(pl.BlockSpec((1, bs, nkv, d), blk_idx))
    if int8_pool:
        scale_idx = lambda t, j, *s: blk_idx(t, j, *s)[:3]
        in_specs.append(pl.BlockSpec((1, bs, nkv), scale_idx))
        in_specs.append(pl.BlockSpec((1, bs, nkv), scale_idx))
    if E:
        in_specs.append(pl.BlockSpec((1, E, nkv, d), lambda t, j, *s: (t, 0, 0, 0)))
        in_specs.append(pl.BlockSpec((1, E, nkv, d), lambda t, j, *s: (t, 0, 0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=num_scalar,
        grid=(T, B + (1 if E else 0)),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nh, d), lambda t, j, *s: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel, bs=bs, nh=nh, nkv=nkv, d=d, B=B, E=E,
        window=int(window), scale=scale, int8=int8_pool, has_limit=has_limit,
    )
    operands = [
        block_tables.astype(jnp.int32),
        q_pos.astype(jnp.int32),
        jnp.asarray(trash_block, jnp.int32).reshape(1),
    ]
    if has_limit:
        operands.append(jnp.asarray(pool_limit, jnp.int32).reshape(T))
    if E:
        operands.append(jnp.asarray(extra_kv[2], jnp.int32).reshape(T, E))
    operands.append(q)
    operands.extend([k_cache, v_cache])
    if int8_pool:
        operands.extend([k_scale, v_scale])
    if E:
        operands.extend([extra_kv[0], extra_kv[1]])
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, nh, d), q.dtype),
        # pre-0.5 jax spells it TPUCompilerParams
        compiler_params=getattr(
            pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
        )(
            # tokens are independent (scratch re-inits at j==0) → megacore
            # can split the T dim; only the block dim accumulates
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(*operands)


def paged_decode_attention_dense(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_pos: jax.Array,
    trash_block,
    window: int = 0,
    scale: Optional[float] = None,
    extra_kv=None,
    pool_limit=None,
    k_scale=None,
    v_scale=None,
) -> jax.Array:
    """Decode attention as plain XLA (block gather + masked einsum) — no
    Pallas. On the profile (PERF.md serving roofline) per-program launch
    overhead (~9 us x grid size) dominates grid kernels at decode shapes,
    while the whole-table gather is a single fused op; the gather over-reads
    unallocated (trash) slots but stays ahead until contexts are long.
    GSPMD shards it (cache on the kv-head dim) without a shard_map island.
    q [R, nh, d], tables [R, B] per-row; ``trash_block`` may be traced
    (layer-offset trash ids).

    ``extra_kv`` = (ke [R, E, nkv, d], ve, epos [R, E]): NOT-YET-CACHED
    tokens (this step's / this round's K/V), appended as extra score
    columns; epos are their global positions, -1 = invalid. ``pool_limit``
    [R]: pool positions >= pool_limit are masked (default q_pos + 1, i.e.
    the causal <=). The pool is gathered BEFORE this step's writes — a
    scatter-then-gather of the same pool made XLA materialize a full cache
    copy per layer-step (PERF.md serving roofline, the round-4 bottleneck).
    ``k_scale``/``v_scale`` [NB, bs, nkv] fp32: int8-pool dequant planes
    (extras stay in compute dtype — only the pool payload is quantized).
    """
    R, nh, d = q.shape
    NB, bs, nkv, _ = k_cache.shape
    B = block_tables.shape[1]
    S = B * bs
    group = nh // nkv
    k_ctx = (
        k_cache[block_tables].transpose(0, 3, 1, 2, 4).reshape(R, nkv, S, d)
    ).astype(jnp.float32)
    v_ctx = (
        v_cache[block_tables].transpose(0, 3, 1, 2, 4).reshape(R, nkv, S, d)
    ).astype(jnp.float32)
    if k_scale is not None:
        k_ctx = k_ctx * k_scale[block_tables].transpose(0, 3, 1, 2).reshape(
            R, nkv, S
        )[..., None]
        v_ctx = v_ctx * v_scale[block_tables].transpose(0, 3, 1, 2).reshape(
            R, nkv, S
        )[..., None]
    kpos = jnp.arange(S, dtype=jnp.int32)
    limit = (q_pos + 1) if pool_limit is None else pool_limit
    mask = (kpos[None] < limit[:, None]) & jnp.repeat(
        block_tables != trash_block, bs, axis=1
    )  # [R, S]
    if window:
        from deepspeed_tpu.ops.attention.core import window_too_far

        mask = mask & jnp.logical_not(
            window_too_far(q_pos[:, None], kpos[None], window)
        )
    qg = q.reshape(R, nkv, group, d).astype(jnp.float32) * (
        scale if scale is not None else d**-0.5
    )
    s = jnp.einsum("rngd,rnsd->rngs", qg, k_ctx)
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    if extra_kv is not None:
        ke, ve, epos = extra_kv
        E = ke.shape[1]
        emask = (epos >= 0) & (epos <= q_pos[:, None])  # [R, E]
        if window:
            from deepspeed_tpu.ops.attention.core import window_too_far

            emask = emask & jnp.logical_not(
                window_too_far(q_pos[:, None], epos, window)
            )
        ke32 = ke.transpose(0, 2, 1, 3).astype(jnp.float32)  # [R, nkv, E, d]
        ve32 = ve.transpose(0, 2, 1, 3).astype(jnp.float32)
        se = jnp.einsum("rngd,rned->rnge", qg, ke32)
        se = jnp.where(emask[:, None, None], se, NEG_INF)
        s = jnp.concatenate([s, se], axis=-1)
        any_valid = jnp.any(mask, axis=1) | jnp.any(emask, axis=1)
        w = jax.nn.softmax(s, axis=-1)
        w = jnp.where(any_valid[:, None, None, None], w, 0.0)
        out = jnp.einsum("rngs,rnsd->rngd", w[..., :S], v_ctx) + jnp.einsum(
            "rnge,rned->rngd", w[..., S:], ve32
        )
        return out.reshape(R, nh, d).astype(q.dtype)
    w = jax.nn.softmax(s, axis=-1)
    w = jnp.where(jnp.any(mask, axis=1)[:, None, None, None], w, 0.0)
    out = jnp.einsum("rngs,rnsd->rngd", w, v_ctx)
    return out.reshape(R, nh, d).astype(q.dtype)


def paged_chunk_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    row_tables: jax.Array,
    q_pos: jax.Array,
    trash_block,
    window: int = 0,
    scale: Optional[float] = None,
    new_kv=None,
    pool_limit=None,
    k_scale=None,
    v_scale=None,
) -> jax.Array:
    """Prefill-chunk attention: Rc rows x tq new tokens each, every row's
    tokens sharing that ROW's block table (q [Rc, tq, nh, d],
    row_tables [Rc, B], q_pos [Rc, tq] global positions, -1 = padding).
    One context gather per ROW (not per token — the (T, B)-grid kernel's
    launch-overhead failure mode at prefill grids) then a dense masked
    softmax; chunk MXU work is real matmuls. Padded tail tokens (q_pos < 0)
    emit exactly 0.

    ``new_kv`` = (ke [Rc, tq, nkv, d], ve): THIS chunk's not-yet-cached
    K/V — in-chunk attention runs causally over them while the pool covers
    only positions < ``pool_limit`` [Rc] (the chunk's start). Without
    new_kv the pool is assumed to already hold the chunk (legacy form) and
    pool_limit defaults to the causal <=. ``k_scale``/``v_scale``
    [NB, bs, nkv] fp32: int8-pool dequant planes (new_kv stays in compute
    dtype)."""
    Rc, tq, nh, d = q.shape
    NB, bs, nkv, _ = k_cache.shape
    B = row_tables.shape[1]
    S = B * bs
    group = nh // nkv
    k_ctx = (
        k_cache[row_tables].transpose(0, 3, 1, 2, 4).reshape(Rc, nkv, S, d)
    ).astype(jnp.float32)
    v_ctx = (
        v_cache[row_tables].transpose(0, 3, 1, 2, 4).reshape(Rc, nkv, S, d)
    ).astype(jnp.float32)
    if k_scale is not None:
        k_ctx = k_ctx * k_scale[row_tables].transpose(0, 3, 1, 2).reshape(
            Rc, nkv, S
        )[..., None]
        v_ctx = v_ctx * v_scale[row_tables].transpose(0, 3, 1, 2).reshape(
            Rc, nkv, S
        )[..., None]
    kpos = jnp.arange(S, dtype=jnp.int32)
    blk_valid = jnp.repeat(row_tables != trash_block, bs, axis=1)  # [Rc, S]
    if pool_limit is None:
        pool_ok = kpos[None, None] <= q_pos[:, :, None]
    else:
        pool_ok = jnp.broadcast_to(
            (kpos[None] < pool_limit[:, None])[:, None], (Rc, tq, S)
        )
    mask = pool_ok & (q_pos[:, :, None] >= 0) & blk_valid[:, None]  # [Rc, tq, S]
    if window:
        from deepspeed_tpu.ops.attention.core import window_too_far

        mask = mask & jnp.logical_not(
            window_too_far(q_pos[:, :, None], kpos[None, None], window)
        )
    qg = q.reshape(Rc, tq, nkv, group, d).astype(jnp.float32) * (
        scale if scale is not None else d**-0.5
    )
    s = jnp.einsum("rtngd,rnsd->rntgs", qg, k_ctx)
    s = jnp.where(mask[:, None, :, None], s, NEG_INF)
    if new_kv is not None:
        ke, ve = new_kv
        # in-chunk causal: key j visible to query i iff 0 <= pos_j <= pos_i
        cmask = (
            (q_pos[:, None, :] >= 0)
            & (q_pos[:, :, None] >= 0)
            & (q_pos[:, None, :] <= q_pos[:, :, None])
        )  # [Rc, tq(i), tq(j)]
        if window:
            from deepspeed_tpu.ops.attention.core import window_too_far

            cmask = cmask & jnp.logical_not(
                window_too_far(q_pos[:, :, None], q_pos[:, None, :], window)
            )
        ke32 = ke.transpose(0, 2, 1, 3).astype(jnp.float32)  # [Rc, nkv, tq, d]
        ve32 = ve.transpose(0, 2, 1, 3).astype(jnp.float32)
        sc = jnp.einsum("rtngd,rnjd->rntgj", qg, ke32)
        sc = jnp.where(cmask[:, None, :, None], sc, NEG_INF)
        s = jnp.concatenate([s, sc], axis=-1)
        any_valid = jnp.any(mask, axis=2) | jnp.any(cmask, axis=2)  # [Rc, tq]
        w = jax.nn.softmax(s, axis=-1)
        w = jnp.where(any_valid[:, None, :, None, None], w, 0.0)
        out = jnp.einsum("rntgs,rnsd->rtngd", w[..., :S], v_ctx) + jnp.einsum(
            "rntgj,rnjd->rtngd", w[..., S:], ve32
        )
        return out.reshape(Rc, tq, nh, d).astype(q.dtype)
    w = jax.nn.softmax(s, axis=-1)
    w = jnp.where(jnp.any(mask, axis=2)[:, None, :, None, None], w, 0.0)
    out = jnp.einsum("rntgs,rnsd->rtngd", w, v_ctx)
    return out.reshape(Rc, tq, nh, d).astype(q.dtype)
