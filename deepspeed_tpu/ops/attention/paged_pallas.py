"""Paged (block-table) attention kernel for the ragged inference batch.

TPU-native analogue of the reference blocked-flash ragged kernels
(``inference/v2/kernels/ragged_ops/blocked_flash``, ``linear_blocked_kv_rotary``):
every query token carries its own block table and context length, so one
kernel call serves a fused batch of decode tokens and prompt chunks from
different sequences (the Dynamic SplitFuse execution model).

Layout:
  q            [T, nh, d]     — packed new-token queries (T = token budget)
  k/v cache    [NB, bs, nkv, d] — the paged pool, one layer's slice
  block_tables [T, B]         — per TOKEN block table (row's table gathered
                                by seq index before the call)
  q_pos        [T]            — global position of each query in its sequence

Kernel structure: grid (T, B); per program one query token against one of
its context blocks. The block index comes from a scalar-prefetched table
(``PrefetchScalarGridSpec``) so the DMA of the right cache block overlaps
compute — the TPU form of the reference kernel's block-table gather. Online
softmax accumulates in VMEM scratch across the B (sequential) grid dim.
GQA handled by an unrolled per-kv-head loop (MXU dots on [group, d]@[d, bs]).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def paged_attention_reference(q, k_cache, v_cache, block_tables, q_pos, trash_block,
                              window: int = 0, scale=None):
    """jnp reference: per-token context gather + masked softmax, mapped over
    tokens so peak memory is one context window ([S, nkv, d]) rather than T
    of them. Shapes as module docstring; returns [T, nh, d]. ``window``:
    static sliding-window band over sequence positions (mistral/starcoder2;
    band convention shared via core.window_too_far)."""
    T, nh, d = q.shape
    NB, bs, nkv, _ = k_cache.shape
    B = block_tables.shape[1]
    S = B * bs
    group = nh // nkv
    kpos = jnp.arange(S, dtype=jnp.int32)

    def one_token(args):
        qt, bt, pos = args  # [nh, d], [B], scalar
        k_ctx = k_cache[bt].reshape(S, nkv, d).astype(jnp.float32)
        v_ctx = v_cache[bt].reshape(S, nkv, d).astype(jnp.float32)
        blk_valid = jnp.repeat(bt != trash_block, bs)
        mask = (kpos <= pos) & blk_valid  # [S]
        if window:
            from deepspeed_tpu.ops.attention.core import window_too_far

            mask = mask & jnp.logical_not(window_too_far(pos, kpos, window))
        qg = qt.reshape(nkv, group, d).astype(jnp.float32)
        scores = jnp.einsum("ngd,snd->ngs", qg, k_ctx) * (scale if scale is not None else d**-0.5)
        scores = jnp.where(mask[None, None], scores, NEG_INF)
        w = jax.nn.softmax(scores, axis=-1)
        # fully-masked token (all-trash padding): return 0 like the kernel
        # does, not the uniform-softmax mean of trash V
        w = jnp.where(jnp.any(mask), w, 0.0)
        return jnp.einsum("ngs,snd->ngd", w, v_ctx).reshape(nh, d)

    out = jax.lax.map(one_token, (q, block_tables, q_pos), batch_size=min(T, 32))
    return out.astype(q.dtype)


def _paged_kernel(
    bt_ref, qpos_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, bs, nh, nkv, d,
    trash, window=0, scale=None
):
    t = pl.program_id(0)
    j = pl.program_id(1)
    B = pl.num_programs(1)
    group = nh // nkv
    scale = scale if scale is not None else d**-0.5

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    blk = bt_ref[t, j]
    qpos = qpos_ref[t]
    base = j * bs
    kpos = base + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)  # [1, bs]
    valid = (kpos <= qpos) & (blk != trash)  # [1, bs]
    if window:
        from deepspeed_tpu.ops.attention.core import window_too_far

        valid = valid & jnp.logical_not(window_too_far(qpos, kpos, window))

    q = q_ref[0].astype(jnp.float32) * scale  # [nh, d]
    k = k_ref[0].astype(jnp.float32)  # [bs, nkv, d]
    v = v_ref[0].astype(jnp.float32)

    m_prev = m_scr[...]  # [nh, 128] (col 0 meaningful)
    l_prev = l_scr[...]
    for n in range(nkv):
        qn = q[n * group : (n + 1) * group]  # [group, d]
        kn = k[:, n, :]  # [bs, d]
        vn = v[:, n, :]
        s = jax.lax.dot_general(
            qn, kn, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [group, bs]
        s = jnp.where(valid, s, NEG_INF)
        m_p = m_prev[n * group : (n + 1) * group, :1]  # [group, 1]
        m_new = jnp.maximum(m_p, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_p - m_new)
        p = jnp.exp(s - m_new)  # [group, bs]
        l_p = l_prev[n * group : (n + 1) * group, :1]
        l_new = l_p * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc_scr[n * group : (n + 1) * group, :]  # [group, d]
        acc_scr[n * group : (n + 1) * group, :] = acc * alpha + jax.lax.dot_general(
            p, vn, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[n * group : (n + 1) * group, :1] = m_new
        l_scr[n * group : (n + 1) * group, :1] = l_new

    @pl.when(j == B - 1)
    def _finish():
        l = l_scr[:, :1]
        # fully-masked token (all-trash padding): m never left NEG_INF and
        # every p degenerated to exp(0) — emit 0, matching the reference
        any_valid = m_scr[:, :1] > NEG_INF * 0.5
        out = jnp.where(any_valid, acc_scr[...] / jnp.maximum(l, 1e-30), 0.0)
        o_ref[0] = out.astype(o_ref.dtype)


def paged_attention(
    q: jax.Array,
    k_cache: jax.Array,
    v_cache: jax.Array,
    block_tables: jax.Array,
    q_pos: jax.Array,
    trash_block: int,
    impl: Optional[str] = None,
    interpret: bool = False,
    window: int = 0,
    scale: Optional[float] = None,
) -> jax.Array:
    """Dispatching entry point (kernel on TPU, reference otherwise).
    ``window``: static sliding-window band (uniform across layers);
    ``scale``: softmax scale override (gpt_neo's unscaled logits)."""
    T, nh, d = q.shape
    NB, bs, nkv, _ = k_cache.shape
    use_kernel = impl == "kernel" or (
        impl is None and jax.default_backend() == "tpu" and d in (64, 128, 256)
    )
    if not use_kernel and not interpret:
        return paged_attention_reference(
            q, k_cache, v_cache, block_tables, q_pos, trash_block, window=window,
            scale=scale,
        )

    B = block_tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T, B),
        in_specs=[
            pl.BlockSpec((1, nh, d), lambda t, j, bt, qp: (t, 0, 0)),
            pl.BlockSpec((1, bs, nkv, d), lambda t, j, bt, qp: (bt[t, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, nkv, d), lambda t, j, bt, qp: (bt[t, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, d), lambda t, j, bt, qp: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, 128), jnp.float32),
            pltpu.VMEM((nh, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel, bs=bs, nh=nh, nkv=nkv, d=d, trash=trash_block,
        window=int(window), scale=scale,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, nh, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            # tokens are independent (scratch re-inits at j==0) → megacore
            # can split the T dim; only the block dim accumulates
            dimension_semantics=("parallel", "arbitrary")
        ),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), q_pos.astype(jnp.int32), q, k_cache, v_cache)
