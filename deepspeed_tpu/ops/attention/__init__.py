"""Attention ops: TPU flash attention (Pallas) with a jnp reference fallback.

TPU-native replacement for the reference's attention kernel zoo
(csrc/transformer/inference softmax/attention kernels, evoformer_attn,
blocked/flash attention in inference/v2/kernels/ragged_ops). One public
entry point ``attention`` dispatches to the best implementation for the
platform; numerics are validated against ``mha_reference`` in
tests/unit/ops/test_attention.py.
"""

from deepspeed_tpu.ops.attention.core import attention, mha_reference
from deepspeed_tpu.ops.attention.sharded import (
    head_sharded_flash,
    ring_flash_attention,
)

__all__ = [
    "attention",
    "head_sharded_flash",
    "mha_reference",
    "ring_flash_attention",
]
