"""Core attention dispatch.

``attention(q, k, v, causal=..., segment_ids=...)`` picks the best backend:
  * TPU: Pallas flash attention (ops/attention/flash_pallas.py) when shapes
    allow tiling onto the MXU (head_dim and block sizes aligned),
  * otherwise: a numerically-stable jnp implementation that XLA fuses well.

Shapes follow the TPU-friendly layout [batch, num_heads, seq, head_dim]
(q) / [batch, num_kv_heads, seq, head_dim] (k, v); grouped-query attention
(num_heads a multiple of num_kv_heads) is handled in all backends.

Reference parity: the fused softmax/attention CUDA ops of
csrc/transformer/inference/csrc/pt_binding.cpp (softmax_context etc.) and the
blocked flash kernels of deepspeed/inference/v2/kernels/ragged_ops.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def window_too_far(q_pos, k_pos, window: int, window_flag=None):
    """THE sliding-window band convention, shared by every implementation
    (flash kernel, reference einsum, ring loop, decode mask) so the masks
    cannot drift: key k is out of band for query q iff ``q - k >= window``
    (query sees keys in ``(q - window, q]``). ``window_flag`` (traced 0/1
    scalar from attn_layer_pattern) gates the band per layer — flag 0 means
    the layer is global and nothing is masked. Returns a boolean array of
    ``broadcast(q_pos, k_pos)`` shape, True = mask out."""
    far = (q_pos - k_pos) >= window
    if window_flag is not None:
        far = jnp.logical_and(far, window_flag > 0)
    return far


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Expand kv heads for grouped-query attention: [b, h_kv, s, d] -> [b, h, s, d]."""
    if n_rep == 1:
        return k
    b, h_kv, s, d = k.shape
    return jnp.broadcast_to(k[:, :, None], (b, h_kv, n_rep, s, d)).reshape(b, h_kv * n_rep, s, d)


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    alibi_slopes: Optional[jax.Array] = None,
    alibi_positions: Optional[jax.Array] = None,
    window: int = 0,
    window_flag: Optional[jax.Array] = None,
) -> jax.Array:
    """Numerically-stable reference attention in jnp (fp32 softmax).

    q: [b, h, sq, d]; k, v: [b, h_kv, sk, d]. Returns [b, h, sq, d].
    ``alibi_slopes`` ([h]): adds ``slope_h * key_position`` to the logits
    (bloom's absolute-position ALiBi; positions default to arange(sk)).
    ``window``: sliding-window band (query i sees keys in (i - window, i],
    requires causal); ``window_flag`` (traced 0/1 scalar) toggles the band
    per layer for alternating local/global stacks.
    """
    if window and not causal:
        # fail-fast to match flash_attention — silently computing full
        # bidirectional attention would be platform-dependent wrongness
        raise ValueError("mha_reference: window > 0 requires causal=True")
    b, h, sq, d = q.shape
    h_kv = k.shape[1]
    k = _repeat_kv(k, h // h_kv)
    v = _repeat_kv(v, h // h_kv)
    scale = scale if scale is not None else (1.0 / (d ** 0.5))
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if alibi_slopes is not None:
        kp = (
            jnp.arange(k.shape[2], dtype=jnp.float32)[None]
            if alibi_positions is None
            else jnp.asarray(alibi_positions, jnp.float32)
        )
        if kp.ndim == 1:
            kp = kp[None]
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        logits = logits + slopes[None, :, None, None] * kp[:, None, None, :]
    sk = k.shape[2]
    if causal:
        # offset so the last q position attends to all sk keys (decode-friendly)
        q_pos = jnp.arange(sq)[:, None] + (sk - sq)
        k_pos = jnp.arange(sk)[None, :]
        mask = q_pos >= k_pos
        if window:
            mask = jnp.logical_and(
                mask, jnp.logical_not(window_too_far(q_pos, k_pos, window, window_flag))
            )
        logits = jnp.where(mask[None, None], logits, jnp.float32(-1e30))
    if segment_ids is not None:
        # segment_ids: [b, s] per position; requires sq == sk (training path)
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, jnp.float32(-1e30))
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


_warned_alibi_fallback = False
_warned_window_fallback = False


@functools.lru_cache(maxsize=1)
def _flash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from deepspeed_tpu.ops.attention import flash_pallas  # noqa: F401

        return True
    except Exception:
        return False


def _flash_sharded(q, k, v, causal, segment_ids, scale, alibi_slopes=None,
                   alibi_positions=None, window=0, window_flag=None):
    """Run the Pallas flash kernel under a multi-device mesh.

    pallas_call is opaque to the GSPMD partitioner — invoked bare inside jit
    it would force an all-gather of every operand. Batch and heads are
    embarrassingly parallel for self-attention, so we pin the canonical
    layout (batch over data/expert, heads over model+sequence — the TP and
    post-Ulysses placements) and run the kernel under fully-manual shard_map;
    each device computes its local (batch, head) slab over the full sequence.
    """
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.ops.attention.flash_pallas import flash_attention
    from deepspeed_tpu.parallel.topology import (
        BATCH_AXES,
        MODEL_AXIS,
        SEQUENCE_AXIS,
        get_topology,
    )

    topo = get_topology()
    if topo.world_size == 1:
        return flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, scale=scale,
            alibi_slopes=alibi_slopes, alibi_positions=alibi_positions,
            window=window, window_flag=window_flag,
        )
    if alibi_slopes is not None:
        # multi-device alibi would need the slope plane sharded with the
        # head axes inside the manual region — not wired yet; the caller
        # falls back to the reference einsum (GSPMD partitions that, but it
        # materializes [b, h, s, s] fp32 scores — warn once, loudly)
        global _warned_alibi_fallback
        if not _warned_alibi_fallback:
            _warned_alibi_fallback = True
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                "alibi attention on a multi-device mesh falls back to the "
                "dense reference path (O(seq²) HBM for scores) — the flash "
                "kernel's in-kernel alibi is single-device only for now; "
                "expect much higher memory at long sequence lengths"
            )
        return None

    b, h, s, d = q.shape
    h_kv = k.shape[1]
    batch_div = topo.data_parallel_size * topo.expert_parallel_size
    head_div = topo.model_parallel_size * topo.sequence_parallel_size
    if b % batch_div or h % head_div or h_kv % head_div:
        return None  # caller falls back to the reference impl
    if (h // h_kv) > 1 and (h // head_div) % (h // h_kv) != 0:
        return None  # GQA group would straddle a head shard
    head_axes = (MODEL_AXIS, SEQUENCE_AXIS)
    spec = P(BATCH_AXES, head_axes, None, None)
    sharding = jax.sharding.NamedSharding(topo.mesh, spec)
    q, k, v = (jax.lax.with_sharding_constraint(x, sharding) for x in (q, k, v))

    # optional extra operands: segment ids (batch-sharded plane) and the
    # traced per-layer window flag (replicated scalar)
    extra_ops, extra_specs, has_seg, has_wf = [], [], segment_ids is not None, None
    if has_seg:
        seg_spec = P(BATCH_AXES, None)
        segment_ids = jax.lax.with_sharding_constraint(
            segment_ids, jax.sharding.NamedSharding(topo.mesh, seg_spec)
        )
        extra_ops.append(segment_ids)
        extra_specs.append(seg_spec)
    has_wf = window > 0 and window_flag is not None
    if has_wf:
        extra_ops.append(jnp.asarray(window_flag, jnp.int32))
        extra_specs.append(P())

    def body(q_, k_, v_, *rest):
        rest = list(rest)
        seg = rest.pop(0) if has_seg else None
        wf = rest.pop(0) if has_wf else None
        return flash_attention(q_, k_, v_, causal=causal, segment_ids=seg,
                               scale=scale, window=window, window_flag=wf)

    fn = jax.shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(spec, spec, spec, *extra_specs),
        out_specs=spec,
        axis_names=set(topo.mesh.axis_names),
        check_vma=False,
    )
    return fn(q, k, v, *extra_ops)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    impl: Optional[str] = None,
    alibi_slopes: Optional[jax.Array] = None,
    alibi_positions: Optional[jax.Array] = None,
    window: int = 0,
    window_flag: Optional[jax.Array] = None,
) -> jax.Array:
    """Dispatching attention entry point. ``impl`` forces 'flash' or
    'reference'. ALiBi and sliding windows ride the flash path (in-kernel
    masking; a static window additionally prunes out-of-band kv blocks from
    the grid); a dense ``bias`` forces the reference path."""
    d = q.shape[-1]
    sq, sk = q.shape[2], k.shape[2]
    use_flash = impl == "flash" or (
        impl is None
        and _flash_available()
        and bias is None
        and d in (64, 128, 256)
        and sq % 128 == 0
        and sk % 128 == 0
        and sq == sk  # self-attention training path; decode uses reference
    )
    if use_flash:
        out = _flash_sharded(q, k, v, causal, segment_ids, scale, alibi_slopes,
                             alibi_positions, window, window_flag)
        if out is not None:
            return out
    if window and sq == sk and sq >= 4096:
        global _warned_window_fallback
        if not _warned_window_fallback:
            _warned_window_fallback = True
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                f"sliding-window attention fell back to the dense reference "
                f"path at seq={sq} (flash needs TPU, head_dim in 64/128/256, "
                "seq % 128 == 0) — [b, h, s, s] fp32 scores materialize in HBM"
            )
    return mha_reference(
        q, k, v, causal=causal, segment_ids=segment_ids, bias=bias, scale=scale,
        alibi_slopes=alibi_slopes, alibi_positions=alibi_positions,
        window=window, window_flag=window_flag,
    )
