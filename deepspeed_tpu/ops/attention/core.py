"""Core attention dispatch.

``attention(q, k, v, causal=..., segment_ids=...)`` picks the best backend:
  * TPU: Pallas flash attention (ops/attention/flash_pallas.py) when shapes
    allow tiling onto the MXU (head_dim and block sizes aligned),
  * otherwise: a numerically-stable jnp implementation that XLA fuses well.

Shapes follow the TPU-friendly layout [batch, num_heads, seq, head_dim]
(q) / [batch, num_kv_heads, seq, head_dim] (k, v); grouped-query attention
(num_heads a multiple of num_kv_heads) is handled in all backends.

Reference parity: the fused softmax/attention CUDA ops of
csrc/transformer/inference/csrc/pt_binding.cpp (softmax_context etc.) and the
blocked flash kernels of deepspeed/inference/v2/kernels/ragged_ops.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def window_too_far(q_pos, k_pos, window: int, window_flag=None):
    """THE sliding-window band convention, shared by every implementation
    (flash kernel, reference einsum, ring loop, decode mask) so the masks
    cannot drift: key k is out of band for query q iff ``q - k >= window``
    (query sees keys in ``(q - window, q]``). ``window_flag`` (traced 0/1
    scalar from attn_layer_pattern) gates the band per layer — flag 0 means
    the layer is global and nothing is masked. Returns a boolean array of
    ``broadcast(q_pos, k_pos)`` shape, True = mask out."""
    far = (q_pos - k_pos) >= window
    if window_flag is not None:
        far = jnp.logical_and(far, window_flag > 0)
    return far


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Expand kv heads for grouped-query attention: [b, h_kv, s, d] -> [b, h, s, d]."""
    if n_rep == 1:
        return k
    b, h_kv, s, d = k.shape
    return jnp.broadcast_to(k[:, :, None], (b, h_kv, n_rep, s, d)).reshape(b, h_kv * n_rep, s, d)


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    alibi_slopes: Optional[jax.Array] = None,
    alibi_positions: Optional[jax.Array] = None,
    window: int = 0,
    window_flag: Optional[jax.Array] = None,
) -> jax.Array:
    """Numerically-stable reference attention in jnp (fp32 softmax).

    q: [b, h, sq, d]; k, v: [b, h_kv, sk, d]. Returns [b, h, sq, d].
    ``alibi_slopes`` ([h]): adds ``slope_h * key_position`` to the logits
    (bloom's absolute-position ALiBi; positions default to arange(sk)).
    ``window``: sliding-window band (query i sees keys in (i - window, i],
    requires causal); ``window_flag`` (traced 0/1 scalar) toggles the band
    per layer for alternating local/global stacks.
    """
    if window and not causal:
        # fail-fast to match flash_attention — silently computing full
        # bidirectional attention would be platform-dependent wrongness
        raise ValueError("mha_reference: window > 0 requires causal=True")
    b, h, sq, d = q.shape
    h_kv = k.shape[1]
    k = _repeat_kv(k, h // h_kv)
    v = _repeat_kv(v, h // h_kv)
    scale = scale if scale is not None else (1.0 / (d ** 0.5))
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if alibi_slopes is not None:
        kp = (
            jnp.arange(k.shape[2], dtype=jnp.float32)[None]
            if alibi_positions is None
            else jnp.asarray(alibi_positions, jnp.float32)
        )
        if kp.ndim == 1:
            kp = kp[None]
        slopes = jnp.asarray(alibi_slopes, jnp.float32)
        logits = logits + slopes[None, :, None, None] * kp[:, None, None, :]
    sk = k.shape[2]
    if causal:
        # offset so the last q position attends to all sk keys (decode-friendly)
        q_pos = jnp.arange(sq)[:, None] + (sk - sq)
        k_pos = jnp.arange(sk)[None, :]
        mask = q_pos >= k_pos
        if window:
            mask = jnp.logical_and(
                mask, jnp.logical_not(window_too_far(q_pos, k_pos, window, window_flag))
            )
        logits = jnp.where(mask[None, None], logits, jnp.float32(-1e30))
    if segment_ids is not None:
        # segment_ids: [b, s] per position; requires sq == sk (training path)
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, jnp.float32(-1e30))
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights.astype(v.dtype), v)


_warned_alibi_fallback = False
_warned_window_fallback = False
_warned_splash_fallback = False


@functools.lru_cache(maxsize=64)
def _derived_splash_schedule(sq: int, sk: int, causal: bool, window: int,
                             block: int):
    """Schedule for the mask implied by (causal, window) alone — the
    impl='splash' path with no explicit mask configured. Cached: the
    schedule is a trace-time constant, never rebuilt per step."""
    from deepspeed_tpu.ops.sparse_attention.mask import (
        CausalMask, FullMask, LocalMask,
    )
    from deepspeed_tpu.ops.sparse_attention.schedule import schedule_from_mask

    if window and causal:
        mask = LocalMask((sq, sk), window)
    elif causal:
        mask = CausalMask((sq, sk))
    else:
        mask = FullMask((sq, sk))
    return schedule_from_mask(mask, block)


def _splash_block(s: int) -> int:
    import os

    from deepspeed_tpu.ops.attention.flash_pallas import _pick_block

    return _pick_block(s, int(os.environ.get("DSTPU_SPLASH_BLOCK", 512)))


def _splash_dispatch(q, k, v, causal, segment_ids, bias, scale, window,
                     window_flag, schedule, strict):
    """impl='splash' (strict) or auto-promotion (a schedule was configured).
    Returns None when the shapes/arguments cannot take the scheduled path
    (the caller falls back to the dense dispatch chain); strict mode raises
    instead, matching the other explicit impls."""
    from deepspeed_tpu.ops.sparse_attention.splash_pallas import splash_attention

    def bail(msg):
        if strict:
            raise ValueError(f"attention(impl='splash'): {msg}")
        return None

    if bias is not None:
        return bail("dense bias is not supported on the scheduled path")
    if window_flag is not None:
        return bail("a traced per-layer window flag cannot alter a static "
                    "schedule (use the dense/flash path for flag-gated "
                    "local layers)")
    sq, sk = q.shape[2], k.shape[2]
    if schedule is None:
        block = _splash_block(min(sq, sk))
        if sq % block or sk % block:
            return bail(f"seq ({sq}, {sk}) does not divide block {block}")
        schedule = _derived_splash_schedule(sq, sk, bool(causal),
                                            int(window or 0), block)
    from deepspeed_tpu.parallel.topology import get_topology

    if get_topology().world_size > 1:
        from deepspeed_tpu.ops.attention.sharded import head_sharded_splash

        out = head_sharded_splash(q, k, v, schedule, segment_ids=segment_ids,
                                  scale=scale,
                                  interpret=not _flash_available())
        if out is not None:
            return out
        # shapes don't divide the mesh: run the kernel unsharded (GSPMD
        # replicates the pallas_call) — scheduling still prunes, only the
        # head parallelism is lost
    return splash_attention(q, k, v, schedule, segment_ids=segment_ids,
                            scale=scale, interpret=not _flash_available())


@functools.lru_cache(maxsize=1)
def _flash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from deepspeed_tpu.ops.attention import flash_pallas  # noqa: F401

        return True
    except Exception:
        return False


def _flash_sharded(q, k, v, causal, segment_ids, scale, alibi_slopes=None,
                   alibi_positions=None, window=0, window_flag=None):
    """Run the Pallas flash kernel under a multi-device mesh (batch/head
    sharding — ops.attention.sharded.head_sharded_flash). Returns None when
    the shapes don't divide; the caller falls back to the reference einsum
    (GSPMD partitions that, but it materializes O(s²) scores — warn when
    that happens with alibi at long sequence, the expensive case)."""
    from deepspeed_tpu.ops.attention.sharded import head_sharded_flash

    out = head_sharded_flash(
        q, k, v, causal=causal, segment_ids=segment_ids, scale=scale,
        alibi_slopes=alibi_slopes, alibi_positions=alibi_positions,
        window=window, window_flag=window_flag,
    )
    if out is None and alibi_slopes is not None:
        global _warned_alibi_fallback
        if not _warned_alibi_fallback:
            _warned_alibi_fallback = True
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                "alibi attention fell back to the dense reference path "
                "(O(seq²) HBM for scores): batch/head shapes do not divide "
                "the mesh for the head-sharded flash kernel"
            )
    return out


def _ring_eligible(q, k, bias, causal, window):
    """Whether 'auto' dispatch may take the ring context-parallel path: the
    topology's ``context`` axis is >1 (explicit opt-in via mesh config) and
    the schedule/shapes fit the ring's contract."""
    from deepspeed_tpu.parallel.topology import get_topology

    topo = get_topology()
    n = topo.context_parallel_size
    if n <= 1 or bias is not None or not causal or window:
        return False
    b, h, s, d = q.shape
    h_kv, sk = k.shape[1], k.shape[2]
    if s != sk or d not in (64, 128, 256) or s % n or (s // n) % 128:
        return False
    if not (_flash_available() or jax.default_backend() == "cpu"):
        return False
    from deepspeed_tpu.ops.attention.sharded import _divisible

    return _divisible(topo, b, h, h_kv, s=s)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    bias: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    impl: Optional[str] = None,
    alibi_slopes: Optional[jax.Array] = None,
    alibi_positions: Optional[jax.Array] = None,
    window: int = 0,
    window_flag: Optional[jax.Array] = None,
    schedule=None,
) -> jax.Array:
    """Dispatching attention entry point.

    ``impl`` selects the backend:
      * None / 'auto' — splash when a block ``schedule`` (or sparse mask)
        is configured, flash when the platform/shapes allow (ring context
        parallelism when the topology's ``context`` axis is >1 and the
        schedule supports it), else the jnp reference;
      * 'flash' — flash kernel, auto-sharded over batch/head axes;
      * 'flash_head_sharded' — splash-style head sharding, hard error if the
        shapes don't divide the mesh;
      * 'flash_ring' — context-parallel ring over the ``context`` mesh axis
        (causal only; hard error on unsupported schedules);
      * 'splash' — the scheduled block-sparse kernel
        (ops/sparse_attention/splash_pallas.py): ``schedule`` (a
        BlockSchedule) or, absent that, the (causal, window) pair compiles
        into a compacted active-block schedule — masked blocks are never
        visited. Head-sharded automatically on multi-device meshes;
      * 'reference' — the jnp einsum.
    ALiBi and sliding windows ride the flash path (in-kernel masking; a
    static window additionally prunes out-of-band kv blocks from the grid);
    a dense ``bias`` forces the reference path."""
    d = q.shape[-1]
    sq, sk = q.shape[2], k.shape[2]
    if alibi_slopes is not None and (impl == "splash" or schedule is not None):
        raise ValueError("attention: ALiBi is not supported on the splash "
                         "scheduled path")
    if impl == "splash":
        out = _splash_dispatch(q, k, v, causal, segment_ids, bias, scale,
                               window, window_flag, schedule, strict=True)
        if out is not None:
            return out
    elif impl in (None, "auto") and schedule is not None:
        # auto promotion: a sparse mask/window schedule was configured
        out = _splash_dispatch(q, k, v, causal, segment_ids, bias, scale,
                               window, window_flag, schedule, strict=False)
        if out is not None:
            return out
        global _warned_splash_fallback
        if not _warned_splash_fallback:
            _warned_splash_fallback = True
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                "configured splash schedule fell back to the dense dispatch "
                "chain (bias/window-flag/mesh constraints) — sparsity will "
                "be masked, not pruned")
    if impl == "reference":
        return mha_reference(
            q, k, v, causal=causal, segment_ids=segment_ids, bias=bias,
            scale=scale, alibi_slopes=alibi_slopes,
            alibi_positions=alibi_positions, window=window,
            window_flag=window_flag,
        )
    if impl in ("flash_head_sharded", "flash_ring"):
        from deepspeed_tpu.ops.attention import sharded

        if bias is not None:
            raise ValueError(f"attention(impl={impl!r}): dense bias is not "
                             "supported on the flash paths")
        if impl == "flash_ring":
            return sharded.ring_flash_attention(
                q, k, v, causal=causal, segment_ids=segment_ids, scale=scale,
                alibi_slopes=alibi_slopes, window=window,
                interpret=not _flash_available(),
            )
        out = sharded.head_sharded_flash(
            q, k, v, causal=causal, segment_ids=segment_ids, scale=scale,
            alibi_slopes=alibi_slopes, alibi_positions=alibi_positions,
            window=window, window_flag=window_flag,
            interpret=not _flash_available(),
        )
        if out is None:
            raise ValueError(
                "attention(impl='flash_head_sharded'): batch/head shapes "
                f"{q.shape} do not divide the mesh"
            )
        return out
    if impl in (None, "auto") and _ring_eligible(q, k, bias, causal, window):
        from deepspeed_tpu.ops.attention import sharded

        return sharded.ring_flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids, scale=scale,
            alibi_slopes=alibi_slopes, interpret=not _flash_available(),
        )
    use_flash = impl == "flash" or (
        impl in (None, "auto")
        and _flash_available()
        and bias is None
        and d in (64, 128, 256)
        and sq % 128 == 0
        and sk % 128 == 0
        and sq == sk  # self-attention training path; decode uses reference
    )
    if use_flash:
        out = _flash_sharded(q, k, v, causal, segment_ids, scale, alibi_slopes,
                             alibi_positions, window, window_flag)
        if out is not None:
            return out
    if window and sq == sk and sq >= 4096:
        global _warned_window_fallback
        if not _warned_window_fallback:
            _warned_window_fallback = True
            from deepspeed_tpu.utils.logging import logger

            logger.warning(
                f"sliding-window attention fell back to the dense reference "
                f"path at seq={sq} (flash needs TPU, head_dim in 64/128/256, "
                "seq % 128 == 0) — [b, h, s, s] fp32 scores materialize in HBM"
            )
    return mha_reference(
        q, k, v, causal=causal, segment_ids=segment_ids, bias=bias, scale=scale,
        alibi_slopes=alibi_slopes, alibi_positions=alibi_positions,
        window=window, window_flag=window_flag,
    )
