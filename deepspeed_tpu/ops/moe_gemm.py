"""Grouped (ragged) GEMM for MoE expert FFNs.

Reference: ``deepspeed/inference/v2/kernels/cutlass_ops/moe_gemm`` (grouped
GEMM over variable tokens-per-expert) + ``mixed_gemm``. The TPU-native form
is ``jax.lax.ragged_dot``: tokens sorted by expert with a ``group_sizes``
vector, lowered by XLA to an MXU grouped matmul — no capacity padding, no
dropped tokens. On top of it, ``moe_mlp_dropless`` is a MegaBlocks-style
dropless expert MLP: sort tokens by assigned expert, two ragged GEMMs,
scatter-add back weighted by the gate.

(The training MoE layer in parallel/moe/sharded_moe.py keeps the GShard
capacity-padded einsum dispatch — batched GEMMs with static shapes — which
is itself the grouped-GEMM fast path when capacity padding is acceptable.)
"""

from typing import Optional

import jax
import jax.numpy as jnp


def grouped_gemm(x: jax.Array, weights: jax.Array, group_sizes: jax.Array) -> jax.Array:
    """x: [n, h] sorted so the first group_sizes[0] rows belong to expert 0,
    etc.; weights: [E, h, f]; group_sizes: [E] int32 summing to n.
    Returns [n, f] where row i is multiplied by its expert's weight."""
    return jax.lax.ragged_dot(x, weights, group_sizes.astype(jnp.int32))


def _sort_by_expert(expert_of: jax.Array):
    """Stable sort token slots by expert id."""
    return jnp.argsort(expert_of, stable=True)


def moe_mlp_dropless(
    tokens: jax.Array,  # [t, h]
    logits: jax.Array,  # [t, E] gate logits
    w_up: jax.Array,  # [E, h, f]
    w_down: jax.Array,  # [E, f, h]
    w_gate: Optional[jax.Array] = None,  # [E, h, f] (gated MLPs)
    top_k: int = 2,
    activation=jax.nn.silu,
):
    """Dropless top-k expert MLP via grouped GEMMs (no capacity, no drops).

    Each token is routed to its top-k experts with softmax-renormalized
    weights (reference topkgating semantics minus the capacity machinery);
    outputs scatter-add back. Compute cost is exactly t*k expert-row GEMMs.
    """
    t, h = tokens.shape
    E = logits.shape[-1]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = jax.lax.top_k(gates, top_k)  # [t, k]
    top_vals = top_vals / jnp.maximum(top_vals.sum(-1, keepdims=True), 1e-9)

    flat_expert = top_idx.reshape(-1)  # [t*k]
    flat_weight = top_vals.reshape(-1)
    flat_token = jnp.repeat(jnp.arange(t), top_k)

    order = _sort_by_expert(flat_expert)
    sorted_tokens = tokens[flat_token[order]]  # [t*k, h]
    group_sizes = jnp.bincount(flat_expert, length=E).astype(jnp.int32)

    up = grouped_gemm(sorted_tokens, w_up, group_sizes)
    if w_gate is not None:
        up = activation(grouped_gemm(sorted_tokens, w_gate, group_sizes)) * up
    else:
        up = activation(up)
    down = grouped_gemm(up, w_down, group_sizes)  # [t*k, h]

    down = down * flat_weight[order][:, None].astype(down.dtype)
    out = jnp.zeros_like(tokens).at[flat_token[order]].add(down)
    return out, group_sizes


def moe_mlp_dropless_reference(tokens, logits, w_up, w_down, w_gate=None,
                               top_k=2, activation=jax.nn.silu):
    """Dense per-token loop reference (einsum over all experts, masked)."""
    t, h = tokens.shape
    E = logits.shape[-1]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = jax.lax.top_k(gates, top_k)
    top_vals = top_vals / jnp.maximum(top_vals.sum(-1, keepdims=True), 1e-9)
    out = jnp.zeros_like(tokens)
    for e in range(E):
        up = tokens @ w_up[e]
        if w_gate is not None:
            up = activation(tokens @ w_gate[e]) * up
        else:
            up = activation(up)
        y = up @ w_down[e]  # [t, h]
        w = jnp.where(top_idx == e, top_vals, 0.0).sum(-1)  # [t]
        out = out + y * w[:, None].astype(y.dtype)
    return out
