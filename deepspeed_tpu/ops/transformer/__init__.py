"""Fused transformer layer (reference ``deepspeed/ops/transformer/``:
``DeepSpeedTransformerConfig`` + ``DeepSpeedTransformerLayer``
transformer.py:296, backed by csrc/transformer/ fused CUDA kernels).

TPU-native: the layer is a functional BERT-style block whose hot ops route
through the repo's fused kernels — flash attention (Pallas) and fused
layer norm — and whose elementwise chains XLA fuses; the reference's
hand-written gelu/dropout/softmax kernels have no separate existence here.
Weights follow the reference layout (qkv fused, [hidden, 3*hidden]) so
``from_reference_state`` can import torch-side checkpoints.
"""

from dataclasses import dataclass
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.attention import attention
from deepspeed_tpu.ops.normalization import layer_norm_reference


@dataclass
class DeepSpeedTransformerConfig:
    """Reference DeepSpeedTransformerConfig (ops/transformer/transformer.py:22)."""

    batch_size: int = 1
    hidden_size: int = 768
    intermediate_size: int = 3072
    heads: int = 12
    attn_dropout_ratio: float = 0.0
    hidden_dropout_ratio: float = 0.0
    num_hidden_layers: int = 1
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    seed: int = 0
    fp16: bool = False
    pre_layer_norm: bool = True
    return_tuple: bool = False
    stochastic_mode: bool = False  # [compat]
    local_rank: int = -1  # [compat]


class DeepSpeedTransformerLayer:
    """Functional fused BERT layer (reference DeepSpeedTransformerLayer).

    ``init_params(key)`` builds the weight pytree; ``__call__(params, x,
    attention_mask, rng)`` runs the block. Both LN placements supported.
    """

    def __init__(self, config: DeepSpeedTransformerConfig):
        self.config = config

    def init_params(self, key: jax.Array) -> Dict[str, Any]:
        c = self.config
        h, m = c.hidden_size, c.intermediate_size
        ks = jax.random.split(key, 4)
        std = c.initializer_range
        dtype = jnp.float16 if c.fp16 else jnp.float32
        return {
            "attn_qkvw": (jax.random.normal(ks[0], (h, 3 * h)) * std).astype(dtype),
            "attn_qkvb": jnp.zeros((3 * h,), dtype),
            "attn_ow": (jax.random.normal(ks[1], (h, h)) * std).astype(dtype),
            "attn_ob": jnp.zeros((h,), dtype),
            "attn_nw": jnp.ones((h,), dtype),
            "attn_nb": jnp.zeros((h,), dtype),
            "inter_w": (jax.random.normal(ks[2], (h, m)) * std).astype(dtype),
            "inter_b": jnp.zeros((m,), dtype),
            "output_w": (jax.random.normal(ks[3], (m, h)) * std).astype(dtype),
            "output_b": jnp.zeros((h,), dtype),
            "norm_w": jnp.ones((h,), dtype),
            "norm_b": jnp.zeros((h,), dtype),
        }

    def _dropout(self, rng, x, ratio):
        if ratio <= 0.0 or rng is None:
            return x
        keep = jax.random.bernoulli(rng, 1.0 - ratio, x.shape)
        return jnp.where(keep, x / (1.0 - ratio), 0.0).astype(x.dtype)

    def __call__(self, params, hidden_states, attention_mask=None, rng=None,
                 grads=None):
        c = self.config
        b, s, h = hidden_states.shape
        nh = c.heads
        hd = h // nh
        eps = c.layer_norm_eps
        r1 = r2 = r_attn = None
        if rng is not None:
            r1, r2, r_attn = jax.random.split(rng, 3)

        x = hidden_states
        attn_in = layer_norm_reference(x, params["attn_nw"], params["attn_nb"], eps) \
            if c.pre_layer_norm else x
        qkv = attn_in @ params["attn_qkvw"] + params["attn_qkvb"]
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)

        bias = None
        if attention_mask is not None:
            # reference: additive mask broadcast over heads ([b, 1, 1, s])
            bias = attention_mask.astype(jnp.float32).reshape(b, 1, 1, s)
        if c.attn_dropout_ratio > 0.0 and rng is not None:
            # probability dropout needs the dense softmax weights — compute
            # attention inline (the fused kernel path requires ratio 0, like
            # most flash implementations)
            qh, kh, vh = heads(q), heads(k), heads(v)
            logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                                preferred_element_type=jnp.float32) * (hd ** -0.5)
            if bias is not None:
                logits = logits + bias
            w = jax.nn.softmax(logits, axis=-1)
            w = self._dropout(r_attn, w, c.attn_dropout_ratio)
            ctx = jnp.einsum("bhqk,bhkd->bhqd", w.astype(vh.dtype), vh)
        else:
            ctx = attention(heads(q), heads(k), heads(v), causal=False, bias=bias)
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, h)
        attn_out = ctx @ params["attn_ow"] + params["attn_ob"]
        attn_out = self._dropout(r1, attn_out, c.hidden_dropout_ratio)
        x = x + attn_out
        if not c.pre_layer_norm:
            x = layer_norm_reference(x, params["attn_nw"], params["attn_nb"], eps)

        ffn_in = layer_norm_reference(x, params["norm_w"], params["norm_b"], eps) \
            if c.pre_layer_norm else x
        inter = jax.nn.gelu(ffn_in @ params["inter_w"] + params["inter_b"])
        out = inter @ params["output_w"] + params["output_b"]
        out = self._dropout(r2, out, c.hidden_dropout_ratio)
        x = x + out
        if not c.pre_layer_norm:
            x = layer_norm_reference(x, params["norm_w"], params["norm_b"], eps)
        return (x,) if self.config.return_tuple else x


__all__ = ["DeepSpeedTransformerConfig", "DeepSpeedTransformerLayer"]
