"""Random-LTD token sampling/gather/scatter ops.

Reference: ``csrc/random_ltd/`` (token_sort.cu / gather_scatter kernels,
~700 LoC) wrapped by ``deepspeed/ops/random_ltd`` — backing the
random layer-token-drop pipeline (``runtime/data_pipeline/random_ltd.py``
here). On TPU the gather/scatter lower to single XLA ops; the sampling is
jax.random, keeping everything jit-compatible with static kept-token counts.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def gpt_sample_tokens(rng: jax.Array, seq_len: int, kept: int, batch: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """Sample ``kept`` token indices per batch row, SORTED ascending so the
    causal order survives (reference gpt_sample_tokens: sorted random sample).
    Returns (indices [batch, kept], mask [batch, seq_len])."""
    keys = jax.random.split(rng, batch)
    idx = jax.vmap(
        lambda k: jnp.sort(jax.random.permutation(k, seq_len)[:kept])
    )(keys).astype(jnp.int32)
    mask = jnp.zeros((batch, seq_len), jnp.bool_)
    mask = jax.vmap(lambda m, i: m.at[i].set(True))(mask, idx)
    return idx, mask


def bert_sample_tokens(rng: jax.Array, seq_len: int, kept: int, batch: int
                       ) -> Tuple[jax.Array, jax.Array]:
    """Bidirectional variant: same sampling; sort kept for stable layouts."""
    return gpt_sample_tokens(rng, seq_len, kept, batch)


def token_gather(x: jax.Array, indices: jax.Array) -> jax.Array:
    """Gather kept tokens: x [b, s, ...] + indices [b, k] -> [b, k, ...]
    (reference token_gather kernel)."""
    return jax.vmap(lambda row, i: jnp.take(row, i, axis=0))(x, indices)


def token_scatter(full: jax.Array, kept_values: jax.Array, indices: jax.Array) -> jax.Array:
    """Scatter processed kept tokens back into the full sequence; dropped
    positions keep ``full``'s values (reference token_scatter_: the dropped
    tokens bypass the layer)."""
    return jax.vmap(lambda row, vals, i: row.at[i].set(vals))(full, kept_values, indices)
