"""Symmetric block quantization + quantized gradient reduction.

Semantics (matching reference csrc/quantization/pt_binding.cpp ds_quantize
symmetric path): values are grouped into fixed-size blocks; each block stores
int8 values (int4 packed two-per-byte) and one fp32 scale = absmax/qmax.
Dequant is ``q * scale``.

ZeRO++ qgZ (quantized-gradient all-to-all, reference
runtime/comm/coalesced_collectives.py all_to_all_quant_reduce +
csrc/quantization/quant_reduce.cu): ``quantized_reduce_scatter`` runs inside
a ``shard_map`` collective context — the int8 payload and fp32 scales cross
the wire via ``lax.all_to_all`` (2× fewer bytes than fp16 grads at int8, 4×
at packed int4), each rank dequantizes the received shards and reduces
locally, exactly the reference pipeline.
"""

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

_QMAX = {8: 127.0, 4: 7.0}


class QuantizedTensor(NamedTuple):
    values: jax.Array  # int8 payload; for bits=4, two biased nibbles per byte
    scales: jax.Array  # fp32 per block
    shape: tuple  # original shape
    bits: int
    block_size: int


def _pad_to(x, multiple):
    n = x.shape[0]
    pad = (-n) % multiple
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    return x, pad


def _pack_int4(q: jax.Array) -> jax.Array:
    """[-7, 7] int values → two biased nibbles per uint8 byte ([nb, block/2])."""
    biased = (q + 7).astype(jnp.uint8)  # 0..14
    lo, hi = biased[:, ::2], biased[:, 1::2]
    return (lo | (hi << 4)).astype(jnp.int8)


def _unpack_int4(packed: jax.Array) -> jax.Array:
    u = packed.astype(jnp.uint8)
    lo = (u & 0xF).astype(jnp.float32) - 7.0
    hi = (u >> 4).astype(jnp.float32) - 7.0
    nb, half = u.shape
    return jnp.stack([lo, hi], axis=-1).reshape(nb, half * 2)


def quantize_blockwise(
    x: jax.Array,
    bits: int = 8,
    block_size: int = 2048,
    stochastic: bool = False,
    rng: Optional[jax.Array] = None,
) -> QuantizedTensor:
    """Symmetric per-block quantization. Flattens, pads to block_size."""
    qmax = _QMAX[bits]
    flat = x.reshape(-1).astype(jnp.float32)
    flat, _pad = _pad_to(flat, block_size)
    blocks = flat.reshape(-1, block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scales = absmax / qmax
    inv = jnp.where(scales > 0, 1.0 / jnp.maximum(scales, 1e-30), 0.0)
    scaled = blocks * inv
    if stochastic:
        if rng is None:
            raise ValueError("stochastic=True requires an rng key (silent deterministic fallback would bias gradients)")
        noise = jax.random.uniform(rng, scaled.shape) - 0.5
        q = jnp.clip(jnp.round(scaled + noise), -qmax, qmax)
    else:
        q = jnp.clip(jnp.round(scaled), -qmax, qmax)
    values = _pack_int4(q) if bits == 4 else q.astype(jnp.int8)
    return QuantizedTensor(
        values=values,
        scales=scales[:, 0],
        shape=tuple(x.shape),
        bits=bits,
        block_size=block_size,
    )


def dequantize_blockwise(qt: QuantizedTensor, dtype=jnp.float32) -> jax.Array:
    vals = _unpack_int4(qt.values) if qt.bits == 4 else qt.values.astype(jnp.float32)
    flat = (vals * qt.scales[:, None]).reshape(-1)
    n = 1
    for d in qt.shape:
        n *= d
    return flat[:n].reshape(qt.shape).astype(dtype)


def quantized_reduce_scatter(
    x: jax.Array,
    axis_name: str,
    bits: int = 8,
    block_size: int = 256,
    mean: bool = True,
) -> jax.Array:
    """qgZ gradient exchange, to be called INSIDE shard_map over ``axis_name``.

    x: this rank's local (replica) gradient, flat or any shape; logically the
    same array exists on every rank of the axis. Each rank quantizes W chunks
    of its local grads, the int8 payload + scales move via ``lax.all_to_all``,
    and each rank dequantizes + reduces the W received copies of its own
    chunk. Returns this rank's reduced chunk [ceil(n/W) elements], matching
    reference all_to_all_quant_reduce (reduce-scatter semantics). Bytes on
    the wire: n/2 (int8 vs bf16) or n/4 (int4) + scales.
    """
    W = jax.lax.axis_size(axis_name)
    flat = x.reshape(-1).astype(jnp.float32)
    flat, _ = _pad_to(flat, W * block_size)
    chunk = flat.shape[0] // W
    rows = flat.reshape(W, chunk)

    payload, scales = _quantize_rows(rows, bits, block_size)
    # the int8 payload and fp32 block scales are what crosses ICI
    payload_rx = jax.lax.all_to_all(payload, axis_name, split_axis=0, concat_axis=0, tiled=True)
    scales_rx = jax.lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0, tiled=True)
    deq = _dequantize_rows(payload_rx, scales_rx, bits, block_size)  # [W, chunk]
    total = jnp.sum(deq, axis=0)
    if mean:
        total = total / W
    return total.astype(x.dtype)


def _quantize_rows(rows: jax.Array, bits: int, block_size: int):
    """Per-row blockwise quantization helper: rows [R, m] (m % block == 0) →
    (payload int8 [R, nb, bs or bs/2], scales fp32 [R, nb, 1])."""
    qmax = _QMAX[bits]
    R, m = rows.shape
    blocks = rows.reshape(R, m // block_size, block_size)
    absmax = jnp.max(jnp.abs(blocks), axis=-1, keepdims=True)
    scales = absmax / qmax
    inv = jnp.where(scales > 0, 1.0 / jnp.maximum(scales, 1e-30), 0.0)
    q = jnp.clip(jnp.round(blocks * inv), -qmax, qmax)
    if bits == 4:
        payload = _pack_int4(q.reshape(-1, block_size)).reshape(R, m // block_size, block_size // 2)
    else:
        payload = q.astype(jnp.int8)
    return payload, scales


def _dequantize_rows(payload: jax.Array, scales: jax.Array, bits: int, block_size: int):
    R, nb = payload.shape[0], payload.shape[1]
    if bits == 4:
        vals = _unpack_int4(payload.reshape(-1, block_size // 2)).reshape(R, nb, block_size)
    else:
        vals = payload.astype(jnp.float32)
    return (vals * scales).reshape(R, nb * block_size)


def quantize_kv(x: jax.Array):
    """Symmetric per-head-vector int8 quantization for paged KV-cache
    payloads: ``x`` [..., d] → (int8 payload [..., d], fp32 scales [...]),
    scale = absmax/127 over each head vector's d components, dequant
    ``q * scale`` (the ds_quantize symmetric convention above).

    Per-VECTOR (not per-block) granularity is what makes quantize-on-write
    compatible with the engine's write-only scatter protocol: a new token's
    row never changes an already-written row's scale, so incremental
    appends need no read-modify-write of neighbouring pool slots."""
    qmax = _QMAX[8]
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scales = absmax / qmax
    inv = jnp.where(scales > 0, 1.0 / jnp.maximum(scales, 1e-30), 0.0)
    q = jnp.clip(jnp.round(xf * inv[..., None]), -qmax, qmax).astype(jnp.int8)
    return q, scales


def dequantize_kv(values: jax.Array, scales: jax.Array, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv`: int8 payload [..., d] + fp32 scales
    [...] → dense [..., d] in ``dtype``."""
    return (values.astype(jnp.float32) * scales[..., None].astype(jnp.float32)).astype(dtype)


def quantized_reduce_scatter_along(
    x: jax.Array,
    axis_name: str,
    dim: int,
    bits: int = 8,
    block_size: int = 256,
    mean: bool = True,
) -> jax.Array:
    """qgZ exchange producing a *dimension* shard: reduce-scatter ``x`` along
    logical dim ``dim`` of the tensor (which must divide by the axis size),
    int8/int4 payload on the wire. Call INSIDE shard_map over ``axis_name``
    with the full local gradient; returns this rank's dim-``dim`` slice —
    i.e. the ZeRO stage-2/3 gradient layout (``grad_specs`` data placement).
    """
    W = jax.lax.axis_size(axis_name)
    D = x.shape[dim]
    if D % W != 0:
        raise ValueError(f"dim {dim} of size {D} not divisible by axis {axis_name}={W}")
    moved = jnp.moveaxis(x, dim, 0)
    rest_shape = moved.shape[1:]
    rows = moved.reshape(W, -1).astype(jnp.float32)  # [W, m] — row w goes to rank w
    m = rows.shape[1]
    pad = (-m) % block_size
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))

    payload, scales = _quantize_rows(rows, bits, block_size)
    payload_rx = jax.lax.all_to_all(payload, axis_name, split_axis=0, concat_axis=0, tiled=True)
    scales_rx = jax.lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0, tiled=True)
    deq = _dequantize_rows(payload_rx, scales_rx, bits, block_size)  # [W, m+pad]
    total = jnp.sum(deq, axis=0)[:m]
    if mean:
        total = total / W
    out = total.reshape((D // W,) + rest_shape)
    return jnp.moveaxis(out, 0, dim).astype(x.dtype)


def quantized_allreduce(
    x: jax.Array,
    axis_name: str,
    bits: int = 8,
    block_size: int = 256,
    mean: bool = True,
) -> jax.Array:
    """Quantized mean-allreduce for replicated-gradient layouts (ZeRO ≤ 1
    under ``zero_quantized_gradients``): quantized reduce-scatter followed by
    a *re-quantized* all-gather (the reference qgZ two-hop pipeline,
    quant_reduce.cu — both hops move int payloads, never full-width floats).
    Call INSIDE shard_map over ``axis_name``. Returns the full averaged
    tensor in ``x``'s shape/dtype."""
    W = jax.lax.axis_size(axis_name)
    n = x.size
    chunk = quantized_reduce_scatter(x, axis_name, bits=bits, block_size=block_size, mean=mean)
    rows = chunk.reshape(1, -1).astype(jnp.float32)
    pad = (-rows.shape[1]) % block_size
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
    payload, scales = _quantize_rows(rows, bits, block_size)
    payload_all = jax.lax.all_gather(payload, axis_name, axis=0, tiled=True)  # [W, nb, bs]
    scales_all = jax.lax.all_gather(scales, axis_name, axis=0, tiled=True)
    deq = _dequantize_rows(payload_all, scales_all, bits, block_size)  # [W, chunk+pad]
    flat = deq[:, : chunk.shape[0]].reshape(-1)[:n]
    return flat.reshape(x.shape).astype(x.dtype)


def loco_quantized_reduce_scatter_along(
    x: jax.Array,
    err: jax.Array,
    axis_name: str,
    dim: int,
    bits: int = 8,
    block_size: int = 256,
    err_beta: float = 0.8,
    mean: bool = True,
):
    """LoCo error-feedback qgZ exchange (reference ZeRO++ LoCo:
    ``coalesced_collectives.all_to_all_loco_quant_reduce`` +
    ``loco_swizzled_quant_kernel``, csrc/quantization/swizzled_quantize.cu:200).

    The compensated gradient ``x + err`` is what gets block-quantized onto
    the wire, and the error buffer EMA-absorbs this step's quantization
    residual: ``err' = err_beta·err + (1-err_beta)·(compensated - dequant)``
    — computed LOCALLY from this rank's own quantization, before the
    all-to-all. The reference runs two hops (intra/inter node) with two
    buffers; the ICI mesh is one hop, so one buffer suffices. ``err``
    persists across steps in the caller (engine loco state), stored bf16
    (reference stores it int8-requantized; bf16 is strictly more faithful).

    Call INSIDE shard_map over ``axis_name``. Returns (this rank's reduced
    dim-``dim`` slice, new local error buffer in ``err``'s dtype).
    """
    W = jax.lax.axis_size(axis_name)
    D = x.shape[dim]
    if D % W != 0:
        raise ValueError(f"dim {dim} of size {D} not divisible by axis {axis_name}={W}")
    comp = x.astype(jnp.float32) + err.astype(jnp.float32)
    moved = jnp.moveaxis(comp, dim, 0)
    rest_shape = moved.shape[1:]
    rows = moved.reshape(W, -1)
    m = rows.shape[1]
    pad = (-m) % block_size
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))

    payload, scales = _quantize_rows(rows, bits, block_size)
    # local residual BEFORE the exchange: what this rank failed to send
    deq_local = _dequantize_rows(payload, scales, bits, block_size)
    resid = (rows - deq_local)[:, :m].reshape((D,) + rest_shape)
    resid = jnp.moveaxis(resid, 0, dim)
    new_err = err_beta * err.astype(jnp.float32) + (1.0 - err_beta) * resid

    payload_rx = jax.lax.all_to_all(payload, axis_name, split_axis=0, concat_axis=0, tiled=True)
    scales_rx = jax.lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0, tiled=True)
    deq = _dequantize_rows(payload_rx, scales_rx, bits, block_size)
    total = jnp.sum(deq, axis=0)[:m]
    if mean:
        total = total / W
    out = total.reshape((D // W,) + rest_shape)
    return jnp.moveaxis(out, 0, dim).astype(x.dtype), new_err.astype(err.dtype)


def loco_quantized_allreduce(
    x: jax.Array,
    err: jax.Array,
    axis_name: str,
    bits: int = 8,
    block_size: int = 256,
    err_beta: float = 0.8,
    mean: bool = True,
):
    """LoCo error-feedback variant of :func:`quantized_allreduce` for
    replicated-gradient layouts: error feedback compensates the reduce hop
    (where the W-way quantization noise accumulates); the re-quantized
    gather hop stays plain — a deliberate single-buffer simplification of
    the reference's two-buffer intra/inter scheme (one ICI hop here).
    Returns (full averaged tensor, new local error buffer)."""
    W = jax.lax.axis_size(axis_name)
    n = x.size
    flat = x.reshape(-1).astype(jnp.float32) + err.reshape(-1).astype(jnp.float32)
    flat_p, _ = _pad_to(flat, W * block_size)
    chunk = flat_p.shape[0] // W
    rows = flat_p.reshape(W, chunk)

    payload, scales = _quantize_rows(rows, bits, block_size)
    deq_local = _dequantize_rows(payload, scales, bits, block_size)
    resid = (rows - deq_local).reshape(-1)[:n].reshape(x.shape)
    new_err = err_beta * err.astype(jnp.float32) + (1.0 - err_beta) * resid

    payload_rx = jax.lax.all_to_all(payload, axis_name, split_axis=0, concat_axis=0, tiled=True)
    scales_rx = jax.lax.all_to_all(scales, axis_name, split_axis=0, concat_axis=0, tiled=True)
    red = jnp.sum(_dequantize_rows(payload_rx, scales_rx, bits, block_size), axis=0)
    if mean:
        red = red / W
    # second hop: re-quantized all-gather of the reduced chunk (unchanged)
    rows2 = red.reshape(1, -1)
    pad2 = (-rows2.shape[1]) % block_size
    if pad2:
        rows2 = jnp.pad(rows2, ((0, 0), (0, pad2)))
    p2, s2 = _quantize_rows(rows2, bits, block_size)
    p_all = jax.lax.all_gather(p2, axis_name, axis=0, tiled=True)
    s_all = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
    deq = _dequantize_rows(p_all, s_all, bits, block_size)
    full = deq[:, : red.shape[0]].reshape(-1)[:n]
    return full.reshape(x.shape).astype(x.dtype), new_err.astype(err.dtype)


def quantized_all_gather_along(
    x: jax.Array,
    axis_name: str,
    dim: int,
    bits: int = 8,
    block_size: int = 256,
) -> jax.Array:
    """qwZ: quantized parameter all-gather (reference zero_quantized_weights,
    stage3.py:1610 + csrc/quantization swizzled gather). Each rank quantizes
    its dim-``dim`` slice, int8 payload + fp32 block scales cross the wire,
    receivers dequantize — halving gather bytes vs bf16 weights. Call INSIDE
    shard_map over ``axis_name`` with the local slice; returns the full
    tensor along ``dim`` in ``x``'s dtype."""
    moved = jnp.moveaxis(x, dim, 0)
    rest_shape = moved.shape[1:]
    rows = moved.reshape(1, -1).astype(jnp.float32)
    m = rows.shape[1]
    pad = (-m) % block_size
    if pad:
        rows = jnp.pad(rows, ((0, 0), (0, pad)))
    payload, scales = _quantize_rows(rows, bits, block_size)
    payload_all = jax.lax.all_gather(payload, axis_name, axis=0, tiled=True)
    scales_all = jax.lax.all_gather(scales, axis_name, axis=0, tiled=True)
    deq = _dequantize_rows(payload_all, scales_all, bits, block_size)  # [W, m+pad]
    W = deq.shape[0]
    full = deq[:, :m].reshape((W * moved.shape[0],) + rest_shape)
    return jnp.moveaxis(full, 0, dim).astype(x.dtype)


# ---------------------------------------------------------------------------
# fp8 scaled casts (reference csrc/fp_quantizer/ FP6/FP8 paths)
# ---------------------------------------------------------------------------
def fp8_cast(x: jax.Array, dtype=jnp.float8_e4m3fn):
    """Tensor-scaled fp8 cast: returns (fp8 values, fp32 scale)."""
    finfo_max = jnp.finfo(dtype).max.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.where(absmax > 0, absmax / finfo_max, 1.0)
    return (x.astype(jnp.float32) / scale).astype(dtype), scale


def fp8_uncast(values: jax.Array, scale: jax.Array, dtype=jnp.float32):
    return (values.astype(jnp.float32) * scale).astype(dtype)


# ---------------------------------------------------------------------------
# low-bit float quantization: fp6 (e3m2, FP6-LLM) and fp12 (e4m7)
# Reference: csrc/fp_quantizer/ (quantize.cu templated on q_bits 6/8/12,
# wrapped by ops/fp_quantizer/quantize.py FP_Quantize with group_size scaling)
# ---------------------------------------------------------------------------
_FP_FORMATS = {6: (3, 2), 8: (4, 3), 12: (4, 7)}  # bits -> (exp_bits, man_bits)


def _round_to_fp(x, exp_bits, man_bits):
    """Round |x| to the nearest representable e{exp_bits}m{man_bits} value
    (RNE via float round-half-even of the mantissa grid), flushing
    sub-subnormals to zero and saturating at the format max."""
    bias = (1 << (exp_bits - 1)) - 1
    emin = 1 - bias  # smallest normal exponent
    emax = bias  # reserve nothing for inf/nan (reference formats are finite)
    ax = jnp.abs(x)
    e = jnp.floor(jnp.log2(jnp.maximum(ax, 1e-38)))
    e = jnp.clip(e, emin, emax)
    step = jnp.exp2(e - man_bits)
    q = jnp.round(ax / step) * step
    max_val = jnp.exp2(float(emax)) * (2.0 - jnp.exp2(-float(man_bits)))
    q = jnp.minimum(q, max_val)
    # below half the smallest subnormal -> 0
    min_sub = jnp.exp2(float(emin - man_bits))
    q = jnp.where(ax < min_sub / 2, 0.0, q)
    return jnp.sign(x) * q


def fp_quantize(x: jax.Array, q_bits: int = 6, group_size: int = 128):
    """Group-scaled low-bit float quantization (reference FP_Quantize.quantize):
    per-group absmax scaling into the format's range, then e/m rounding.
    Returns (values fp32 [*, groups, group_size] SIMULATED in the format,
    scales fp32) — the memory-format pack/unpack lives in ``fp_pack``."""
    if q_bits not in _FP_FORMATS:
        raise ValueError(f"q_bits must be one of {sorted(_FP_FORMATS)}, got {q_bits}")
    exp_bits, man_bits = _FP_FORMATS[q_bits]
    orig_shape = x.shape
    flat, _ = _pad_to(x.astype(jnp.float32).reshape(-1), group_size)
    groups = flat.reshape(-1, group_size)
    bias = (1 << (exp_bits - 1)) - 1
    fmt_max = 2.0 ** bias * (2.0 - 2.0 ** (-man_bits))
    absmax = jnp.max(jnp.abs(groups), axis=-1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / fmt_max, 1.0)
    q = _round_to_fp(groups / scale, exp_bits, man_bits)
    return q, scale, orig_shape


def fp_dequantize(q, scale, orig_shape, dtype=jnp.float32):
    n = 1
    for s in orig_shape:
        n *= s
    return (q * scale).reshape(-1)[:n].reshape(orig_shape).astype(dtype)


def fp_pack(q: jax.Array, q_bits: int, exp_bits: int = None, man_bits: int = None):
    """Encode format-rounded values into integer codes and pack to uint8:
    fp6 packs 4 codes into 3 bytes, fp12 packs 2 codes into 3 bytes
    (reference swizzled packing, csrc/fp_quantizer/quantize.cu)."""
    if exp_bits is None:
        exp_bits, man_bits = _FP_FORMATS[q_bits]
    bias = (1 << (exp_bits - 1)) - 1
    sign = (q < 0).astype(jnp.uint32)
    ax = jnp.abs(q)
    e = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(ax, 1e-38))), 1 - bias, bias)
    # subnormal handling: values below 2^emin encode with biased exp 0
    is_sub = ax < jnp.exp2(1.0 - bias)
    man_scale = jnp.where(is_sub, jnp.exp2(float(1 - bias - man_bits)),
                          jnp.exp2(e - man_bits))
    man = jnp.round(jnp.where(is_sub, ax, ax / jnp.exp2(e) - 1.0) *
                    jnp.where(is_sub, 1.0 / man_scale, 2.0 ** man_bits))
    man = jnp.clip(man, 0, (1 << man_bits) - 1).astype(jnp.uint32)
    biased = jnp.where(is_sub, 0, (e + bias).astype(jnp.uint32))
    code = (sign << (exp_bits + man_bits)) | (biased << man_bits) | man
    flat = code.reshape(-1)
    if q_bits == 6:
        flat, _ = _pad_to(flat, 4)
        flat = flat.reshape(-1, 4).astype(jnp.uint32)
        b0 = (flat[:, 0] | ((flat[:, 1] & 0x3) << 6)).astype(jnp.uint8)
        b1 = ((flat[:, 1] >> 2) | ((flat[:, 2] & 0xF) << 4)).astype(jnp.uint8)
        b2 = ((flat[:, 2] >> 4) | (flat[:, 3] << 2)).astype(jnp.uint8)
        return jnp.stack([b0, b1, b2], -1).reshape(-1)
    if q_bits == 12:
        flat, _ = _pad_to(flat, 2)
        flat = flat.reshape(-1, 2).astype(jnp.uint32)
        b0 = (flat[:, 0] & 0xFF).astype(jnp.uint8)
        b1 = ((flat[:, 0] >> 8) | ((flat[:, 1] & 0xF) << 4)).astype(jnp.uint8)
        b2 = (flat[:, 1] >> 4).astype(jnp.uint8)
        return jnp.stack([b0, b1, b2], -1).reshape(-1)
    return flat.astype(jnp.uint8)  # q_bits == 8: one code per byte


def fp_unpack(packed: jax.Array, n: int, q_bits: int):
    """Inverse of fp_pack -> fp32 values (pre-scale)."""
    exp_bits, man_bits = _FP_FORMATS[q_bits]
    bias = (1 << (exp_bits - 1)) - 1
    if q_bits == 6:
        trip = packed.reshape(-1, 3).astype(jnp.uint32)
        c0 = trip[:, 0] & 0x3F
        c1 = ((trip[:, 0] >> 6) | (trip[:, 1] << 2)) & 0x3F
        c2 = ((trip[:, 1] >> 4) | (trip[:, 2] << 4)) & 0x3F
        c3 = (trip[:, 2] >> 2) & 0x3F
        codes = jnp.stack([c0, c1, c2, c3], -1).reshape(-1)[:n]
    elif q_bits == 12:
        trip = packed.reshape(-1, 3).astype(jnp.uint32)
        c0 = trip[:, 0] | ((trip[:, 1] & 0xF) << 8)
        c1 = (trip[:, 1] >> 4) | (trip[:, 2] << 4)
        codes = jnp.stack([c0, c1], -1).reshape(-1)[:n]
    else:
        codes = packed.astype(jnp.uint32)[:n]
    sign = jnp.where((codes >> (exp_bits + man_bits)) & 1, -1.0, 1.0)
    biased = (codes >> man_bits) & ((1 << exp_bits) - 1)
    man = (codes & ((1 << man_bits) - 1)).astype(jnp.float32)
    is_sub = biased == 0
    mag = jnp.where(
        is_sub,
        man * jnp.exp2(float(1 - bias - man_bits)),
        (1.0 + man * 2.0 ** (-man_bits)) * jnp.exp2(biased.astype(jnp.float32) - bias),
    )
    return sign * mag


# ---------------------------------------------------------------------------
# Pallas kernel path (TPU): fused absmax + scale + round in VMEM, optional
# in-kernel stochastic rounding via the TPU PRNG
# ---------------------------------------------------------------------------
def _quant_kernel(seed_ref, x_ref, v_ref, s_ref, *, qmax, stochastic):
    from jax.experimental.pallas import tpu as pltpu

    blk = x_ref[:].astype(jnp.float32)  # [rows, block]
    absmax = jnp.max(jnp.abs(blk), axis=-1, keepdims=True)
    scale = absmax / qmax
    inv = jnp.where(scale > 0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    scaled = blk * inv
    if stochastic:
        import jax.experimental.pallas as pl

        pltpu.prng_seed(seed_ref[0, 0] + pl.program_id(0))
        bits = pltpu.prng_random_bits(scaled.shape)
        # top 24 bits → uniform [0, 1) → centered noise [-0.5, 0.5)
        u = (bits >> 8).astype(jnp.float32) * (1.0 / (1 << 24))
        scaled = scaled + (u - 0.5)
    v_ref[:] = jnp.clip(jnp.round(scaled), -qmax, qmax).astype(jnp.int8)
    s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)


def quantize_blockwise_pallas(
    x: jax.Array,
    bits: int = 8,
    block_size: int = 2048,
    stochastic: bool = False,
    seed: int = 0,
    interpret: bool = False,
) -> QuantizedTensor:
    """Pallas path: one VMEM pass per row-block (int8 layout; int4 packing is
    a host-side post-pass)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    qmax = _QMAX[bits]
    flat = x.reshape(-1)
    flat, _ = _pad_to(flat, block_size * 8)
    rows = flat.shape[0] // block_size
    blocks = flat.reshape(rows, block_size)
    row_tile = 8
    seed_arr = jnp.asarray(seed, jnp.int32).reshape(1, 1)

    values, scales = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=qmax, stochastic=stochastic),
        grid=(rows // row_tile,),
        in_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((row_tile, block_size), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((row_tile, block_size), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, block_size), jnp.int8),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        ],
        interpret=interpret,
    )(seed_arr, blocks)
    if bits == 4:
        values = _pack_int4(values.astype(jnp.float32))
    return QuantizedTensor(
        values=values,
        scales=scales[:, 0],
        shape=tuple(x.shape),
        bits=bits,
        block_size=block_size,
    )
