"""Block quantization ops (reference csrc/quantization/ + csrc/fp_quantizer/).

Symmetric per-block int8/int4 quantize/dequantize (the reference's
``quantize.cu``/``dequantize.cu``), fp8 (e4m3/e5m2) scaled casts (the
FP6/FP8 ``fp_quantizer``), and the fused dequant-reduce used by ZeRO++ qgZ
all-to-all gradient reduction (``quant_reduce.cu``).

TPU-native: a Pallas kernel handles the hot block-quant path on TPU; a jnp
path (used for CPU tests and as the XLA-fusable fallback) defines the
semantics. Stochastic rounding uses the TPU PRNG in-kernel.
"""

from deepspeed_tpu.ops.quantizer.block_quant import (
    QuantizedTensor,
    quantize_blockwise,
    dequantize_blockwise,
    quantized_reduce_scatter,
    fp8_cast,
    fp8_uncast,
)

# reference-parity alias (runtime/comm/coalesced_collectives.py name)
all_to_all_quant_reduce = quantized_reduce_scatter

__all__ = [
    "QuantizedTensor",
    "quantize_blockwise",
    "dequantize_blockwise",
    "quantized_reduce_scatter",
    "all_to_all_quant_reduce",
    "fp8_cast",
    "fp8_uncast",
]
