"""TPU op layer (reference deepspeed/ops/ + op_builder/).

Each op family ships a Pallas TPU kernel plus a jnp reference fallback and is
registered in the OpBuilder registry so ``get_accelerator().create_op_builder``
resolves them like the reference's JIT-compiled CUDA ops.
"""

from deepspeed_tpu.ops.op_builder import ALL_OPS, OpBuilder, PallasOpBuilder, register_op


@register_op
class FlashAttnBuilder(PallasOpBuilder):
    NAME = "flash_attn"

    def _build(self):
        from deepspeed_tpu.ops.attention import attention

        return attention


@register_op
class FusedAdamBuilder(PallasOpBuilder):
    NAME = "fused_adam"

    def _build(self):
        from deepspeed_tpu.ops.adam import FusedAdam

        return FusedAdam


@register_op
class QuantizerBuilder(PallasOpBuilder):
    NAME = "quantizer"

    def _build(self):
        from deepspeed_tpu.ops import quantizer

        return quantizer


@register_op
class FusedRMSNormBuilder(PallasOpBuilder):
    NAME = "rms_norm"

    def _build(self):
        # mesh-aware entry: per-shard Pallas under multi-device topologies
        # (the raw fused_rms_norm kernel is GSPMD-opaque)
        from deepspeed_tpu.ops.normalization import rms_norm

        return rms_norm


@register_op
class SparseAttnBuilder(PallasOpBuilder):
    NAME = "sparse_attn"

    def _build(self):
        # importlib: the package attribute `sparse_attention` is rebound to
        # the kernel *function* by the re-export block below — the builder
        # hands out the module (reference parity: sparse_attn is a package)
        import importlib

        return importlib.import_module("deepspeed_tpu.ops.sparse_attention")


@register_op
class EvoformerAttnBuilder(PallasOpBuilder):
    NAME = "evoformer_attn"

    def _build(self):
        from deepspeed_tpu.ops.deepspeed4science import DS4Sci_EvoformerAttention

        return DS4Sci_EvoformerAttention


@register_op
class SpatialInferenceBuilder(PallasOpBuilder):
    NAME = "spatial_inference"

    def _build(self):
        from deepspeed_tpu.ops import spatial

        return spatial


@register_op
class RandomLTDBuilder(PallasOpBuilder):
    NAME = "random_ltd"

    def _build(self):
        from deepspeed_tpu.ops import random_ltd

        return random_ltd


@register_op
class FPQuantizerBuilder(PallasOpBuilder):
    NAME = "fp_quantizer"

    def _build(self):
        from deepspeed_tpu.ops.quantizer import block_quant

        return block_quant


# Native (C++ host) ops register themselves on import of their modules.
from deepspeed_tpu.ops import aio as _aio  # noqa: F401  (registers async_io)
from deepspeed_tpu.ops.adam import cpu_adam as _cpu_adam  # noqa: F401  (registers cpu_adam)

# Sparse attention is a first-class export, not just a builder target:
# the scheduled splash kernel + its mask/schedule surface (reference
# exposes these as deepspeed.ops.sparse_attention.*).
from deepspeed_tpu.ops.sparse_attention import (  # noqa: F401
    BigBirdSparsityConfig,
    BlockSchedule,
    BSLongformerSparsityConfig,
    CausalMask,
    DenseSparsityConfig,
    DocumentMask,
    FixedSparsityConfig,
    LocalMask,
    MultiHeadMask,
    SparseSelfAttention,
    SparsityConfig,
    VariableSparsityConfig,
    schedule_from_layout,
    schedule_from_mask,
    sparse_attention,
    sparse_attention_reference,
    splash_attention,
    splash_prefill_attention,
)

# Compatibility table (reference deepspeed.ops.__compatible_ops__)
__compatible_ops__ = {name: True for name in ALL_OPS}
