"""Evoformer attention (DS4Science) as a Pallas TPU kernel.

Reference: ``csrc/deepspeed4science/evoformer_attn/`` (CUTLASS fwd/bwd,
~15k LoC) wrapped by ``deepspeed/ops/deepspeed4science/evoformer_attn.py``
(``DS4Sci_EvoformerAttention(Q, K, V, [bias1, bias2])``). Evoformer MSA-row /
triangle attention is softmax(QKᵀ·scale + bias₁ + bias₂)V where bias₁ is a
per-row padding mask [b, 1, 1, s] and bias₂ the pair-representation bias
[b or 1, h, s, s]; both need gradients (bias₂'s grad feeds the pair stack).

TPU-native: flash-style online softmax with the combined bias streamed in
per q-block row ([bq, s] slab — evoformer s is hundreds, so VMEM-friendly),
plus a bwd pass that also emits dBias (= dS) row slabs. Broadcasting of each
input bias and the corresponding gradient reduction happen at the jnp level.
"""

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
LANES = 128


def _fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref, *, scale, bq, bk):
    # q_ref: [bq, d]; k/v_ref: [s, d]; b_ref: [bq, s]; outputs like flash
    s = k_ref.shape[0]
    d = q_ref.shape[1]
    nk = s // bk
    q = q_ref[:].astype(jnp.float32) * scale

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)

    def body(ki, carry):
        m, l, acc = carry
        k = k_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        v = v_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) + b_ref[:, pl.ds(ki * bk, bk)].astype(jnp.float32)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        p = jnp.exp(logits - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[:] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[:] = jnp.broadcast_to((m + jnp.log(l_safe))[:, None], (bq, LANES))


def _bwd_dq_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, do_ref, lse_ref, dq_ref, db_ref,
                   *, scale, bq, bk):
    s = k_ref.shape[0]
    d = q_ref.shape[1]
    nk = s // bk
    q = q_ref[:].astype(jnp.float32) * scale
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:, 0]
    delta = jnp.sum(do * o_ref[:].astype(jnp.float32), axis=-1)

    def body(ki, dq):
        k = k_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        v = v_ref[pl.ds(ki * bk, bk), :].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) + b_ref[:, pl.ds(ki * bk, bk)].astype(jnp.float32)
        p = jnp.exp(logits - lse[:, None])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])  # [bq, bk] — also the bias gradient
        db_ref[:, pl.ds(ki * bk, bk)] = ds.astype(db_ref.dtype)
        return dq + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = jax.lax.fori_loop(0, nk, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref,
                    *, scale, bq, bk):
    ki = pl.program_id(2)
    sq = q_ref.shape[0]
    d = k_ref.shape[1]
    nq = sq // bq
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    def body(qj, carry):
        dk, dv = carry
        q = q_ref[pl.ds(qj * bq, bq), :].astype(jnp.float32) * scale
        do = do_ref[pl.ds(qj * bq, bq), :].astype(jnp.float32)
        o = o_ref[pl.ds(qj * bq, bq), :].astype(jnp.float32)
        lse = lse_ref[pl.ds(qj * bq, bq), 0]
        delta = jnp.sum(do * o, axis=-1)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) + b_ref[pl.ds(qj * bq, bq), :].astype(jnp.float32)
        p = jnp.exp(logits - lse[:, None])
        dv_new = dv + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        dk_new = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    zeros = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (zeros, zeros))
    dk_ref[:] = dk.astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _pick_block(s):
    b = min(256, s)
    while s % b:
        b //= 2
    return max(b, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _evo_core(q, k, v, bias, scale, interpret):
    out, _ = _evo_fwd(q, k, v, bias, scale, interpret)
    return out


def _evo_call(q, k, v, bias, scale, interpret):
    b, h, s, d = q.shape
    bq = _pick_block(s)
    bk = _pick_block(s)
    kernel = functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk)
    out, lse = pl.pallas_call(
        lambda qr, kr, vr, br, orf, lr: kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], br.at[0, 0], orf.at[0, 0], lr.at[0, 0]
        ),
        grid=(b, h, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, bq, s), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, bias)
    return out, lse


def _evo_fwd(q, k, v, bias, scale, interpret):
    out, lse = _evo_call(q, k, v, bias, scale, interpret)
    return out, (q, k, v, bias, out, lse)


def _evo_bwd(scale, interpret, res, g):
    q, k, v, bias, out, lse = res
    b, h, s, d = q.shape
    bq = _pick_block(s)
    bk = _pick_block(s)

    dq_kernel = functools.partial(_bwd_dq_kernel, scale=scale, bq=bq, bk=bk)
    dq, dbias = pl.pallas_call(
        lambda qr, kr, vr, br, orf, dor, lr, dqr, dbr: dq_kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], br.at[0, 0], orf.at[0, 0],
            dor.at[0, 0], lr.at[0, 0], dqr.at[0, 0], dbr.at[0, 0]
        ),
        grid=(b, h, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, bq, s), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, LANES), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bq, s), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((b, h, s, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, bias, out, g, lse)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, scale=scale, bq=bq, bk=bk)
    dk, dv = pl.pallas_call(
        lambda qr, kr, vr, br, orf, dor, lr, dkr, dvr: dkv_kernel(
            qr.at[0, 0], kr.at[0, 0], vr.at[0, 0], br.at[0, 0], orf.at[0, 0],
            dor.at[0, 0], lr.at[0, 0], dkr.at[0, 0], dvr.at[0, 0]
        ),
        grid=(b, h, s // bk),
        in_specs=[
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, s, bk), lambda b_, h_, i: (b_, h_, 0, i)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, s, LANES), lambda b_, h_, i: (b_, h_, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda b_, h_, i: (b_, h_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(q.shape, q.dtype),
        ],
        interpret=interpret,
    )(q, k, v, bias, out, g, lse)
    return dq, dk, dv, dbias


_evo_core.defvjp(_evo_fwd, _evo_bwd)


def DS4Sci_EvoformerAttention(Q, K, V, biases: Optional[List] = None,
                              interpret: bool = False):
    """Reference-parity entry (ops/deepspeed4science/evoformer_attn.py):
    Q/K/V: [*, s, h, d] with arbitrary leading batch dims (MSA layout);
    ``biases``: up to two additive biases broadcastable to [*, h, s, s]
    (padding mask + pair bias). Returns [*, s, h, d]; bias gradients flow
    (reduced over broadcast dims by JAX's transpose of broadcast_to)."""
    biases = biases or []
    *lead, s, h, d = Q.shape
    b = 1
    for x in lead:
        b *= x
    # [*, s, h, d] -> [b, h, s, d]
    q = jnp.moveaxis(Q.reshape(b, s, h, d), 1, 2)
    k = jnp.moveaxis(K.reshape(b, s, h, d), 1, 2)
    v = jnp.moveaxis(V.reshape(b, s, h, d), 1, 2)
    bias = jnp.zeros((b, h, s, s), jnp.float32)
    for extra in biases:
        # reference bias shapes broadcast against [*lead, h, s, s]
        eb = jnp.broadcast_to(extra.astype(jnp.float32), tuple(lead) + (h, s, s))
        bias = bias + eb.reshape(b, h, s, s)
    scale = d**-0.5
    out = _evo_core(q, k, v, bias, scale, interpret)
    return jnp.moveaxis(out, 1, 2).reshape(*lead, s, h, d)


def evoformer_reference(Q, K, V, biases=None):
    """Dense jnp reference for numerics tests."""
    biases = biases or []
    *lead, s, h, d = Q.shape
    q = jnp.einsum("...shd->...hsd", Q)
    k = jnp.einsum("...shd->...hsd", K)
    v = jnp.einsum("...shd->...hsd", V)
    logits = jnp.einsum("...hqd,...hkd->...hqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * (d**-0.5)
    for bb in biases:
        logits = logits + bb.astype(jnp.float32)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("...hqk,...hkd->...hqd", w.astype(v.dtype), v)
    return jnp.einsum("...hsd->...shd", out)
