"""Quantized forward matmuls (fp8 / int8) with full-precision backward.

Reference analogue: ``csrc/fp_quantizer/`` (FP8 cast kernels) + the
transformer-engine-style recipe the reference's fp8 blogs describe: the
FORWARD projection runs on low-precision operands with per-tensor scales,
the BACKWARD uses the saved full-precision operands — a straight-through
custom vjp, so training dynamics stay those of the bf16 model while the
forward rides the faster MXU path.

TPU notes: v5e's MXU has native int8 (2x bf16 throughput); fp8 (e4m3)
lowers through XLA (upcast on v5e, native on newer parts) — both paths are
measured honestly in PERF.md.

Scale granularity (VERDICT round-3 #9 — per-tensor int8 degraded the loss;
finer scales are the known fix, matching the reference's per-group
``csrc/quantization`` layouts):
  * ``int8`` — per-TOKEN activation scales (absmax over the contraction
    dim) x per-OUTPUT-CHANNEL weight scales: the int32 matmul result gets
    a rank-1 rescale ``out * sx[..., 1] * sw[1, n]``, so outlier channels
    no longer clip the whole tensor. This is the scheme that keeps the
    loss trajectory at dense parity (test_qmatmul int8 tolerance 5e-3).
  * ``int8_tensor`` — the round-3 per-tensor form, kept for A/B.
  * ``fp8`` — per-tensor e4m3 (fp8's exponent absorbs per-channel spread).
"""

import functools

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.quantizer.block_quant import fp8_cast

MODES = ("fp8", "int8", "int8_tensor")


def _cast_i8_axis(a: jax.Array, axis: int):
    """Symmetric int8 cast with absmax scales along ``axis`` (kept dim)."""
    af = a.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(af), axis=axis, keepdims=True)
    scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
    q = jnp.clip(jnp.round(af / scale), -127, 127)
    return q.astype(jnp.int8), scale


def _q_forward(x: jax.Array, w: jax.Array, mode: str) -> jax.Array:
    """x [..., k] @ w [k, n] with quantized operands, fp32 accumulation."""
    if mode == "fp8":
        xq, sx = fp8_cast(x)
        wq, sw = fp8_cast(w)
        out = jnp.dot(xq, wq, preferred_element_type=jnp.float32)
        return (out * (sx * sw)).astype(x.dtype)
    if mode == "int8":
        # per-token rows x per-channel columns: scales stay OUTSIDE the
        # int8 dot (exact rank-1 rescale of the int32 accumulator)
        xq, sx = _cast_i8_axis(x, axis=-1)  # sx [..., 1]
        wq, sw = _cast_i8_axis(w, axis=0)  # sw [1, n]
        out = jnp.dot(xq, wq, preferred_element_type=jnp.int32)
        return (out.astype(jnp.float32) * sx * sw).astype(x.dtype)
    if mode == "int8_tensor":
        def cast_i8(a):
            absmax = jnp.max(jnp.abs(a.astype(jnp.float32)))
            scale = jnp.where(absmax > 0, absmax / 127.0, 1.0)
            q = jnp.clip(jnp.round(a.astype(jnp.float32) / scale), -127, 127)
            return q.astype(jnp.int8), scale

        xq, sx = cast_i8(x)
        wq, sw = cast_i8(w)
        out = jnp.dot(xq, wq, preferred_element_type=jnp.int32)
        return (out.astype(jnp.float32) * (sx * sw)).astype(x.dtype)
    raise ValueError(f"qmatmul mode must be one of {MODES}, got {mode!r}")


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def qmatmul(x: jax.Array, w: jax.Array, mode: str) -> jax.Array:
    """Quantized-forward matmul; backward is the exact bf16 vjp."""
    return _q_forward(x, w, mode)


def _qmm_fwd(x, w, mode):
    return _q_forward(x, w, mode), (x, w)


def _qmm_bwd(mode, res, g):
    x, w = res
    dx = jnp.dot(g, w.T).astype(x.dtype)
    k = x.shape[-1]
    dw = jnp.dot(
        x.reshape(-1, k).T.astype(jnp.float32),
        g.reshape(-1, g.shape[-1]).astype(jnp.float32),
    ).astype(w.dtype)
    return dx, dw


qmatmul.defvjp(_qmm_fwd, _qmm_bwd)
