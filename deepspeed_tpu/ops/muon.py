"""Muon optimizer core: Newton–Schulz momentum orthogonalization.

Analogue of the reference ``runtime/zero/muon/original_muon.py`` /
``muon_optimizer.py``: SGD-momentum whose 2-D updates are orthogonalized by a
quintic Newton–Schulz iteration. The NS iteration is 5 matmuls of the
parameter's own shape — ideal MXU work, done in bf16 like the reference does
on tensor cores. Non-2D params (embeddings flattened? no — biases, norms)
route to Adam, matching the reference's `use_muon` routing.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

NS_COEFFS = (3.4445, -4.7750, 2.0315)


def newton_schulz_orthogonalize(g: jnp.ndarray, steps: int = 5, eps: float = 1e-7) -> jnp.ndarray:
    """Quintic Newton–Schulz iteration producing an approximate orthogonal
    factor of g (reference original_muon.py zeropower_via_newtonschulz5)."""
    a, b, c = NS_COEFFS
    transposed = g.shape[-2] > g.shape[-1]
    x = g.astype(jnp.bfloat16)
    if transposed:
        x = jnp.swapaxes(x, -1, -2)
    x = x / (jnp.linalg.norm(x) + eps)

    def body(x, _):
        gram = x @ jnp.swapaxes(x, -1, -2)
        update = b * gram + c * (gram @ gram)
        return a * x + update @ x, None

    x, _ = jax.lax.scan(body, x, None, length=steps)
    if transposed:
        x = jnp.swapaxes(x, -1, -2)
    return x.astype(g.dtype)


class MuonState(NamedTuple):
    momentum: any
    adam_mu: any
    adam_nu: any
    count: jnp.ndarray


def _is_matrix(p):
    return p.ndim == 2 and min(p.shape) > 1


def muon_transform(beta=0.95, ns_steps=5, weight_decay=0.0, adam_betas=(0.9, 0.95), eps=1e-8, adam_lr_ratio=0.1):
    """Muon for 2-D params, Adam for the rest; lr injected at update time."""

    def init(params):
        zeros = lambda: jax.tree.map(jnp.zeros_like, params)
        return MuonState(momentum=zeros(), adam_mu=zeros(), adam_nu=zeros(), count=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None, *, lr):
        count = state.count + 1
        b1, b2 = adam_betas

        def upd(g, mom, mu, nu, p):
            if _is_matrix(g):
                new_mom = beta * mom + g
                ortho = newton_schulz_orthogonalize(beta * new_mom + g, steps=ns_steps)
                # scale to match RMS of Adam-style updates (reference 0.2*sqrt(max dim))
                scale = 0.2 * jnp.sqrt(jnp.float32(max(g.shape)))
                u = -lr * (ortho * scale + (weight_decay * p if weight_decay else 0.0))
                return u, new_mom, mu, nu
            new_mu = b1 * mu + (1 - b1) * g
            new_nu = b2 * nu + (1 - b2) * jnp.square(g)
            mu_hat = new_mu / (1 - b1**count.astype(jnp.float32))
            nu_hat = new_nu / (1 - b2**count.astype(jnp.float32))
            u = -lr * adam_lr_ratio * (mu_hat / (jnp.sqrt(nu_hat) + eps) + (weight_decay * p if weight_decay else 0.0))
            return u, mom, new_mu, new_nu

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_mom = treedef.flatten_up_to(state.momentum)
        flat_mu = treedef.flatten_up_to(state.adam_mu)
        flat_nu = treedef.flatten_up_to(state.adam_nu)
        flat_p = treedef.flatten_up_to(params) if params is not None else [jnp.zeros(()) for _ in flat_g]
        out = [upd(g, m, mu, nu, p) for g, m, mu, nu, p in zip(flat_g, flat_mom, flat_mu, flat_nu, flat_p)]
        updates = treedef.unflatten([o[0] for o in out])
        new_state = MuonState(
            momentum=treedef.unflatten([o[1] for o in out]),
            adam_mu=treedef.unflatten([o[2] for o in out]),
            adam_nu=treedef.unflatten([o[3] for o in out]),
            count=count,
        )
        return updates, new_state

    return optax.GradientTransformation(init, update)
