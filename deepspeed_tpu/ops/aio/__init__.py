"""Async host file I/O — the DeepNVMe/AIO analogue.

Reference surface: ``deepspeed/ops/aio`` + ``csrc/aio/py_lib`` (aio_handle with
sync_pread/sync_pwrite/async_pread/async_pwrite/wait, pinned-tensor manager
deepspeed_pin_tensor.cpp). Here the native engine is ``csrc/aio/dstpu_aio.cpp``
(worker-thread pool slicing each transfer, page-aligned buffers), JIT-built by
``NativeOpBuilder`` and bound via ctypes; a ThreadPoolExecutor fallback keeps
the API available when no C++ toolchain exists.

Buffers are numpy arrays (any contiguous dtype); the NVMe swap tier moves
bytes between these host buffers and jax arrays at the HBM boundary.
"""

import ctypes
import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from deepspeed_tpu.ops.op_builder import NativeOpBuilder, register_op


@register_op
class AsyncIOBuilder(NativeOpBuilder):
    NAME = "async_io"
    SOURCES = ("aio/dstpu_aio.cpp",)

    def _bind(self, lib):
        p, i32, i64, cp = ctypes.c_void_p, ctypes.c_int, ctypes.c_int64, ctypes.c_char_p
        lib.dstpu_aio_handle_new.restype = p
        lib.dstpu_aio_handle_new.argtypes = [i64, i32, i32, i32, i32]
        lib.dstpu_aio_handle_free.argtypes = [p]
        for fn in ("dstpu_aio_async_pread", "dstpu_aio_async_pwrite",
                   "dstpu_aio_sync_pread", "dstpu_aio_sync_pwrite"):
            f = getattr(lib, fn)
            f.restype = i64
            f.argtypes = [p, ctypes.c_void_p, i64, cp, i64]
        lib.dstpu_aio_wait.restype = i64
        lib.dstpu_aio_wait.argtypes = [p]
        lib.dstpu_aio_pending.restype = i64
        lib.dstpu_aio_pending.argtypes = [p]
        lib.dstpu_aio_block_size.restype = i64
        lib.dstpu_aio_block_size.argtypes = [p]
        lib.dstpu_aio_alloc_pinned.restype = ctypes.c_void_p
        lib.dstpu_aio_alloc_pinned.argtypes = [i64]
        lib.dstpu_aio_free_pinned.argtypes = [ctypes.c_void_p]
        lib.dstpu_aio_file_size.restype = i64
        lib.dstpu_aio_file_size.argtypes = [cp]


def _native_lib():
    # legacy kill-switch kept alongside the canonical DSTPU_DISABLE_NATIVE_ASYNC_IO
    if os.environ.get("DSTPU_DISABLE_NATIVE_AIO") == "1":
        return None
    return AsyncIOBuilder.lib()


def _check(arr):
    if not (isinstance(arr, np.ndarray) and arr.flags["C_CONTIGUOUS"]):
        raise ValueError("aio buffers must be C-contiguous numpy arrays")
    return arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes


class AioHandle:
    """Reference ``aio_handle`` parity object (csrc/aio/py_lib/py_ds_aio.cpp).

    One handle owns ``intra_op_parallelism`` worker threads; each pread/pwrite
    is sliced across them in ``block_size`` chunks. ``wait()`` blocks until all
    in-flight ops retire and returns how many it retired.
    """

    def __init__(self, block_size=1 << 20, queue_depth=8, single_submit=False,
                 overlap_events=True, intra_op_parallelism=4):
        self._lib = _native_lib()
        self._block_size = block_size
        self._queue_depth = queue_depth
        self._parallelism = intra_op_parallelism
        self._pinned = {}  # id(array) -> base pointer
        if self._lib is not None:
            self._h = self._lib.dstpu_aio_handle_new(
                block_size, queue_depth, int(single_submit), int(overlap_events),
                intra_op_parallelism)
            self._pool = None
            self._futures = []
        else:
            self._h = None
            self._pool = ThreadPoolExecutor(max_workers=intra_op_parallelism)
            self._futures = []

    # -- properties (reference get_block_size/get_queue_depth/...) --
    def get_block_size(self):
        return self._block_size

    def get_queue_depth(self):
        return self._queue_depth

    def get_intra_op_parallelism(self):
        return self._parallelism

    # -- sync ops --
    def sync_pread(self, buffer, filename, file_offset=0):
        if self._h is not None:
            ptr, n = _check(buffer)
            rc = self._lib.dstpu_aio_sync_pread(self._h, ptr, n, filename.encode(), file_offset)
            if rc < 0:
                raise OSError(-rc, os.strerror(-rc), filename)
            return n
        return self._py_pread(buffer, filename, file_offset)

    def sync_pwrite(self, buffer, filename, file_offset=0):
        if self._h is not None:
            ptr, n = _check(buffer)
            rc = self._lib.dstpu_aio_sync_pwrite(self._h, ptr, n, filename.encode(), file_offset)
            if rc < 0:
                raise OSError(-rc, os.strerror(-rc), filename)
            return n
        return self._py_pwrite(buffer, filename, file_offset)

    # -- async ops --
    def async_pread(self, buffer, filename, file_offset=0):
        if self._h is not None:
            ptr, n = _check(buffer)
            rc = self._lib.dstpu_aio_async_pread(self._h, ptr, n, filename.encode(), file_offset)
            if rc < 0:
                raise OSError(-rc, os.strerror(-rc), filename)
            return rc
        self._futures.append(self._pool.submit(self._py_pread, buffer, filename, file_offset))
        return len(self._futures)

    def async_pwrite(self, buffer, filename, file_offset=0):
        if self._h is not None:
            ptr, n = _check(buffer)
            rc = self._lib.dstpu_aio_async_pwrite(self._h, ptr, n, filename.encode(), file_offset)
            if rc < 0:
                raise OSError(-rc, os.strerror(-rc), filename)
            return rc
        self._futures.append(self._pool.submit(self._py_pwrite, buffer, filename, file_offset))
        return len(self._futures)

    def wait(self):
        if self._h is not None:
            rc = self._lib.dstpu_aio_wait(self._h)
            if rc < 0:
                raise OSError(-rc, os.strerror(-rc))
            return rc
        # drain ALL futures exactly once, even when one raises
        futures, self._futures = self._futures, []
        done = 0
        first_err = None
        for f in futures:
            try:
                f.result()
                done += 1
            except Exception as e:
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err
        return done

    def pending(self):
        if self._h is not None:
            return self._lib.dstpu_aio_pending(self._h)
        return sum(0 if f.done() else 1 for f in self._futures)

    # -- pinned buffers (reference new_cpu_locked_tensor) --
    def new_cpu_locked_tensor(self, num_elem, dtype=np.float32):
        dtype = np.dtype(dtype)
        nbytes = int(num_elem) * dtype.itemsize
        if self._h is not None:
            base = self._lib.dstpu_aio_alloc_pinned(nbytes)
            if not base:
                raise MemoryError("pinned alloc failed")
            buf = (ctypes.c_char * nbytes).from_address(base)
            arr = np.frombuffer(buf, dtype=dtype, count=num_elem)
            arr.flags.writeable = True
            # keyed by data address so views/reshapes of the buffer free too
            self._pinned[int(arr.ctypes.data)] = base
            return arr
        return np.empty(num_elem, dtype=dtype)

    def free_cpu_locked_tensor(self, arr):
        base = self._pinned.pop(int(arr.ctypes.data), None)
        if base is not None:
            self._lib.dstpu_aio_free_pinned(base)

    # -- fallback impls --
    @staticmethod
    def _py_pread(buffer, filename, offset):
        with open(filename, "rb") as f:
            f.seek(offset)
            data = f.read(buffer.nbytes)
        flat = buffer.reshape(-1).view(np.uint8)
        flat[: len(data)] = np.frombuffer(data, dtype=np.uint8)
        if len(data) < buffer.nbytes:
            flat[len(data):] = 0
        return buffer.nbytes

    @staticmethod
    def _py_pwrite(buffer, filename, offset):
        # O_CREAT without O_TRUNC: concurrent writers to distinct offsets of a
        # new file must not clobber each other (matches the native engine).
        fd = os.open(filename, os.O_WRONLY | os.O_CREAT, 0o644)
        try:
            os.pwrite(fd, buffer.tobytes(), offset)
        finally:
            os.close(fd)
        return buffer.nbytes

    def __del__(self):
        try:
            if self._h is not None and self._lib is not None:
                self._lib.dstpu_aio_handle_free(self._h)
                self._h = None
                for base in self._pinned.values():
                    self._lib.dstpu_aio_free_pinned(base)
                self._pinned.clear()
            if self._pool is not None:
                self._pool.shutdown(wait=False)
        except Exception:
            pass


def aio_handle(*args, **kwargs):
    """Factory matching the reference module-level constructor name."""
    return AioHandle(*args, **kwargs)


def is_native():
    """True when the C++ engine (not the thread-pool fallback) is active."""
    return _native_lib() is not None
