"""Fused normalization ops (reference csrc/transformer/inference layer_norm.cu
/ rms_norm.cu — fused_ln, fused_rms_norm, residual-add variants).

TPU-native: one Pallas VMEM pass per row block computing the statistics and
the scaled output (optionally with residual add), with a custom VJP. A jnp
path defines the semantics for CPU tests and XLA-fusion comparison.
"""

from deepspeed_tpu.ops.normalization.fused_norm import (
    fused_layer_norm,
    fused_rms_norm,
    layer_norm_reference,
    rms_norm,
    rms_norm_reference,
)

__all__ = [
    "fused_layer_norm",
    "fused_rms_norm",
    "layer_norm_reference",
    "rms_norm",
    "rms_norm_reference",
]
