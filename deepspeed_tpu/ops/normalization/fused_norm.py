"""Fused RMSNorm / LayerNorm Pallas kernels with custom VJP.

Reference: csrc/transformer/inference/csrc/rms_norm.cu, layer_norm.cu
(fused_rms_norm / fused_ln bindings, pt_binding.cpp). Forward computes the
row statistics and normalized output in one VMEM pass; backward recomputes
statistics (cheaper than storing them for long rows) and reduces the weight
grads across the row grid.
"""

import functools

import jax
import jax.numpy as jnp


def rms_norm_reference(x, w, eps=1e-5):
    xf = x.astype(jnp.float32)
    y = xf * jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    return (y * w.astype(jnp.float32)).astype(x.dtype)


def layer_norm_reference(x, w, b, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (y * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def _rms_fwd_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)  # [rows, h]
    inv = jax.lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    o_ref[:] = (x * inv * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rms_bwd_kernel(x_ref, w_ref, g_ref, dx_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps
    inv = jax.lax.rsqrt(ms)
    xhat = x * inv
    gw = g * w
    # dx = inv * (gw - xhat * mean(gw * xhat))
    dot = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (inv * (gw - xhat * dot)).astype(dx_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def fused_rms_norm(x, w, eps=1e-5, interpret=False):
    """x: [..., h]; w: [h]. Pallas on TPU, jnp elsewhere unless interpret."""
    out, _ = _rms_fwd(x, w, eps, interpret)
    return out


def _use_pallas(interpret):
    # single-shard gate only: multi-device dispatch happens in rms_norm(),
    # which runs this kernel per-shard under shard_map
    return interpret or jax.default_backend() == "tpu"


def _rows_view(x):
    h = x.shape[-1]
    return x.reshape(-1, h), x.shape


def _pick_rows(n_rows, h=0):
    # cap rows*h so the kernel's fp32 scratch stays under the ~16 MB scoped
    # VMEM limit: 256 rows at h=4096 is 16.1 MB of stack and fails to compile
    max_rows = 256
    while h and max_rows > 1 and max_rows * h > (1 << 19):
        max_rows //= 2
    for r in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if r <= max_rows and n_rows % r == 0:
            return r
    return 1


def _rms_fwd(x, w, eps, interpret):
    if not _use_pallas(interpret):
        return rms_norm_reference(x, w, eps), (x, w)
    from jax.experimental import pallas as pl

    x2, shape = _rows_view(x)
    n, h = x2.shape
    rows = _pick_rows(n, h)
    out = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((rows, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
        interpret=interpret,
    )(x2, w)
    return out.reshape(shape), (x, w)


def _rms_bwd(eps, interpret, res, g):
    x, w = res
    if not _use_pallas(interpret):
        def f(x, w):
            return rms_norm_reference(x, w, eps)

        _, vjp = jax.vjp(f, x, w)
        return vjp(g)
    from jax.experimental import pallas as pl

    x2, shape = _rows_view(x)
    g2, _ = _rows_view(g)
    n, h = x2.shape
    rows = _pick_rows(n, h)
    dx = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, eps=eps),
        grid=(n // rows,),
        in_specs=[
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
            pl.BlockSpec((h,), lambda i: (0,)),
            pl.BlockSpec((rows, h), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((rows, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h), x.dtype),
        interpret=interpret,
    )(x2, w, g2)
    # dw reduction is one fused elementwise+sum in XLA; keeping it out of the
    # kernel avoids the (8,128) output-tile constraint on the [1, h] partial
    xf = x2.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1, keepdims=True) + eps)
    dw = jnp.sum(g2.astype(jnp.float32) * xf * inv, axis=0).astype(w.dtype)
    return dx.reshape(shape), dw


fused_rms_norm.defvjp(_rms_fwd, _rms_bwd)

_SHARDED_FALLBACK_WARNED = False


def rms_norm(x, w, eps=1e-5, interpret=False):
    """Mesh-aware RMSNorm entry point (the one model code should call).

    Single device: the Pallas kernel directly. Multi-device mesh: pallas_call
    is opaque to GSPMD, so the activation is pinned to the canonical layout
    (batch over data/expert, seq over sequence, h replicated) and the kernel
    runs per-shard under partial-manual shard_map — same pattern as
    ops/attention/core._flash_sharded. shard_map is differentiable: w enters
    replicated (P()), so its cotangent is psum'd across shards by the
    transpose, and dx stays in the activation layout. Falls back to the jnp
    reference whenever the layout preconditions don't hold.
    """
    if not _use_pallas(interpret):
        return rms_norm_reference(x, w, eps)

    from deepspeed_tpu.parallel.topology import get_topology

    topo = get_topology()
    if topo.world_size == 1:
        return fused_rms_norm(x, w, eps, interpret)
    if x.ndim != 3:
        return rms_norm_reference(x, w, eps)
    b, s, _h = x.shape
    batch_div = topo.data_parallel_size * topo.expert_parallel_size
    seq_div = topo.sequence_parallel_size
    if b % batch_div or s % seq_div:
        return rms_norm_reference(x, w, eps)

    from jax.sharding import NamedSharding, PartitionSpec as P

    from deepspeed_tpu.parallel.topology import BATCH_AXES, SEQUENCE_AXIS

    spec = P(BATCH_AXES, SEQUENCE_AXIS, None)
    x = jax.lax.with_sharding_constraint(x, NamedSharding(topo.mesh, spec))
    fn = jax.shard_map(
        lambda x_, w_: fused_rms_norm(x_, w_, eps, interpret),
        mesh=topo.mesh,
        in_specs=(spec, P()),
        out_specs=spec,
        axis_names={*BATCH_AXES, SEQUENCE_AXIS},
        check_vma=False,
    )
    try:
        return fn(x, w)
    except Exception as e:
        # e.g. nested-manual-axis contexts the current JAX can't compose;
        # trace-time failure, so the jnp path is a safe same-semantics swap —
        # but say so once, or a dead kernel path hides as an MFU regression
        global _SHARDED_FALLBACK_WARNED
        if not _SHARDED_FALLBACK_WARNED:
            _SHARDED_FALLBACK_WARNED = True
            import logging

            logging.getLogger(__name__).warning(
                "sharded rms_norm kernel dispatch failed (%s: %s); "
                "falling back to the jnp reference path",
                type(e).__name__,
                e,
            )
        return rms_norm_reference(x, w, eps)


def fused_layer_norm(x, w, b, eps=1e-5):
    """LayerNorm: jnp semantics (XLA fuses this well already); kept as the
    single entry point so a Pallas variant can swap in transparently."""
    return layer_norm_reference(x, w, b, eps)
