"""zero_to_fp32: offline fp32 weight reconstruction from a checkpoint.

Reference: ``deepspeed/utils/zero_to_fp32.py`` — the standalone script the
engine copies into every checkpoint directory (engine._copy_recovery_script
:3991) so users can rebuild a consolidated fp32 state dict from per-rank
ZeRO shard files without the training stack.

TPU form: checkpoints are orbax global-array stores, so "reconstruction" is
a single restore on CPU (no shard-merging arithmetic — orbax reassembles the
global arrays) followed by an fp32 cast of the half-precision params. When
the checkpoint carries the optimizer's fp32 master weights, those are
preferred — they are the exact values, not a bf16 round trip.

Usage (standalone, no TPU needed):
    python zero_to_fp32.py <checkpoint_dir> <output_file> [--tag TAG]
Produces an .npz mapping dotted parameter names to fp32 numpy arrays
(loadable with np.load; keys match save_16bit_model's layout).
"""

import argparse
import json
import os
import sys


def _flatten(prefix, tree, out):
    # key scheme matches checkpoint/engine.py save_16bit_model exactly
    # (including its unconditional ".{i}" for sequences) so the two .npz
    # exports line up key-for-key
    if isinstance(tree, dict):
        for k, v in tree.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            _flatten(f"{prefix}.{i}", v, out)
    elif hasattr(tree, "shape"):
        out[prefix] = tree
    return out


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=None):
    """Reference-parity function name. Returns {dotted_name: fp32 ndarray}."""
    import numpy as np

    # force CPU so this runs on any login/CPU node (reference script likewise
    # runs without GPUs)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import orbax.checkpoint as ocp

    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        if not os.path.isfile(latest):
            raise FileNotFoundError(f"no 'latest' in {checkpoint_dir}; pass --tag")
        tag = open(latest).read().strip()
    state_path = os.path.abspath(os.path.join(checkpoint_dir, str(tag), "state"))
    if not os.path.exists(state_path):
        raise FileNotFoundError(state_path)
    with ocp.StandardCheckpointer() as ckptr:
        # restore against THIS host's devices (the checkpoint was written by a
        # different topology — the whole point of an offline converter): build
        # an abstract target from the stored metadata, everything on one CPU
        # device
        meta = ckptr.metadata(state_path)
        # orbax wraps the item pytree in StepMetadata on recent versions
        meta = getattr(meta, "item_metadata", meta)
        sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])

        def abstr(m):
            shape = getattr(m, "shape", None)
            dtype = getattr(m, "dtype", None)
            if shape is None or dtype is None:
                return m
            return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)

        target = jax.tree.map(abstr, meta)
        # prune the Adam moments: this script needs params + fp32 masters
        # only, and reads ~5x the param bytes otherwise (None subtrees are
        # skipped by the restore, matching the engine's template semantics)
        pruned = dict(target) if isinstance(target, dict) else target
        opt = pruned.get("opt_state") if isinstance(pruned, dict) else None
        if isinstance(opt, dict) and "master" in opt:
            pruned["opt_state"] = {
                k: (v if k == "master" else None) for k, v in opt.items()
            }
        if isinstance(pruned, dict) and "scaler_state" in pruned:
            pruned["scaler_state"] = None
        try:
            restored = ckptr.restore(state_path, pruned)
        except Exception as e:  # noqa: BLE001 — orbax's refusal type varies
            # by version for partial (None-subtree) targets; surface the
            # cause, then pay for the full read (which re-raises real errors)
            print(f"partial restore failed ({type(e).__name__}: {e}); "
                  "reading full state", file=sys.stderr)
            restored = ckptr.restore(state_path, target)

    params = restored.get("params", {})
    flat_params = _flatten("", params, {})
    # prefer exact fp32 masters when the optimizer state carries them
    masters = {}
    opt = restored.get("opt_state")
    if isinstance(opt, dict) and "master" in opt:
        masters = _flatten("", opt["master"], {})
    elif hasattr(opt, "master"):  # OptState namedtuple survives as dict/obj
        masters = _flatten("", opt.master, {})
    elif isinstance(opt, (list, tuple)) and opt and isinstance(opt[0], dict):
        pass  # unknown layout: fall back to casting params

    out = {}
    for name, arr in flat_params.items():
        src = masters.get(name, arr)
        out[name] = np.asarray(jax.device_get(src)).astype(np.float32)
    return out


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir, output_file, tag=None):
    import numpy as np

    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    if not output_file.endswith(".npz"):
        output_file += ".npz"
    np.savez(output_file, **sd)
    total = sum(v.size for v in sd.values())
    print(json.dumps({"output": output_file, "tensors": len(sd), "params": total}))
    return output_file


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "zero_to_fp32", description="Reconstruct consolidated fp32 weights from a checkpoint"
    )
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    args = p.parse_args(argv)
    convert_zero_checkpoint_to_fp32_state_dict(args.checkpoint_dir, args.output_file, args.tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
