"""Checkpoint save/load.

Analogue of the reference checkpoint machinery (engine.save_checkpoint
engine.py:3560, ``CheckpointEngine`` ABC runtime/checkpoint_engine/, and the
universal-checkpoint reshape pipeline checkpoint/ds_to_universal.py).

TPU-native design: checkpoints are orbax sharded array stores. Because orbax
saves *global* arrays with their own metadata and reshards on load to
whatever sharding the restore target declares, every checkpoint is already a
"universal checkpoint" — resuming at a different dp/tp/pp world size is the
default behavior, not an offline conversion (reference bolted this on via
``ds_to_universal.py``; SURVEY.md §7 called for building it in from day one).

Layout (mirrors the reference's tag-directory scheme):
    <save_dir>/<tag>/state/...       orbax store (params/opt_state/scaler)
    <save_dir>/<tag>/client_state.json
    <save_dir>/latest                text file naming the newest tag
"""

import json
import os
from typing import Any, Optional

import jax
import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save_checkpoint(save_dir, tag, params, opt_state, scaler_state, client_state, save_latest=True):
    ocp = _ocp()
    path = os.path.abspath(os.path.join(save_dir, str(tag)))
    os.makedirs(path, exist_ok=True)
    state = {"params": params, "opt_state": opt_state, "scaler_state": scaler_state}
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.join(path, "state"), state, force=True)
    if jax.process_index() == 0:
        with open(os.path.join(path, "client_state.json"), "w") as f:
            json.dump(client_state, f, default=str)
        if save_latest:
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(str(tag))
        copy_recovery_script(save_dir)
    log_dist(f"Saved checkpoint {path}", ranks=[0])
    return path


def copy_recovery_script(save_dir: str):
    """Ship the standalone fp32 recovery script with the checkpoint
    (reference engine._copy_recovery_script :3991). Shared by the orbax path
    and the pluggable writer engines."""
    try:
        import shutil

        src = os.path.join(os.path.dirname(os.path.abspath(__file__)), "zero_to_fp32.py")
        shutil.copy2(src, os.path.join(save_dir, "zero_to_fp32.py"))
    except OSError as e:
        logger.warning(f"could not copy zero_to_fp32.py into checkpoint dir: {e}")


def _read_latest(load_dir):
    latest = os.path.join(load_dir, "latest")
    if os.path.exists(latest):
        with open(latest) as f:
            return f.read().strip()
    return None


def load_checkpoint(load_dir, tag, params_template, opt_state_template=None, scaler_template=None):
    ocp = _ocp()
    tag = tag or _read_latest(load_dir)
    if tag is None:
        logger.warning(f"No 'latest' file found in {load_dir}; cannot auto-resume")
        return None
    path = os.path.abspath(os.path.join(load_dir, str(tag)))
    if not os.path.exists(os.path.join(path, "state")):
        logger.warning(f"Checkpoint {path} not found")
        return None
    target = {
        "params": params_template,
        "opt_state": opt_state_template,
        "scaler_state": scaler_template,
    }
    # Restore against abstract shardings of the current topology: this IS the
    # universal-checkpoint reshape (orbax reads the global layout and
    # redistributes to the target shardings).
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        if hasattr(x, "shape")
        else x,
        target,
    )
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.join(path, "state"), abstract)
    client_state = {}
    cs_path = os.path.join(path, "client_state.json")
    if os.path.exists(cs_path):
        with open(cs_path) as f:
            client_state = json.load(f)
    log_dist(f"Loaded checkpoint {path}", ranks=[0])
    return {
        "params": restored["params"],
        "opt_state": restored["opt_state"],
        "scaler_state": restored["scaler_state"],
        "client_state": client_state,
        "load_path": path,
    }


def save_16bit_model(save_dir, save_filename, params):
    """Consolidated single-file export (reference save_16bit_model :4135):
    gather every shard to host, save one .npz."""
    os.makedirs(save_dir, exist_ok=True)
    host_params = jax.tree.map(lambda p: np.asarray(jax.device_get(p)), params)
    flat = {}

    def flatten(prefix, tree):
        if isinstance(tree, dict):
            for k, v in tree.items():
                flatten(f"{prefix}.{k}" if prefix else str(k), v)
        elif isinstance(tree, (list, tuple)):
            for i, v in enumerate(tree):
                flatten(f"{prefix}.{i}", v)
        else:
            flat[prefix] = tree

    flatten("", host_params)
    out = os.path.join(save_dir, save_filename.replace(".bin", ".npz") if save_filename.endswith(".bin") else save_filename)
    np.savez(out, **flat)
    log_dist(f"Saved 16-bit model to {out}", ranks=[0])
    return out
