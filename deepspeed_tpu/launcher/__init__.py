"""Launcher package (reference deepspeed/launcher/): the dstpu CLI, per-node
launch, and multi-node runner command construction."""

from deepspeed_tpu.launcher.multinode_runner import (
    GcloudRunner,
    MultiNodeRunner,
    PDSHRunner,
    SlurmRunner,
    SSHRunner,
)
from deepspeed_tpu.launcher.runner import (
    main,
    parse_hostfile,
    parse_inclusion_exclusion,
)

__all__ = [
    "GcloudRunner",
    "MultiNodeRunner",
    "PDSHRunner",
    "SSHRunner",
    "SlurmRunner",
    "main",
    "parse_hostfile",
    "parse_inclusion_exclusion",
]
