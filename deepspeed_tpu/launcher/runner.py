"""``dstpu`` — the launcher CLI (reference ``bin/deepspeed`` →
``launcher/runner.py:436 main``).

Single host:   dstpu train.py --config ds_config.json
Multi host:    dstpu --hostfile hosts.txt train.py ...
Cloud TPU pod: dstpu --tpu my-pod --num_nodes 4 train.py ...

Responsibilities (mirroring the reference):
  * hostfile parsing (``hostname slots=N``, reference runner.py:230-275)
  * ``--include``/``--exclude`` resource filtering (:310)
  * runner selection (pdsh/ssh/gcloud/slurm) + per-host command construction
  * env propagation via ``.dstpu_env`` (the ``.deepspeed_env`` analogue,
    :588) and ``--export`` KEY=VALUE
  * master address/port selection; DSTPU_* bootstrap env that
    ``comm.init_distributed`` consumes

A TPU host runs one process owning all local chips, so "slots" count hosts'
processes (usually 1), not accelerators — accelerator topology comes from
the config's ``mesh`` section.
"""

import argparse
import os
import subprocess
import sys
from pathlib import Path
from typing import Dict

from deepspeed_tpu.launcher.multinode_runner import (
    GcloudRunner,
    PDSHRunner,
    SlurmRunner,
    SSHRunner,
)
from deepspeed_tpu.utils.logging import logger

DSTPU_ENVIRONMENT_NAME = ".dstpu_env"
EXPORT_ENVS = ("PYTHONPATH", "JAX_", "LIBTPU", "TPU_", "XLA_", "DSTPU_")


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="dstpu", description="deepspeed_tpu multi-host launcher",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter,
    )
    p.add_argument("--hostfile", type=str, default="/job/hostfile", help="hostname slots=N lines")
    p.add_argument("--include", type=str, default="", help='e.g. "host1@host2" to select hosts')
    p.add_argument("--exclude", type=str, default="", help='e.g. "host3" to drop hosts')
    p.add_argument("--num_nodes", type=int, default=-1, help="limit to first N hosts (-1 = all)")
    p.add_argument("--master_addr", type=str, default="", help="coordinator address (default: first host)")
    p.add_argument("--master_port", type=int, default=29500)
    p.add_argument("--launcher", type=str, default="", choices=["", "pdsh", "ssh", "gcloud", "slurm"])
    p.add_argument("--tpu", dest="tpu_name", type=str, default="", help="Cloud TPU name (gcloud runner)")
    p.add_argument("--zone", type=str, default="", help="Cloud TPU zone")
    p.add_argument("--remote_python", type=str, default="", help="python interpreter on the workers")
    p.add_argument("--export", action="append", default=[], help="KEY=VALUE to export on every host")
    p.add_argument("--force_multi", action="store_true", help="multi-node path even for one host")
    p.add_argument("--module", action="store_true", help="run user_script with python -m")
    p.add_argument("--no_python", action="store_true", help="exec user_script directly")
    p.add_argument(
        "--autotuning", type=str, default="", choices=["", "tune", "dry"],
        metavar="MODE",
        help="run the autotuner instead of launching: 'tune' (subprocess "
        "experiments over stage/micro/remat-policy/flash-block/shape, "
        "cost-model ordered) or 'dry' (print the ranked candidate space)",
    )
    p.add_argument(
        "--autotuning_preset", type=str, default="bench-767m",
        help="model preset whose shape neighborhood the tuner searches",
    )
    p.add_argument(
        "--autotuning_experiments", type=int, default=12,
        help="experiment budget (each is a fresh subprocess)",
    )
    p.add_argument("user_script", type=str, nargs="?", default="", help="training script (or module with --module)")
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def run_autotuning(args) -> int:
    """``dstpu --autotuning tune`` (reference ``deepspeed --autotuning`` +
    autotuner.tune()): search the extended space around a preset with real
    subprocess experiments; print the best config as one JSON line."""
    import json

    from deepspeed_tpu.autotuning import (
        Autotuner,
        AutotunerConfig,
        ModelInfo,
        SubprocessRunner,
        estimate_params,
    )
    from deepspeed_tpu.models.transformer import PRESETS

    base = dict(PRESETS[args.autotuning_preset])
    hidden = base.get("hidden_size", 1024)
    heads = base.get("n_heads", 8)
    # shape neighborhood: the preset itself + width/GQA neighbors at a
    # similar parameter budget (the knob family the round-3 MFU wins came
    # from — hand-swept then, searched now)
    head_dim = hidden // heads
    base_kv = base.get("n_kv_heads") or heads
    gqa_ratio = max(1, heads // base_kv)
    shapes = [dict(base)]
    for h_mult, head_mult in ((0.8, 1.0), (1.25, 1.0), (1.0, 0.5)):
        s = dict(base)
        # width neighbors keep the base HEAD DIM and GQA RATIO: the head
        # count snaps to a multiple of the kv count so n_heads % n_kv_heads
        # holds (naive rounding produced only invalid candidates before)
        want = max(1, int(round(heads * h_mult * head_mult)))
        if base_kv == 1:
            # MQA: any head count divides kv=1 — snapping through the ratio
            # would collapse every neighbor back onto the base shape
            new_kv, new_heads = 1, want
        else:
            new_kv = max(1, want // gqa_ratio)
            new_heads = new_kv * gqa_ratio
        s["hidden_size"] = new_heads * head_dim
        s["n_heads"] = new_heads
        if base.get("n_kv_heads"):
            s["n_kv_heads"] = new_kv
        if s["hidden_size"] == hidden and new_heads == heads:
            continue
        shapes.append(s)
    import jax

    on_tpu = jax.default_backend() == "tpu"
    hbm = 16e9 if on_tpu else 64e9  # CPU smoke runs are unconstrained
    mi = ModelInfo(
        num_params=estimate_params(base),
        hidden_size=hidden,
        num_layers=base.get("n_layers", 4),
        seq_len=base.get("max_seq_len", 2048),
    )
    cfg = AutotunerConfig(
        enabled=True,
        metric="throughput",
        fast=True,
        max_experiments=args.autotuning_experiments,
        stages=(3,),
        micro_batch_sizes=(2, 4, 6, 8),
        remat_policies=("nothing", "flash", "dots_with_no_batch_dims"),
        flash_blocks=(512, 1024) if on_tpu else (512,),
        # int8 only pays on hardware with a native int8 MXU rate — CPU smoke
        # searches skip it to keep the space small
        matmul_precisions=("default", "int8") if on_tpu else ("default",),
        shapes=tuple(shapes),
    )
    runner = SubprocessRunner(
        metric="mfu_pct" if on_tpu else "tok_s",
        platform=None if on_tpu else "cpu",
        steps=6 if on_tpu else 2,
        warmup=2 if on_tpu else 1,
    )
    tuner = Autotuner(mi, int(hbm), dp_world=1, runner=runner, config=cfg)
    if args.autotuning == "dry":
        for exp in tuner._space()[: args.autotuning_experiments]:
            print(json.dumps(exp))
        return 0
    best, best_val = tuner.tune()
    print(tuner.summary())
    print(json.dumps({"best": best, "metric": best_val}))
    return 0 if best is not None else 1


def parse_hostfile(path: str) -> Dict[str, int]:
    """``hostname slots=N`` per line; '#' comments (reference runner.py:230)."""
    resources: Dict[str, int] = {}
    if not os.path.isfile(path):
        return resources
    for lineno, line in enumerate(Path(path).read_text().splitlines(), 1):
        line = line.split("#")[0].strip()
        if not line:
            continue
        parts = line.split()
        host = parts[0]
        slots = 1
        for tok in parts[1:]:
            if tok.startswith("slots="):
                try:
                    slots = int(tok.split("=", 1)[1])
                except ValueError as e:
                    raise ValueError(f"{path}:{lineno}: bad slots in {line!r}") from e
        if host in resources:
            raise ValueError(f"{path}:{lineno}: duplicate host {host!r}")
        resources[host] = slots
    return resources


def parse_inclusion_exclusion(resources: Dict[str, int], include: str, exclude: str) -> Dict[str, int]:
    """Filter hosts: '@'-separated host names (reference parse_resource_filter
    runner.py:310 — slot-level filtering is meaningless on TPU hosts, where a
    process owns every local chip, so only host granularity is supported)."""
    if include and exclude:
        raise ValueError("--include and --exclude are mutually exclusive")
    if include:
        chosen = {}
        for h in include.split("@"):
            h = h.strip()
            if ":" in h:
                raise ValueError(
                    f"slot-level include {h!r} unsupported on TPU (one process per host)"
                )
            if h not in resources:
                raise ValueError(f"include host {h!r} not in hostfile")
            chosen[h] = resources[h]
        return chosen
    if exclude:
        dropped = {h.strip() for h in exclude.split("@")}
        for h in dropped:
            if h not in resources:
                raise ValueError(f"exclude host {h!r} not in hostfile")
        return {h: s for h, s in resources.items() if h not in dropped}
    return dict(resources)


def collect_env(args) -> Dict[str, str]:
    """Env to propagate: allowlisted prefixes from the current env, the
    ``.dstpu_env`` file (cwd then $HOME, reference .deepspeed_env), and
    explicit --export KEY=VALUE."""
    exports: Dict[str, str] = {}
    for k, v in os.environ.items():
        if any(k.startswith(p) for p in EXPORT_ENVS):
            exports[k] = v
    for base in (Path.cwd(), Path.home()):
        f = base / DSTPU_ENVIRONMENT_NAME
        if f.is_file():
            for line in f.read_text().splitlines():
                line = line.strip()
                if not line or line.startswith("#") or "=" not in line:
                    continue
                k, v = line.split("=", 1)
                exports[k.strip()] = v.strip()
            break
    for kv in args.export:
        if "=" not in kv:
            raise ValueError(f"--export needs KEY=VALUE, got {kv!r}")
        k, v = kv.split("=", 1)
        exports[k] = v
    return exports


def select_runner(args, world_info):
    name = args.launcher
    if not name:
        name = "gcloud" if args.tpu_name else "pdsh"
    cls = {"pdsh": PDSHRunner, "ssh": SSHRunner, "gcloud": GcloudRunner, "slurm": SlurmRunner}[name]
    return cls(args, world_info)


def run_local(args, env: Dict[str, str]) -> int:
    """Single-host path: exec the user script in-place with the env set
    (reference runner.py single-node shortcut)."""
    child_env = {**os.environ, **env}
    child_env.setdefault("DSTPU_NUM_PROCESSES", "1")
    child_env.setdefault("DSTPU_PROCESS_ID", "0")
    if args.no_python:
        cmd = [args.user_script]
    elif args.module:
        cmd = [sys.executable, "-u", "-m", args.user_script]
    else:
        cmd = [sys.executable, "-u", args.user_script]
    cmd += list(args.user_args)
    logger.info(f"dstpu local launch: {' '.join(cmd)}")
    return subprocess.call(cmd, env=child_env)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv and argv[0] == "generate":
        # serve a real checkpoint dir: dstpu generate --model DIR --prompt ...
        from deepspeed_tpu.inference.cli import generate_main

        return generate_main(argv[1:])
    if argv and argv[0] == "serve":
        # long-lived HTTP serving: dstpu serve --model DIR --port 8000
        from deepspeed_tpu.inference.cli import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "serve-agent":
        # remote decode replica: dstpu serve-agent --model DIR --join H:P
        from deepspeed_tpu.inference.cli import serve_agent_main

        return serve_agent_main(argv[1:])
    if argv and argv[0] == "lint":
        # static analysis: dstpu lint deepspeed_tpu/ [--verify] [--fail-on error]
        from deepspeed_tpu.analysis.cli import lint_main

        return lint_main(argv[1:])
    if argv and argv[0] == "trace":
        # timeline tooling: dstpu trace dump --url http://HOST:PORT --out X
        from deepspeed_tpu.observability.cli import trace_main

        return trace_main(argv[1:])
    args = parse_args(argv)
    if args.autotuning:
        return run_autotuning(args)
    if not args.user_script:
        print("dstpu: user_script is required (or pass --autotuning tune)", file=sys.stderr)
        return 2
    if args.tpu_name:
        # Cloud TPU: workers are addressed through gcloud + metadata; a
        # hostfile would conflate two addressing schemes, so it is ignored
        if os.path.isfile(args.hostfile):
            logger.warning(f"--tpu given: ignoring hostfile {args.hostfile}")
        n = max(args.num_nodes, 1)
        resources = {f"worker-{i}": 1 for i in range(n)}
        multi = True
    else:
        resources = parse_hostfile(args.hostfile)
        resources = parse_inclusion_exclusion(resources, args.include, args.exclude)
        if args.num_nodes > 0:
            resources = dict(list(resources.items())[: args.num_nodes])
        multi = bool(resources) and (len(resources) > 1 or args.force_multi)

    env = collect_env(args)
    if not multi:
        return run_local(args, env)

    if not args.master_addr:
        args.master_addr = next(iter(resources))
    runner = select_runner(args, resources)
    for k, v in env.items():
        runner.add_export(k, v)
    if not runner.backend_exists():
        raise RuntimeError(f"launcher backend {runner.name!r} not found on PATH")

    if isinstance(runner, SSHRunner):
        procs = []
        for i, host in enumerate(runner.hosts):
            cmd = runner.get_host_cmd(host, i)
            logger.info(f"dstpu ssh[{i}]: {' '.join(cmd)}")
            procs.append(subprocess.Popen(cmd))
        rc = 0
        for p in procs:
            rc = p.wait() or rc  # reap every host; keep the first failure
        return rc

    cmd = runner.get_cmd(dict(os.environ), resources)
    logger.info(f"dstpu {runner.name} launch: {' '.join(cmd[:8])} ...")
    return subprocess.call(cmd)


if __name__ == "__main__":
    sys.exit(main())
