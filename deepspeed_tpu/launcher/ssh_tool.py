"""dstpu_ssh: run a command on every host in a hostfile (reference
``bin/ds_ssh`` — a pdsh/ssh fan-out convenience for cluster admin:
checking versions, clearing caches, killing stray jobs)."""

import argparse
import subprocess
import sys
from concurrent.futures import ThreadPoolExecutor

from deepspeed_tpu.launcher.runner import parse_hostfile


def main(argv=None) -> int:
    p = argparse.ArgumentParser("dstpu_ssh", description=__doc__)
    p.add_argument("-f", "--hostfile", default="/job/hostfile")
    p.add_argument("--ssh_port", type=int, default=22)
    p.add_argument("--timeout", type=int, default=60)
    p.add_argument("--dry_run", action="store_true",
                   help="print the per-host ssh commands without running them")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every host")
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")
    cmd = " ".join(args.command)
    hosts = list(parse_hostfile(args.hostfile))
    if not hosts:
        print(f"dstpu_ssh: no hosts in {args.hostfile!r} (missing or empty hostfile)",
              file=sys.stderr)
        return 1
    if args.dry_run:
        for host in hosts:
            print(f"ssh -o StrictHostKeyChecking=no -p {args.ssh_port} {host} {cmd}")
        return 0

    def run(host):
        try:
            r = subprocess.run(
                ["ssh", "-o", "StrictHostKeyChecking=no", "-p", str(args.ssh_port),
                 host, cmd],
                capture_output=True, text=True, timeout=args.timeout,
            )
        except subprocess.TimeoutExpired:
            # one hung host must not abort the whole fan-out (reference
            # ds_ssh keeps going); report it and continue
            return host, 124, "", f"timeout after {args.timeout}s"
        return host, r.returncode, r.stdout, r.stderr

    rc = 0
    with ThreadPoolExecutor(max_workers=min(32, len(hosts))) as pool:
        for host, code, out, err in pool.map(run, hosts):
            prefix = f"[{host}] "
            for line in (out or "").splitlines():
                print(prefix + line)
            for line in (err or "").splitlines():
                print(prefix + line, file=sys.stderr)
            rc = rc or code
    return rc
