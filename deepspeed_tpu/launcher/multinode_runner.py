"""Multi-node runners: construct the per-host launch command lines.

Analogue of the reference ``launcher/multinode_runner.py`` (MultiNodeRunner
hierarchy :19-411 — PDSH/OpenMPI/MPICH/IMPI/Slurm/MVAPICH). The TPU set is
different because a TPU pod is driven one *process per host* (JAX owns all
local chips), and GCP TPU VMs have their own fan-out tool:

  * PDSHRunner    — pdsh fan-out over a hostfile (reference :55)
  * SSHRunner     — plain ssh per host (portable fallback)
  * GcloudRunner  — ``gcloud compute tpus tpu-vm ssh --worker=all`` (the
                    idiomatic pod launcher on Cloud TPU)
  * SlurmRunner   — srun (reference SlurmRunner :305)

Runners only *construct* command lines (unit-testable without the tools
installed); ``runner.main`` executes them.
"""

import shlex
import shutil
import sys
from abc import ABC, abstractmethod
from typing import Dict, List


class MultiNodeRunner(ABC):
    def __init__(self, args, world_info: Dict[str, int]):
        self.args = args
        self.world_info = world_info  # hostname -> slots
        self.user_arguments = list(args.user_args or [])
        self.user_script = args.user_script
        self.exports: Dict[str, str] = {}

    def add_export(self, key: str, value: str):
        self.exports[key.strip()] = str(value).strip()

    @property
    def hosts(self) -> List[str]:
        return list(self.world_info.keys())

    @abstractmethod
    def get_cmd(self, environment: Dict[str, str], active_resources) -> List[str]:
        """Full fan-out command line for this runner."""

    @abstractmethod
    def backend_exists(self) -> bool:
        """Is the underlying tool available on this machine?"""

    @property
    def name(self) -> str:
        return type(self).__name__.replace("Runner", "").lower()

    def _remote_python(self) -> str:
        """The interpreter on the workers. The launching machine's
        sys.executable is only valid when launching from a worker-identical
        image; gcloud (workstation → pod) defaults to python3."""
        return getattr(self.args, "remote_python", "") or sys.executable

    def _script_cmd(self, extra_env: Dict[str, str], coordinator: bool = True) -> str:
        """The per-host inner command: exports + python + script + args.
        Every token is shell-quoted — it is re-parsed by the remote shell."""
        parts = []
        for k, v in {**self.exports, **extra_env}.items():
            parts.append(f"export {k}={shlex.quote(v)};")
        launch = [self._remote_python(), "-u", "-m", "deepspeed_tpu.launcher.launch"]
        if coordinator:
            launch += ["--coordinator", self.args.master_addr, "--port", str(self.args.master_port)]
        if getattr(self.args, "module", False):
            launch.append("--module")
        if getattr(self.args, "no_python", False):
            launch.append("--no_python")
        launch.append(self.user_script)
        launch += self.user_arguments
        return " ".join(parts + [shlex.quote(p) for p in launch])


class PDSHRunner(MultiNodeRunner):
    """Reference PDSHRunner (multinode_runner.py:55): one pdsh invocation,
    %n/%h substitution not needed — the node launcher derives its process id
    from its position in DSTPU_HOSTS."""

    def backend_exists(self) -> bool:
        return shutil.which("pdsh") is not None

    def get_cmd(self, environment, active_resources):
        env = dict(environment)
        env["PDSH_RCMD_TYPE"] = "ssh"
        hosts = ",".join(self.hosts)
        extra = {
            "DSTPU_COORDINATOR": self.args.master_addr,
            "DSTPU_NUM_PROCESSES": str(len(self.hosts)),
            "DSTPU_HOSTS": ",".join(self.hosts),
        }
        return ["pdsh", "-S", "-f", "1024", "-w", hosts, self._script_cmd(extra)]


class SSHRunner(MultiNodeRunner):
    """One ssh per host (executed concurrently by runner.main). Process id is
    passed explicitly per host."""

    def backend_exists(self) -> bool:
        return shutil.which("ssh") is not None

    def get_cmd(self, environment, active_resources):
        # returns the command for host 0; use get_host_cmd for each host
        return self.get_host_cmd(self.hosts[0], 0)

    def get_host_cmd(self, host: str, process_id: int) -> List[str]:
        extra = {
            "DSTPU_COORDINATOR": self.args.master_addr,
            "DSTPU_NUM_PROCESSES": str(len(self.hosts)),
            "DSTPU_PROCESS_ID": str(process_id),
        }
        return ["ssh", "-o", "StrictHostKeyChecking=no", host, self._script_cmd(extra)]


class GcloudRunner(MultiNodeRunner):
    """Cloud TPU pod fan-out: ``gcloud compute tpus tpu-vm ssh NAME
    --worker=all --command=...``. On TPU VMs jax.distributed discovers the
    coordinator from instance metadata, so only the mesh/env exports ride
    along; DSTPU_* are still set for parity with bare-metal runs."""

    def backend_exists(self) -> bool:
        return shutil.which("gcloud") is not None

    def _remote_python(self) -> str:
        # workstation → pod: the local interpreter path is meaningless remotely
        return getattr(self.args, "remote_python", "") or "python3"

    def get_cmd(self, environment, active_resources):
        # No DSTPU_COORDINATOR / PROCESS_ID exports: on Cloud TPU VMs
        # jax.distributed.initialize() discovers coordinator + process id
        # from instance metadata (TPU_WORKER_ID/TPU_WORKER_HOSTNAMES), which
        # is the only scheme that works when launching from a workstation —
        # fabricated worker-N hostnames would neither resolve nor be unique.
        extra = {"DSTPU_POD": "1"}
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", "ssh", self.args.tpu_name, "--worker=all"]
        if getattr(self.args, "zone", None):
            cmd.append(f"--zone={self.args.zone}")
        cmd.append(f"--command={self._script_cmd(extra, coordinator=False)}")
        return cmd


class SlurmRunner(MultiNodeRunner):
    """Reference SlurmRunner (multinode_runner.py:305): srun launches one
    task per node; SLURM_PROCID provides the process id."""

    def backend_exists(self) -> bool:
        return shutil.which("srun") is not None

    def get_cmd(self, environment, active_resources):
        n = len(self.hosts) or self.args.num_nodes
        extra = {
            "DSTPU_COORDINATOR": self.args.master_addr,
            "DSTPU_NUM_PROCESSES": str(n),
        }
        cmd = ["srun", "--nodes", str(n), "--ntasks-per-node", "1"]
        if self.hosts:
            cmd += ["--nodelist", ",".join(self.hosts)]
        cmd += ["bash", "-c", self._script_cmd(extra)]
        return cmd
