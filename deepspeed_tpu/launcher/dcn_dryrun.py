"""Two-process (DCN-path) training dryrun.

The only place the framework's multi-PROCESS claims are proven without a
real multi-host slice (VERDICT r4 missing #3): ``comm.init_distributed`` →
``jax.distributed.initialize`` (comm/comm.py) with gloo CPU cross-process
collectives, global-array batch feeding, orbax multi-process checkpoint
save, and universal-checkpoint resume at a DIFFERENT process count.

Mirrors the reference's multi-process ``DistributedTest`` harness
(reference tests/unit/common.py:134,265 — forked subprocess ranks against a
per-test master port) as three phases:

  oracle  — 1 process × n devices trains ``steps+1`` steps straight through
  workers — 2 processes × n/2 devices train ``steps`` steps (spawned through
            the real per-node launcher, launcher/launch.py, so the
            DSTPU_COORDINATOR/DSTPU_PROCESS_ID env contract is exercised),
            then save an orbax checkpoint
  resume  — 1 process × n devices loads that checkpoint (process-count
            reshape) and trains one more step

Parity asserted: worker losses == oracle losses for steps 1..n, and the
resumed step equals the oracle's step n+1 — so cross-process collectives
and the checkpoint reshape both preserve the math exactly (fp32).
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile

# fixed tiny-config knobs shared by every phase (fp32 for exact parity;
# bf16 psum on the XLA CPU backend is a known compiler crash — see
# __graft_entry__ leg 2 note)
_TP = 2
_STEPS = 2
_SEQ = 129
_SEED = 11


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _kill_tree(proc):
    """SIGKILL a child's whole process group (children were started with
    start_new_session=True, so the group == the subtree)."""
    import signal

    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        try:
            proc.kill()
        except ProcessLookupError:
            pass


def build_leg_env(n_devices: int, replace_device_count: bool = False) -> dict:
    """Isolated-subprocess env: n-device virtual CPU mesh + capped thread
    pools (single-threaded Eigen/BLAS keeps worker count == device count so
    every collective-rendezvous participant can always be scheduled — the
    round-4 gate-flake fix). Shared by the dryrun orchestrator
    (__graft_entry__._leg_env) and this module's phase spawns;
    ``replace_device_count=True`` drops an inherited device-count flag so a
    phase can use a DIFFERENT per-process count than its parent."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "").split()
    if replace_device_count:
        flags = [f for f in flags if "xla_force_host_platform_device_count" not in f]
    if not any("xla_force_host_platform_device_count" in f for f in flags):
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    if not any("xla_cpu_multi_thread_eigen" in f for f in flags):
        flags.append("--xla_cpu_multi_thread_eigen=false")
    env["XLA_FLAGS"] = " ".join(flags)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("DS_ACCELERATOR", "cpu")
    for var in ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS", "MKL_NUM_THREADS"):
        env[var] = "1"
    return env


def _child_env(n_local_devices: int, extra=None) -> dict:
    """Phase env: workers get n/2 local devices, oracle/resume get n."""
    env = build_leg_env(n_local_devices, replace_device_count=True)
    env["DSTPU_N_LOCAL_DEVICES"] = str(n_local_devices)
    env.update(extra or {})
    return env


def run_two_process_dryrun(n_devices: int, log_prefix="dcn-dryrun", timeout_s=420.0):
    """Parent orchestrator — see module docstring. Raises on any phase
    failure or parity miss."""
    if n_devices % 2 != 0:
        raise ValueError("two-process leg needs an even device count")
    n_local = n_devices // 2
    with tempfile.TemporaryDirectory(prefix="dstpu_dcn_") as tmp:
        ckpt_dir = os.path.join(tmp, "ckpt")
        results = {}

        def phase(role, cmd, env):
            p = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, start_new_session=True,
            )
            try:
                out, err = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                _kill_tree(p)
                out, err = p.communicate()
                sys.stderr.write(out or "")
                sys.stderr.write(err or "")
                raise RuntimeError(f"{log_prefix}: {role} phase timed out after {timeout_s}s")
            if p.returncode != 0:
                sys.stderr.write(out or "")
                sys.stderr.write(err or "")
                raise RuntimeError(f"{log_prefix}: {role} phase rc={p.returncode}")
            with open(os.path.join(tmp, f"{role}.json")) as f:
                return json.load(f)

        base_args = [
            "--n-devices", str(n_devices), "--ckpt-dir", ckpt_dir,
            "--out-dir", tmp,
        ]

        # --- oracle: 1 process, full mesh, steps+1 straight through ---
        results["oracle"] = phase(
            "oracle",
            [sys.executable, "-m", "deepspeed_tpu.launcher.dcn_dryrun",
             "--role", "oracle", *base_args],
            _child_env(n_devices),
        )

        # --- workers: 2 processes through the real launcher ---
        port = _free_port()
        procs = []
        for pid in range(2):
            env = _child_env(
                n_local,
                # MASTER_PORT must be explicit: launch.py only setdefault()s
                # it, so an inherited 29500 from the ambient env would
                # override the freshly allocated free port and collide with
                # any concurrent run on this host
                extra={"DSTPU_NUM_PROCESSES": "2", "MASTER_PORT": str(port)},
            )
            cmd = [
                sys.executable, "-m", "deepspeed_tpu.launcher.launch",
                "--coordinator", "127.0.0.1", "--port", str(port),
                "--process_id", str(pid), "--module",
                "deepspeed_tpu.launcher.dcn_dryrun",
                "--role", "worker", *base_args,
            ]
            procs.append(subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, start_new_session=True,
            ))
        outs = []
        for pid, p in enumerate(procs):
            try:
                out, err = p.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                # kill the whole tree of EVERY worker: SIGKILL on the
                # launch.py wrapper alone orphans the actual training
                # process inside a gloo rendezvous
                for q in procs:
                    _kill_tree(q)
                out, err = p.communicate()
                sys.stderr.write(out or "")
                sys.stderr.write(err or "")
                raise RuntimeError(f"{log_prefix}: worker {pid} timed out")
            outs.append((p.returncode, out, err))
        for pid, (rc, out, err) in enumerate(outs):
            if rc != 0:
                sys.stderr.write(out or "")
                sys.stderr.write(err or "")
                raise RuntimeError(f"{log_prefix}: worker {pid} rc={rc}")
        with open(os.path.join(tmp, "worker.json")) as f:
            results["worker"] = json.load(f)

        # --- resume: 1 process, different process count than the save ---
        results["resume"] = phase(
            "resume",
            [sys.executable, "-m", "deepspeed_tpu.launcher.dcn_dryrun",
             "--role", "resume", *base_args],
            _child_env(n_devices),
        )

    oracle = results["oracle"]["losses"]
    worker = results["worker"]["losses"]
    resumed = results["resume"]["losses"]
    if not (len(worker) == _STEPS and len(oracle) == _STEPS + 1 and len(resumed) == 1):
        raise RuntimeError(
            f"{log_prefix}: phase result counts off — worker {len(worker)}, "
            f"oracle {len(oracle)}, resumed {len(resumed)}")
    for i, (w, o) in enumerate(zip(worker, oracle)):
        if abs(w - o) > 1e-3 * max(abs(o), 1e-6):
            raise RuntimeError(
                f"{log_prefix}: 2-process step {i} loss {w:.6f} != 1-process {o:.6f}"
                " — cross-process collectives changed the math"
            )
    if abs(resumed[0] - oracle[_STEPS]) > 1e-3 * max(abs(oracle[_STEPS]), 1e-6):
        raise RuntimeError(
            f"{log_prefix}: resumed step loss {resumed[0]:.6f} != oracle "
            f"{oracle[_STEPS]:.6f} — process-count reshape broke the state"
        )
    print(
        f"{log_prefix} OK: 2proc x {n_local}dev zero3+tp{_TP} losses "
        f"{[round(x, 4) for x in worker]} == 1proc oracle; UCP resume @1proc "
        f"loss {resumed[0]:.4f} == oracle {oracle[_STEPS]:.4f}"
    )


# --------------------------------------------------------------------------
# child phases
# --------------------------------------------------------------------------

def _setup_jax(n_local: int):
    import os

    if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        # pre-0.5 jax has no jax_num_cpu_devices; the flag must precede
        # backend init, so set it before the import below
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n_local}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_local)
    except AttributeError:
        pass  # XLA_FLAGS fallback above
    return jax


def _build(n_devices: int):
    """Model/config/engine shared by every phase (identical math)."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import (
        get_config,
        init_params,
        make_loss_fn,
        param_partition_specs,
    )
    from deepspeed_tpu.parallel.topology import Topology, reset_topology, set_topology

    cfg = get_config(
        "tiny", vocab_size=512, hidden_size=128, n_layers=2, n_heads=4,
        n_kv_heads=4, max_seq_len=256, dtype="float32",
    )
    params = init_params(cfg, jax.random.key(0))
    reset_topology()
    topo = Topology(model=_TP, devices=jax.devices()[:n_devices])
    set_topology(topo)
    tbs = topo.dp_world_size
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=make_loss_fn(cfg),
        model_parameters=params,
        mpu=topo,
        config={
            "train_batch_size": tbs,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
        },
        param_specs=param_partition_specs(cfg),
    )
    return engine, cfg, tbs


def _batch(cfg, tbs, step):
    import numpy as np

    rng = np.random.default_rng(_SEED + step)
    return {
        "input_ids": rng.integers(0, cfg.vocab_size, size=(tbs, _SEQ)).astype(np.int32)
    }


def _write(out_dir, role, payload):
    import jax

    if jax.process_index() == 0:
        with open(os.path.join(out_dir, f"{role}.json"), "w") as f:
            json.dump(payload, f)


def _role_oracle(args):
    _setup_jax(args.n_devices)
    engine, cfg, tbs = _build(args.n_devices)
    losses = [
        float(engine.train_batch(batch=_batch(cfg, tbs, s)))
        for s in range(_STEPS + 1)
    ]
    _write(args.out_dir, "oracle", {"losses": losses})


def _role_worker(args):
    n_local = int(os.environ["DSTPU_N_LOCAL_DEVICES"])
    jax = _setup_jax(n_local)
    from deepspeed_tpu import comm

    # the launcher (launch.py) exported DSTPU_COORDINATOR/DSTPU_PROCESS_ID/
    # DSTPU_NUM_PROCESSES; this is the production bootstrap path
    comm.init_distributed()
    if jax.process_count() != 2:
        raise RuntimeError(f"expected 2 jax processes, got {jax.process_count()}")
    if len(jax.devices()) != args.n_devices:
        raise RuntimeError(f"expected {args.n_devices} devices, got {len(jax.devices())}")
    engine, cfg, tbs = _build(args.n_devices)
    losses = [
        float(engine.train_batch(batch=_batch(cfg, tbs, s))) for s in range(_STEPS)
    ]
    engine.save_checkpoint(args.ckpt_dir, tag="dcn")
    engine.checkpoint_commit()
    _write(args.out_dir, "worker", {"losses": losses})


def _role_resume(args):
    _setup_jax(args.n_devices)
    engine, cfg, tbs = _build(args.n_devices)
    loaded = engine.load_checkpoint(args.ckpt_dir, tag="dcn")
    if loaded is None or not loaded[0]:
        raise RuntimeError("resume phase found no checkpoint")
    loss = float(engine.train_batch(batch=_batch(cfg, tbs, _STEPS)))
    _write(args.out_dir, "resume", {"losses": [loss]})


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--role", required=True, choices=["oracle", "worker", "resume"])
    p.add_argument("--n-devices", type=int, required=True)
    p.add_argument("--ckpt-dir", required=True)
    p.add_argument("--out-dir", required=True)
    args = p.parse_args(argv)
    {"oracle": _role_oracle, "worker": _role_worker, "resume": _role_resume}[args.role](args)


if __name__ == "__main__":
    main()
