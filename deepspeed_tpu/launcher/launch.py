"""Per-node launcher: run the user script with the distributed env set.

Analogue of the reference ``launcher/launch.py:145`` — but where the
reference spawns one process per local GPU rank with RANK/LOCAL_RANK, a TPU
host runs ONE process that owns all local chips (JAX's multi-controller
model), so this launcher:

  * derives DSTPU_PROCESS_ID (from DSTPU_HOSTS position or SLURM_PROCID when
    the fan-out tool could not pass it per host),
  * sets DSTPU_COORDINATOR / DSTPU_NUM_PROCESSES for
    ``comm.init_distributed`` (comm/comm.py),
  * execs the user script, forwarding SIGTERM/SIGINT to the child and
    killing the process tree on exit (reference launch.py:131,333).
"""

import argparse
import os
import signal
import socket
import subprocess
import sys

from deepspeed_tpu.utils.logging import logger


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="dstpu per-node launcher")
    p.add_argument("--coordinator", default=None, help="coordinator (master) address")
    p.add_argument("--port", type=int, default=29500)
    p.add_argument("--process_id", type=int, default=None, help="override this host's process id")
    p.add_argument("--module", action="store_true", help="run user_script as a python module (-m)")
    p.add_argument("--no_python", action="store_true", help="exec user_script directly")
    p.add_argument("user_script", type=str)
    p.add_argument("user_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _local_addresses() -> set:
    """Local hostname + every address it resolves to (for IP hostfiles)."""
    me = socket.gethostname()
    addrs = {me, "localhost", "127.0.0.1"}
    try:
        _, aliases, ips = socket.gethostbyname_ex(me)
        addrs.update(aliases)
        addrs.update(ips)
    except OSError:
        pass
    return addrs


def infer_process_id(env) -> int:
    """Process id resolution order: explicit env, TPU_WORKER_ID (Cloud TPU
    metadata), position of this host in DSTPU_HOSTS (pdsh path — matched by
    hostname, hostname prefix, or resolved IP, so IP hostfiles work),
    SLURM_PROCID, else 0."""
    if env.get("DSTPU_PROCESS_ID"):
        return int(env["DSTPU_PROCESS_ID"])
    if env.get("TPU_WORKER_ID"):
        return int(env["TPU_WORKER_ID"])
    hosts = [h for h in env.get("DSTPU_HOSTS", "").split(",") if h]
    if hosts:
        me = socket.gethostname()
        local = _local_addresses()
        for i, h in enumerate(hosts):
            if h in local or me.startswith(h + ".") or h.startswith(me + "."):
                return i
            try:
                if socket.gethostbyname(h) in local:
                    return i
            except OSError:
                pass
        logger.warning(f"host {me} not found in DSTPU_HOSTS={hosts}; defaulting to 0")
    if env.get("SLURM_PROCID"):
        return int(env["SLURM_PROCID"])
    return 0


def build_child_cmd(args) -> list:
    if args.no_python:
        cmd = [args.user_script]
    elif args.module:
        cmd = [sys.executable, "-u", "-m", args.user_script]
    else:
        cmd = [sys.executable, "-u", args.user_script]
    return cmd + list(args.user_args)


def main(argv=None):
    args = parse_args(argv)
    env = dict(os.environ)
    if env.get("DSTPU_POD"):
        # Cloud TPU pod: jax.distributed discovers coordinator/process id
        # from instance metadata — exporting fabricated values would break it
        pid = int(env.get("TPU_WORKER_ID", "0"))
    else:
        if args.coordinator:
            env["DSTPU_COORDINATOR"] = args.coordinator
            env.setdefault("MASTER_PORT", str(args.port))
        pid = args.process_id if args.process_id is not None else infer_process_id(env)
        env["DSTPU_PROCESS_ID"] = str(pid)
        env.setdefault("DSTPU_NUM_PROCESSES", "1")

    cmd = build_child_cmd(args)
    logger.info(f"launch: process {pid}/{env['DSTPU_NUM_PROCESSES']} exec: {' '.join(cmd)}")
    child = subprocess.Popen(cmd, env=env)

    def forward(signum, _frame):
        try:
            child.send_signal(signum)
        except ProcessLookupError:
            pass

    signal.signal(signal.SIGTERM, forward)
    signal.signal(signal.SIGINT, forward)
    rc = child.wait()
    sys.exit(rc)


if __name__ == "__main__":
    main()
