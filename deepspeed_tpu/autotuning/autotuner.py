"""Autotuner: search ZeRO stage × micro-batch × remat for best throughput.

Analogue of the reference ``autotuning/autotuner.py:42`` (``Autotuner``) +
``tuner/{index_based_tuner,model_based_tuner}.py``: a memory model prunes
infeasible (stage, micro-batch) points, then experiments run through a
user-supplied runner (the reference launches each through the CLI launcher;
here the runner is a callable so the search also works in-process — on TPU a
failed compile reports OOM deterministically, which the runner maps to
``None``). The fast path mirrors the reference's stage-ordered search with
early termination.
"""

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger

AUTOTUNING_METRICS = ("throughput", "latency", "flops")


# ---------------------------------------------------------------------------
# ZeRO memory model (reference autotuner's get_instantiation_memory_required_*
# heuristics, expressed per chip)
# ---------------------------------------------------------------------------
def zero_memory_per_chip(
    n_params: int,
    stage: int,
    dp_world: int,
    param_bytes: int = 2,
    grad_bytes: int = 4,
    optim_bytes: int = 12,  # fp32 master + two moments
) -> int:
    """Model-state bytes per chip for a ZeRO stage (the standard 2+4+12
    accounting; sharded terms divide by the data-parallel world)."""
    dp = max(dp_world, 1)
    params = n_params * param_bytes / (dp if stage >= 3 else 1)
    grads = n_params * grad_bytes / (dp if stage >= 2 else 1)
    optim = n_params * optim_bytes / (dp if stage >= 1 else 1)
    return int(params + grads + optim)


def activation_memory_per_chip(
    micro_batch: int,
    seq_len: int,
    hidden: int,
    n_layers: int,
    bytes_per_el: int = 2,
    remat: bool = True,
    saved_factor: float = 12.0,
) -> int:
    """Rough activation bytes: ``saved_factor`` elements of width ``hidden``
    per token per layer survive the remat policy (measured ~12 for the
    dots-saveable policy; ~34 with remat off)."""
    factor = saved_factor if remat else 34.0
    return int(micro_batch * seq_len * hidden * n_layers * factor * bytes_per_el)


@dataclass
class ModelInfo:
    """Reference model_info (profile step, engine.py:2198): what the memory
    model needs to prune the space."""

    num_params: int
    hidden_size: int
    num_layers: int
    seq_len: int


@dataclass
class TuningRecord:
    config: Dict[str, Any]
    metric_val: Optional[float]  # None = failed / OOM


def estimate_params(shape: Dict[str, Any]) -> int:
    """Analytic parameter count of a TransformerConfig-kwargs dict (for the
    memory model when the tuner searches SHAPE candidates — the knob class
    that actually drove the round-3 MFU wins and that the old 3-knob space
    could not express, VERDICT r3 weak #7)."""
    h = shape.get("hidden_size", 512)
    L = shape.get("n_layers", 4)
    v = shape.get("vocab_size", 32000)
    nh = shape.get("n_heads", 8)
    nkv = shape.get("n_kv_heads") or nh
    d = shape.get("head_dim_override") or h // nh
    glu = shape.get("activation", "swiglu") in ("swiglu", "geglu")
    # default ffn mirrors TransformerConfig.ffn_dim exactly: llama-style
    # 8h/3 rounded up to 256 for GLU activations, 4h otherwise (a 4h GLU
    # default overestimated MLP params ~1.5x and over-pruned candidates)
    ffn = shape.get("ffn_hidden_size") or (
        ((int(8 * h / 3) + 255) // 256) * 256 if glu else 4 * h
    )
    attn = h * nh * d + 2 * h * nkv * d + nh * d * h
    mlp = (3 if glu else 2) * h * ffn
    embed = v * h * (1 if shape.get("tie_embeddings") else 2)
    return int(L * (attn + mlp + 2 * h) + embed + h)


@dataclass
class AutotunerConfig:
    """The ``autotuning`` config section (reference autotuning/config.py).

    Round-4 extensions (VERDICT r3 #8): the space covers the knobs that
    actually moved the bench — remat POLICY (not just on/off), flash block
    size, and model-shape candidates — and candidates are cost-model-ordered
    (scheduler.predicted_score) so the experiment budget goes to promising
    points first, like the reference's model_based_tuner."""

    enabled: bool = False
    metric: str = "throughput"
    fast: bool = True
    max_experiments: int = 50
    tuner_type: str = "gridsearch"  # gridsearch | random | cost_model
    micro_batch_sizes: Sequence[int] = (1, 2, 4, 8, 16, 32)
    stages: Sequence[int] = (0, 1, 2, 3)
    remat: Sequence[bool] = (True,)
    # -- extended space (each defaults to "not searched") ------------------
    remat_policies: Sequence[str] = ()  # e.g. ("nothing", "flash", "dots")
    flash_blocks: Sequence[int] = ()  # e.g. (256, 512, 1024)
    shapes: Sequence[Dict[str, Any]] = ()  # TransformerConfig kwarg dicts
    # forward-projection precision (the +4.3pp round-4 lever: per-channel
    # int8 rides the MXU's native 2x rate) — e.g. ("default", "int8")
    matmul_precisions: Sequence[str] = ()
    seed: int = 0


class Autotuner:
    def __init__(
        self,
        model_info: ModelInfo,
        hbm_bytes_per_chip: int,
        dp_world: int,
        runner: Callable[[Dict[str, Any]], Optional[float]],
        config: Optional[AutotunerConfig] = None,
    ):
        """runner(exp_config) -> metric value (higher better) or None on
        failure/OOM — the reference's scheduler+launcher round trip."""
        self.mi = model_info
        self.hbm = hbm_bytes_per_chip
        self.dp = dp_world
        self.runner = runner
        self.cfg = config or AutotunerConfig()
        if self.cfg.metric not in AUTOTUNING_METRICS:
            raise ValueError(f"unknown autotuning metric {self.cfg.metric!r}")
        # latency minimizes; throughput/flops maximize (reference
        # autotuning_metric semantics)
        self._sign = -1.0 if self.cfg.metric == "latency" else 1.0
        self.records: List[TuningRecord] = []

    # -- feasibility ------------------------------------------------------
    def memory_feasible(self, stage: int, micro: int, remat: bool) -> bool:
        need = zero_memory_per_chip(self.mi.num_params, stage, self.dp) + activation_memory_per_chip(
            micro, self.mi.seq_len, self.mi.hidden_size, self.mi.num_layers, remat=remat
        )
        return need < self.hbm * 0.92  # leave runway for workspace/fragmentation

    def max_feasible_micro(self, stage: int, remat: bool) -> Optional[int]:
        feas = [m for m in self.cfg.micro_batch_sizes if self.memory_feasible(stage, m, remat)]
        return max(feas) if feas else None

    def _extended(self) -> bool:
        c = self.cfg
        return bool(c.remat_policies or c.flash_blocks or c.shapes)

    def _shape_feasible(self, shape, stage, micro, policy) -> bool:
        """Memory feasibility for a shape candidate: analytic param count +
        activation model with a policy-dependent saved factor (calibrated
        against the measured bench residencies, PERF.md)."""
        n_params = estimate_params(shape)
        # "everything" disables recompute entirely → the module's no-remat
        # factor (34), NOT the dots default — underestimating admits OOM
        # candidates that waste subprocess budget at the head of the ranking
        saved = {
            "nothing": 2.0,
            # calibrated against the bench config's measured residency
            # (h=2304 micro 6 remat=flash ≈ 15.2 GB total → ~1.4 GB of
            # activations → ~2.5 hidden-elements per token per layer; the
            # old 4.0 pruned the measured-best config as infeasible)
            "flash": 2.5,
            "flash_qkv": 3.5,
            "everything": 34.0,
        }.get(policy, 12.0)
        need = zero_memory_per_chip(n_params, stage, self.dp) + activation_memory_per_chip(
            micro,
            shape.get("max_seq_len", self.mi.seq_len),
            shape.get("hidden_size", self.mi.hidden_size),
            shape.get("n_layers", self.mi.num_layers),
            remat=True,
            saved_factor=saved,
        )
        # 0.97 runway, looser than the in-process 0.92: shape candidates run
        # as ISOLATED subprocesses where an OOM is a cheap data point, and
        # the measured-best bench config (h=2304 micro 6, ~15.2/16 GB) sits
        # exactly in the band the tighter cap pruned
        return need < self.hbm * 0.97

    # -- space enumeration -------------------------------------------------
    def _space(self) -> List[Dict[str, Any]]:
        if self._extended():
            return self._space_extended()
        exps = []
        for stage, remat in itertools.product(self.cfg.stages, self.cfg.remat):
            for micro in self.cfg.micro_batch_sizes:
                if self.memory_feasible(stage, micro, remat):
                    exps.append(
                        {"zero_stage": stage, "micro_batch": micro, "remat": remat}
                    )
        return exps

    def _space_extended(self) -> List[Dict[str, Any]]:
        """The round-4 space: stage x micro x remat-policy x flash-block x
        shape, memory-pruned then COST-MODEL-ORDERED (highest predicted
        throughput first — reference model_based_tuner ordering) so
        max_experiments budgets the promising region."""
        from deepspeed_tpu.autotuning.scheduler import predicted_score

        c = self.cfg
        policies = c.remat_policies or ("flash",)
        blocks = c.flash_blocks or (512,)
        shapes = c.shapes or ({},)
        precisions = c.matmul_precisions or ("default",)
        exps = []
        for shape, stage, policy, block, micro, prec in itertools.product(
            shapes, c.stages, policies, blocks, c.micro_batch_sizes, precisions
        ):
            if shape:
                feasible = self._shape_feasible(shape, stage, micro, policy)
            else:
                # no shape candidates: feasibility comes from ModelInfo (an
                # empty dict through estimate_params would model a ~50M toy
                # and disable the OOM prune entirely)
                feasible = self.memory_feasible(stage, micro, policy != "everything")
            if not feasible:
                continue
            exp = {
                "zero_stage": stage,
                "micro_batch": micro,
                "remat": policy != "everything",
                "remat_policy": policy,
                "flash_block": block,
            }
            if prec != "default":
                exp["matmul_precision"] = prec
            if shape:
                exp["shape"] = dict(shape)
            exps.append(exp)
        exps.sort(key=predicted_score, reverse=True)
        return exps

    def _run(self, exp: Dict[str, Any]) -> Optional[float]:
        try:
            val = self.runner(exp)
        except Exception as e:  # an OOM/compile failure is a data point
            logger.warning(f"autotuning experiment {exp} failed: {e}")
            val = None
        self.records.append(TuningRecord(config=dict(exp), metric_val=val))
        return val

    # -- search ------------------------------------------------------------
    def tune(self) -> Tuple[Optional[Dict[str, Any]], Optional[float]]:
        """Returns (best_config, best_metric). Fast mode: per stage, probe
        the largest feasible micro-batch then its neighbors, keep the stage
        while it improves (reference tune() stage walk :404); otherwise
        grid/random over the full feasible space."""
        if self._extended():
            # extended space: cost-model ordering by default (tuner_type
            # "gridsearch"/"cost_model" are equivalent here — the grid IS
            # ranked); "random" still honors the user's seeded shuffle
            space = self._space()
            if self.cfg.tuner_type == "random":
                import random

                random.Random(self.cfg.seed).shuffle(space)
            best, best_val, since_best = None, None, 0
            for exp in space[: self.cfg.max_experiments]:
                val = self._run(exp)
                if val is not None and (
                    best_val is None or self._sign * val > self._sign * best_val
                ):
                    best, best_val, since_best = exp, val, 0
                else:
                    since_best += 1
                    if self.cfg.fast and since_best >= 4 and best is not None:
                        break
            return best, best_val
        if self.cfg.fast:
            return self._tune_fast()
        space = self._space()
        if self.cfg.tuner_type == "random":
            import random

            rng = random.Random(self.cfg.seed)
            rng.shuffle(space)
        best, best_val = None, None
        for exp in space[: self.cfg.max_experiments]:
            val = self._run(exp)
            if val is not None and (best_val is None or self._sign * val > self._sign * best_val):
                best, best_val = exp, val
        return best, best_val

    def _tune_fast(self):
        best, best_val = None, None
        for stage in self.cfg.stages:
            for remat in self.cfg.remat:
                top = self.max_feasible_micro(stage, remat)
                if top is None:
                    continue
                feas = [m for m in self.cfg.micro_batch_sizes if m <= top]
                stage_best_val = None
                # largest first, then step down while it improves
                for micro in sorted(feas, reverse=True):
                    if len(self.records) >= self.cfg.max_experiments:
                        break
                    val = self._run({"zero_stage": stage, "micro_batch": micro, "remat": remat})
                    if val is None:
                        continue
                    if stage_best_val is not None and self._sign * val <= self._sign * stage_best_val:
                        break  # descending micro stopped helping
                    stage_best_val = val
                    if best_val is None or self._sign * val > self._sign * best_val:
                        best = {"zero_stage": stage, "micro_batch": micro, "remat": remat}
                        best_val = val
            # reference fast mode: once a lower stage beats a higher-capacity
            # one, higher stages only add comm — stop after first regression
            if best is not None and stage > best["zero_stage"]:
                break
        return best, best_val

    def best_experiment(self):
        done = [r for r in self.records if r.metric_val is not None]
        if not done:
            return None
        return max(done, key=lambda r: self._sign * r.metric_val)

    def summary(self) -> str:
        ext = self._extended()
        header = f"{'stage':>5} {'micro':>6} {'remat':>6}"
        if ext:
            header += f" {'policy':>24} {'block':>6} {'hidden':>7}"
        lines = [header + f" {'metric':>12}"]
        for r in self.records:
            c = r.config
            val = f"{r.metric_val:.2f}" if r.metric_val is not None else "FAIL"
            row = f"{c['zero_stage']:>5} {c['micro_batch']:>6} {str(c['remat']):>6}"
            if ext:
                row += (
                    f" {c.get('remat_policy', '-'):>24} {c.get('flash_block', '-'):>6}"
                    f" {c.get('shape', {}).get('hidden_size', '-'):>7}"
                )
            lines.append(row + f" {val:>12}")
        return "\n".join(lines)


def tune_serving(max_experiments: int = 8, metric: str = "gen_tok_s",
                 timeout_s: int = 900, space=None, platform=None):
    """Autotune the v2 serving engine's knobs against generated tok/s
    (reference ``autotuning_metric`` throughput mode, autotuner.py:42,
    applied to FastGen). Reuses the training tuner's subprocess scheduler —
    every candidate runs isolated so an OOM/compile crash is a data point,
    not a tuner death. Space: fused-round length x prompt-chunk grid x
    KV block geometry, seeded with the hand-picked bench config first
    (the tuner must FIND at least that).

    ``space`` replaces the default candidate list entirely (tests use tiny
    shapes). Returns (best_config, best_gen_tok_s, records)."""
    from deepspeed_tpu.autotuning.scheduler import SubprocessRunner

    default_space = [
        # hand-picked bench config first (PERF.md round-5 serving sweep)
        {"decode_steps": 64, "prompt_chunk": 512, "max_prompt_chunks": 2},
        {"decode_steps": 32, "prompt_chunk": 512, "max_prompt_chunks": 2},
        {"decode_steps": 64, "prompt_chunk": 256, "max_prompt_chunks": 4},
        {"decode_steps": 64, "prompt_chunk": 512, "max_prompt_chunks": 2,
         "token_budget": 2048},
        {"decode_steps": 64, "prompt_chunk": 512, "max_prompt_chunks": 2,
         "block_size": 256, "num_blocks": 256, "max_blocks_per_seq": 4},
        {"decode_steps": 128, "prompt_chunk": 512, "max_prompt_chunks": 2,
         "max_new": 128},
        # right-sized block table: the decode gather reads the WHOLE table,
        # so slots beyond the workload's max context are wasted HBM traffic
        {"decode_steps": 64, "prompt_chunk": 256, "max_prompt_chunks": 4,
         "max_blocks_per_seq": 5, "max_context": 640},
    ]
    if space is None:
        space = default_space
    runner = SubprocessRunner(metric=metric, timeout_s=timeout_s, platform=platform)
    best, best_val, records = None, None, []
    for exp in space[:max_experiments]:
        payload = dict(exp)
        payload["mode"] = "serving"
        val = runner(payload)
        records.append((dict(exp), val))
        if val is not None and (best_val is None or val > best_val):
            best, best_val = dict(exp), val
    return best, best_val, records
