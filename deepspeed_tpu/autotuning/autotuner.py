"""Autotuner: search ZeRO stage × micro-batch × remat for best throughput.

Analogue of the reference ``autotuning/autotuner.py:42`` (``Autotuner``) +
``tuner/{index_based_tuner,model_based_tuner}.py``: a memory model prunes
infeasible (stage, micro-batch) points, then experiments run through a
user-supplied runner (the reference launches each through the CLI launcher;
here the runner is a callable so the search also works in-process — on TPU a
failed compile reports OOM deterministically, which the runner maps to
``None``). The fast path mirrors the reference's stage-ordered search with
early termination.
"""

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from deepspeed_tpu.utils.logging import logger

AUTOTUNING_METRICS = ("throughput", "latency", "flops")


# ---------------------------------------------------------------------------
# ZeRO memory model (reference autotuner's get_instantiation_memory_required_*
# heuristics, expressed per chip)
# ---------------------------------------------------------------------------
def zero_memory_per_chip(
    n_params: int,
    stage: int,
    dp_world: int,
    param_bytes: int = 2,
    grad_bytes: int = 4,
    optim_bytes: int = 12,  # fp32 master + two moments
) -> int:
    """Model-state bytes per chip for a ZeRO stage (the standard 2+4+12
    accounting; sharded terms divide by the data-parallel world)."""
    dp = max(dp_world, 1)
    params = n_params * param_bytes / (dp if stage >= 3 else 1)
    grads = n_params * grad_bytes / (dp if stage >= 2 else 1)
    optim = n_params * optim_bytes / (dp if stage >= 1 else 1)
    return int(params + grads + optim)


def activation_memory_per_chip(
    micro_batch: int,
    seq_len: int,
    hidden: int,
    n_layers: int,
    bytes_per_el: int = 2,
    remat: bool = True,
    saved_factor: float = 12.0,
) -> int:
    """Rough activation bytes: ``saved_factor`` elements of width ``hidden``
    per token per layer survive the remat policy (measured ~12 for the
    dots-saveable policy; ~34 with remat off)."""
    factor = saved_factor if remat else 34.0
    return int(micro_batch * seq_len * hidden * n_layers * factor * bytes_per_el)


@dataclass
class ModelInfo:
    """Reference model_info (profile step, engine.py:2198): what the memory
    model needs to prune the space."""

    num_params: int
    hidden_size: int
    num_layers: int
    seq_len: int


@dataclass
class TuningRecord:
    config: Dict[str, Any]
    metric_val: Optional[float]  # None = failed / OOM


@dataclass
class AutotunerConfig:
    """The ``autotuning`` config section (reference autotuning/config.py)."""

    enabled: bool = False
    metric: str = "throughput"
    fast: bool = True
    max_experiments: int = 50
    tuner_type: str = "gridsearch"  # gridsearch | random
    micro_batch_sizes: Sequence[int] = (1, 2, 4, 8, 16, 32)
    stages: Sequence[int] = (0, 1, 2, 3)
    remat: Sequence[bool] = (True,)
    seed: int = 0


class Autotuner:
    def __init__(
        self,
        model_info: ModelInfo,
        hbm_bytes_per_chip: int,
        dp_world: int,
        runner: Callable[[Dict[str, Any]], Optional[float]],
        config: Optional[AutotunerConfig] = None,
    ):
        """runner(exp_config) -> metric value (higher better) or None on
        failure/OOM — the reference's scheduler+launcher round trip."""
        self.mi = model_info
        self.hbm = hbm_bytes_per_chip
        self.dp = dp_world
        self.runner = runner
        self.cfg = config or AutotunerConfig()
        if self.cfg.metric not in AUTOTUNING_METRICS:
            raise ValueError(f"unknown autotuning metric {self.cfg.metric!r}")
        # latency minimizes; throughput/flops maximize (reference
        # autotuning_metric semantics)
        self._sign = -1.0 if self.cfg.metric == "latency" else 1.0
        self.records: List[TuningRecord] = []

    # -- feasibility ------------------------------------------------------
    def memory_feasible(self, stage: int, micro: int, remat: bool) -> bool:
        need = zero_memory_per_chip(self.mi.num_params, stage, self.dp) + activation_memory_per_chip(
            micro, self.mi.seq_len, self.mi.hidden_size, self.mi.num_layers, remat=remat
        )
        return need < self.hbm * 0.92  # leave runway for workspace/fragmentation

    def max_feasible_micro(self, stage: int, remat: bool) -> Optional[int]:
        feas = [m for m in self.cfg.micro_batch_sizes if self.memory_feasible(stage, m, remat)]
        return max(feas) if feas else None

    # -- space enumeration -------------------------------------------------
    def _space(self) -> List[Dict[str, Any]]:
        exps = []
        for stage, remat in itertools.product(self.cfg.stages, self.cfg.remat):
            for micro in self.cfg.micro_batch_sizes:
                if self.memory_feasible(stage, micro, remat):
                    exps.append(
                        {"zero_stage": stage, "micro_batch": micro, "remat": remat}
                    )
        return exps

    def _run(self, exp: Dict[str, Any]) -> Optional[float]:
        try:
            val = self.runner(exp)
        except Exception as e:  # an OOM/compile failure is a data point
            logger.warning(f"autotuning experiment {exp} failed: {e}")
            val = None
        self.records.append(TuningRecord(config=dict(exp), metric_val=val))
        return val

    # -- search ------------------------------------------------------------
    def tune(self) -> Tuple[Optional[Dict[str, Any]], Optional[float]]:
        """Returns (best_config, best_metric). Fast mode: per stage, probe
        the largest feasible micro-batch then its neighbors, keep the stage
        while it improves (reference tune() stage walk :404); otherwise
        grid/random over the full feasible space."""
        if self.cfg.fast:
            return self._tune_fast()
        space = self._space()
        if self.cfg.tuner_type == "random":
            import random

            rng = random.Random(self.cfg.seed)
            rng.shuffle(space)
        best, best_val = None, None
        for exp in space[: self.cfg.max_experiments]:
            val = self._run(exp)
            if val is not None and (best_val is None or self._sign * val > self._sign * best_val):
                best, best_val = exp, val
        return best, best_val

    def _tune_fast(self):
        best, best_val = None, None
        for stage in self.cfg.stages:
            for remat in self.cfg.remat:
                top = self.max_feasible_micro(stage, remat)
                if top is None:
                    continue
                feas = [m for m in self.cfg.micro_batch_sizes if m <= top]
                stage_best_val = None
                # largest first, then step down while it improves
                for micro in sorted(feas, reverse=True):
                    if len(self.records) >= self.cfg.max_experiments:
                        break
                    val = self._run({"zero_stage": stage, "micro_batch": micro, "remat": remat})
                    if val is None:
                        continue
                    if stage_best_val is not None and self._sign * val <= self._sign * stage_best_val:
                        break  # descending micro stopped helping
                    stage_best_val = val
                    if best_val is None or self._sign * val > self._sign * best_val:
                        best = {"zero_stage": stage, "micro_batch": micro, "remat": remat}
                        best_val = val
            # reference fast mode: once a lower stage beats a higher-capacity
            # one, higher stages only add comm — stop after first regression
            if best is not None and stage > best["zero_stage"]:
                break
        return best, best_val

    def best_experiment(self):
        done = [r for r in self.records if r.metric_val is not None]
        if not done:
            return None
        return max(done, key=lambda r: self._sign * r.metric_val)

    def summary(self) -> str:
        lines = [f"{'stage':>5} {'micro':>6} {'remat':>6} {'metric':>12}"]
        for r in self.records:
            c = r.config
            val = f"{r.metric_val:.2f}" if r.metric_val is not None else "FAIL"
            lines.append(f"{c['zero_stage']:>5} {c['micro_batch']:>6} {str(c['remat']):>6} {val:>12}")
        return "\n".join(lines)
