"""Autotuning (reference deepspeed/autotuning/)."""

from deepspeed_tpu.autotuning.autotuner import (
    Autotuner,
    AutotunerConfig,
    ModelInfo,
    TuningRecord,
    activation_memory_per_chip,
    estimate_params,
    zero_memory_per_chip,
)
from deepspeed_tpu.autotuning.scheduler import SubprocessRunner, predicted_score

__all__ = [
    "Autotuner",
    "AutotunerConfig",
    "ModelInfo",
    "TuningRecord",
    "SubprocessRunner",
    "activation_memory_per_chip",
    "estimate_params",
    "predicted_score",
    "zero_memory_per_chip",
]
