"""Autotuning (reference deepspeed/autotuning/)."""

from deepspeed_tpu.autotuning.autotuner import (
    Autotuner,
    AutotunerConfig,
    ModelInfo,
    TuningRecord,
    activation_memory_per_chip,
    zero_memory_per_chip,
)

__all__ = [
    "Autotuner",
    "AutotunerConfig",
    "ModelInfo",
    "TuningRecord",
    "activation_memory_per_chip",
    "zero_memory_per_chip",
]
