"""Autotuning experiment subprocess (reference ``autotuning/scheduler.py``
experiment jobs: every candidate config runs as its OWN process via the
launcher, so an OOM/compile crash kills the experiment, not the tuner).

Usage (spawned by :mod:`deepspeed_tpu.autotuning.scheduler`):

    python -m deepspeed_tpu.autotuning.exp_runner '<json>'

The JSON carries {"shape": {TransformerConfig kwargs}, "zero_stage",
"micro_batch", "remat_policy", "flash_block", "seq", "steps", "warmup",
"platform"}. Prints ONE JSON result line to stdout:
{"ok": true, "tok_s": ..., "mfu_pct": ..., "loss": ...} — everything else
goes to stderr. Exit code 0 even on handled failure (the line carries
ok=false + the reason); hard crashes (OOM kill) surface as a nonzero exit
the scheduler maps to None.
"""

import json
import os
import sys
import time


def run(exp: dict) -> dict:
    # flash block must be in the env BEFORE the ops import chain
    if exp.get("flash_block"):
        os.environ["DSTPU_FLASH_BLOCK"] = str(exp["flash_block"])
    if exp.get("platform") == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""  # never dial a TPU tunnel

    import jax

    if exp.get("platform") == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import (
        TransformerConfig,
        flops_per_token,
        init_params,
        make_loss_fn,
    )

    shape = dict(exp.get("shape") or {})  # no-shape searches benchmark the default config
    shape["remat_policy"] = exp.get("remat_policy") or shape.get("remat_policy", "flash")
    if exp.get("matmul_precision"):
        shape["matmul_precision"] = exp["matmul_precision"]
    cfg = TransformerConfig(**shape)
    micro = int(exp.get("micro_batch", 1))
    seq = int(exp.get("seq", min(cfg.max_seq_len, 2048)))
    steps = int(exp.get("steps", 6))
    warmup = int(exp.get("warmup", 2))

    params = init_params(cfg, jax.random.key(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=make_loss_fn(cfg),
        model_parameters=params,
        config={
            "train_batch_size": micro,
            "bf16": {"enabled": jax.default_backend() == "tpu"},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": int(exp.get("zero_stage", 0))},
            "steps_per_print": 10**9,
        },
    )
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(micro, seq + 1)
    ).astype(np.int32)
    batch = {"input_ids": toks}
    for _ in range(warmup):
        float(engine.train_batch(batch=batch))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    loss = float(loss)  # device sync
    dt = (time.perf_counter() - t0) / steps
    tok_s = micro * seq / dt
    peak = 197e12 if jax.default_backend() == "tpu" else 1e12
    return {
        "ok": True,
        "tok_s": round(tok_s, 1),
        "s_per_step": round(dt, 4),
        "mfu_pct": round(tok_s * flops_per_token(cfg, seq) / peak * 100, 2),
        "loss": round(loss, 4),
    }


def main():
    exp = json.loads(sys.argv[1])
    try:
        out = run(exp)
    except Exception as e:  # handled failure: report, don't crash the tuner
        out = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
