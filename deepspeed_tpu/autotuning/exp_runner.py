"""Autotuning experiment subprocess (reference ``autotuning/scheduler.py``
experiment jobs: every candidate config runs as its OWN process via the
launcher, so an OOM/compile crash kills the experiment, not the tuner).

Usage (spawned by :mod:`deepspeed_tpu.autotuning.scheduler`):

    python -m deepspeed_tpu.autotuning.exp_runner '<json>'

The JSON carries {"shape": {TransformerConfig kwargs}, "zero_stage",
"micro_batch", "remat_policy", "flash_block", "seq", "steps", "warmup",
"platform"}. Prints ONE JSON result line to stdout:
{"ok": true, "tok_s": ..., "mfu_pct": ..., "loss": ...} — everything else
goes to stderr. Exit code 0 even on handled failure (the line carries
ok=false + the reason); hard crashes (OOM kill) surface as a nonzero exit
the scheduler maps to None.
"""

import json
import os
import sys
import time


def run_serving(exp: dict) -> dict:
    """Serving-throughput experiment (reference ``autotuning_metric``
    throughput mode, autotuning/autotuner.py:42, pointed at the v2 engine):
    measure generated tok/s of the FastGen-analogue workload (32 concurrent
    sequences, mixed prompt lengths, 64 new tokens) under the given
    scheduler/engine knobs."""
    if exp.get("platform") == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""

    import jax

    if exp.get("platform") == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import numpy as np

    from deepspeed_tpu.inference.config import RaggedInferenceEngineConfig
    from deepspeed_tpu.inference.v2.engine_v2 import InferenceEngineV2
    from deepspeed_tpu.models import TransformerConfig, init_params

    shape = dict(exp.get("shape") or {})
    if not shape:
        shape = dict(  # the bench 767M serving shape
            vocab_size=32000, hidden_size=2304, n_layers=10, n_heads=18,
            n_kv_heads=6, ffn_hidden_size=6912, max_seq_len=2048,
            dtype="bfloat16",
        )
    cfg = TransformerConfig(**shape)
    params = init_params(cfg, jax.random.key(0))
    rc = RaggedInferenceEngineConfig.from_dict({
        "dtype": cfg.dtype,
        "decode_steps": int(exp.get("decode_steps", 64)),
        "prompt_chunk": int(exp.get("prompt_chunk", 0)),
        "max_prompt_chunks": int(exp.get("max_prompt_chunks", 0)),
        "kv_cache": {
            "block_size": int(exp.get("block_size", 128)),
            "num_blocks": int(exp.get("num_blocks", 512)),
            "max_blocks_per_seq": int(exp.get("max_blocks_per_seq", 8)),
        },
        "state_manager": {
            "max_tracked_sequences": 64,
            "max_ragged_batch_size": int(exp.get("token_budget", 1024)),
            "max_ragged_sequence_count": int(exp.get("concurrency", 32)),
            "max_context": int(exp.get("max_context", 1024)),
        },
    })
    from deepspeed_tpu.inference.v2.engine_v2 import serving_benchmark

    eng = InferenceEngineV2(cfg, params, rc)
    best = serving_benchmark(
        eng,
        n_seq=int(exp.get("concurrency", 32)),
        max_new=int(exp.get("max_new", 64)),
        repeats=int(exp.get("repeats", 2)),
        prompt_min=int(exp.get("prompt_min", 64)),
        prompt_max=int(exp.get("prompt_max", 512)),
    )
    return {"ok": True, "gen_tok_s": round(best, 1)}


def run(exp: dict) -> dict:
    if exp.get("mode") == "serving":
        return run_serving(exp)
    # flash block must be in the env BEFORE the ops import chain
    if exp.get("flash_block"):
        os.environ["DSTPU_FLASH_BLOCK"] = str(exp["flash_block"])
    if exp.get("platform") == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["PALLAS_AXON_POOL_IPS"] = ""  # never dial a TPU tunnel

    import jax

    if exp.get("platform") == "cpu":
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.models import (
        TransformerConfig,
        flops_per_token,
        init_params,
        make_loss_fn,
    )

    shape = dict(exp.get("shape") or {})  # no-shape searches benchmark the default config
    shape["remat_policy"] = exp.get("remat_policy") or shape.get("remat_policy", "flash")
    if exp.get("matmul_precision"):
        shape["matmul_precision"] = exp["matmul_precision"]
    cfg = TransformerConfig(**shape)
    micro = int(exp.get("micro_batch", 1))
    seq = int(exp.get("seq", min(cfg.max_seq_len, 2048)))
    steps = int(exp.get("steps", 6))
    warmup = int(exp.get("warmup", 2))

    params = init_params(cfg, jax.random.key(0))
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=make_loss_fn(cfg),
        model_parameters=params,
        config={
            "train_batch_size": micro,
            "bf16": {"enabled": jax.default_backend() == "tpu"},
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "zero_optimization": {"stage": int(exp.get("zero_stage", 0))},
            "steps_per_print": 10**9,
        },
    )
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(micro, seq + 1)
    ).astype(np.int32)
    batch = {"input_ids": toks}
    for _ in range(warmup):
        float(engine.train_batch(batch=batch))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine.train_batch(batch=batch)
    loss = float(loss)  # device sync
    dt = (time.perf_counter() - t0) / steps
    tok_s = micro * seq / dt
    peak = 197e12 if jax.default_backend() == "tpu" else 1e12
    return {
        "ok": True,
        "tok_s": round(tok_s, 1),
        "s_per_step": round(dt, 4),
        "mfu_pct": round(tok_s * flops_per_token(cfg, seq) / peak * 100, 2),
        "loss": round(loss, 4),
    }


def main():
    exp = json.loads(sys.argv[1])
    try:
        out = run(exp)
    except Exception as e:  # handled failure: report, don't crash the tuner
        out = {"ok": False, "error": f"{type(e).__name__}: {e}"[:300]}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
