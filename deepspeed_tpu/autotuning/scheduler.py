"""Experiment scheduler + cost model for the autotuner.

Reference analogue: ``autotuning/scheduler.py`` (``ResourceManager`` runs
every candidate as a launcher job and harvests metrics from its output) +
``tuner/model_based_tuner.py``/``tuner/cost_model.py`` (a proxy model orders
candidates so the budget goes to promising ones first).

TPU adaptation: one chip ⇒ sequential subprocess jobs (isolation is the
point — an OOM kills the experiment process, never the tuner); the cost
model is an analytic MFU proxy built from the knobs' measured effects
(PERF.md sweeps) instead of an xgboost regressor over past runs.
"""

import json
import os
import subprocess
import sys
from dataclasses import dataclass
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger


def predicted_score(exp: Dict[str, Any]) -> float:
    """Analytic throughput proxy ordering candidates (higher = try earlier).

    Encodes the measured shape of the knobs' effects (PERF.md rounds 2-4):
    micro-batch gains saturate fast (and overfull batches spill); wider
    hidden runs closer to MXU peak; "flash" then "nothing" remat beat
    heavier policies when the batch fits; flash block 1024 measured best at
    the bench shape; per-channel int8 rides the native 2x MXU rate. Only the
    ORDER matters — real numbers come from the subprocess runs.
    """
    # micro-batch gains saturate fast once fixed work is amortized (measured:
    # micro 6→8 at the bench shape is NEGATIVE — spills); fourth-root keeps
    # larger batches slightly ahead without letting them outrank width
    micro = exp.get("micro_batch", 1) ** 0.25
    shape = exp.get("shape", {})
    hidden = shape.get("hidden_size", 1024)
    policy_w = {
        # flash (save attention out+LSE) measured best at the bench shape in
        # rounds 3 AND 4 (59.5 vs 58.5 for nothing under int8)
        "flash": 1.10,
        "nothing": 1.07,
        "flash_qkv": 1.06,
        "dots_with_no_batch_dims": 1.0,
        "dots": 1.0,
        "everything": 0.9,
    }.get(exp.get("remat_policy", "flash"), 1.0)
    # block 1024 measured best at the bench shape (59.5 vs 57.4 at 512 under
    # int8 — PERF.md round 4)
    block_w = {256: 0.95, 512: 0.98, 1024: 1.0}.get(exp.get("flash_block", 512), 0.93)
    # MXU sweet spot: log-ish growth in width, saturating past ~2048
    width_w = min(hidden, 2560) / 2560.0
    stage_w = 1.0 - 0.01 * exp.get("zero_stage", 0)  # stages add comm/plumbing
    # per-channel int8 rides the MXU's native 2x int8 rate (measured +4.3pp
    # MFU at the bench shape, PERF.md round 4); fp8 measured a loss on v5e
    prec_w = {"int8": 1.08, "int8_tensor": 1.05, "fp8": 0.9}.get(
        exp.get("matmul_precision", "default"), 1.0
    )
    return micro * policy_w * block_w * (0.5 + 0.5 * width_w) * stage_w * prec_w


@dataclass
class SubprocessRunner:
    """runner(exp) -> metric (tok/s or MFU) | None, via an isolated python
    subprocess per experiment (reference launcher job round trip)."""

    metric: str = "mfu_pct"  # or tok_s / s_per_step
    timeout_s: int = 900
    platform: Optional[str] = None  # None = inherit; "cpu" forces CPU
    steps: int = 6
    warmup: int = 2
    verbose: bool = True

    def __call__(self, exp: Dict[str, Any]) -> Optional[float]:
        payload = dict(exp)
        payload.setdefault("steps", self.steps)
        payload.setdefault("warmup", self.warmup)
        if self.platform:
            payload["platform"] = self.platform
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # experiments choose their own device view
        try:
            proc = subprocess.run(
                [sys.executable, "-m", "deepspeed_tpu.autotuning.exp_runner", json.dumps(payload)],
                capture_output=True,
                text=True,
                timeout=self.timeout_s,
                env=env,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            )
        except subprocess.TimeoutExpired:
            logger.warning(f"autotuning experiment timed out: {exp}")
            return None
        line = None
        for ln in (proc.stdout or "").splitlines():
            ln = ln.strip()
            if ln.startswith("{"):
                line = ln
        if proc.returncode != 0 or line is None:
            tail = (proc.stderr or "")[-400:]
            logger.warning(f"autotuning experiment crashed (rc={proc.returncode}): {tail}")
            return None
        out = json.loads(line)
        if not out.get("ok"):
            logger.warning(f"autotuning experiment failed: {out.get('error')}")
            return None
        if self.verbose:
            logger.info(f"experiment {exp} -> {out}")
        val = out.get(self.metric)
        return float(val) if val is not None else None
