"""Continuous-batching serving driver.

The long-lived loop MII/FastGen runs around the engine, rebuilt for the v2
TPU engine: a background thread pumps ``engine.step_tokens()`` /
``engine.decode_round()`` while callers submit ``Request``s from any
thread and stream tokens out.

Responsibilities (and how each maps to the loop):

  * **Admission control** — a bounded queue plus KV-aware gating: a prompt
    is only handed to the scheduler when its full token budget
    (prompt + max_new_tokens) fits in ``BlockedAllocator.free_blocks``
    under a configurable occupancy headroom, and the tracked-sequence cap
    has room. Requests that could NEVER fit (max_context / per-seq block
    cap) are rejected at submit.
  * **Timeouts** — per-request deadlines checked every loop pass (queued
    requests time out in the queue too).
  * **Error isolation** — a failing request (stop_fn raising, bad state)
    is finished and its KV blocks freed without killing the loop; an
    engine-level step failure fails the in-flight set but the driver keeps
    serving new requests.
  * **Graceful drain/shutdown** — ``drain()`` stops admissions and runs the
    accepted set to completion; ``shutdown()`` additionally stops the loop.

The driver needs only a small engine protocol — ``scheduler`` (the
``RaggedScheduler`` API), ``state_manager`` (``free_blocks``), and
``step_tokens()`` returning ``{uid: next-token int}`` — so tests drive it
with a compute-free fake over the REAL scheduler/allocator stack.

Since the disaggregated-serving refactor the engine-facing half of the
loop (admission accounting, stepping, spec rounds, capped reaping) lives
in ``serving.cluster.core.EngineCore``; this driver is the degenerate
one-engine (1-prefill=1-decode colocated) owner of a single core, and
``serving.cluster.router.Router`` is the many-engine owner.
"""

import threading
import time
from collections import deque
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.observability.events import get_event_log
from deepspeed_tpu.observability.tracing import (
    begin_request_trace,
    finish_request_trace,
    get_tracer,
    mark_admitted,
    mark_first_token,
)
from deepspeed_tpu.serving.metrics import ServingMetrics
from deepspeed_tpu.serving.request import Request, RequestState, SamplingParams
from deepspeed_tpu.serving.streaming import TokenStream
from deepspeed_tpu.utils.logging import logger


class RequestRejected(Exception):
    """Submit refused (queue full, draining, shed, or the prompt can never
    fit). ``retry_after_s`` — set for backpressure rejections — is the
    server's ``Retry-After`` header, derived from the current queue drain
    rate (how long until the queue has likely made room)."""

    def __init__(self, reason: str, message: str = "",
                 retry_after_s: Optional[float] = None):
        super().__init__(message or reason)
        self.reason = reason
        self.retry_after_s = retry_after_s


class ServingDriver:
    def __init__(
        self,
        engine,
        eos_token_id: Optional[int] = None,
        max_queue: int = 128,
        kv_headroom: float = 0.0,
        default_timeout_s: Optional[float] = None,
        decode_steps: int = 1,
        poll_interval_s: float = 0.02,
        monitor=None,
        spec_k: Optional[int] = None,
        spec_ngram: int = 3,
        proposer=None,
    ):
        self.engine = engine
        self.eos_token_id = eos_token_id
        self.max_queue = int(max_queue)
        self.kv_headroom = float(kv_headroom)
        self.default_timeout_s = default_timeout_s
        self.decode_steps = int(decode_steps)
        self.poll_interval_s = float(poll_interval_s)
        self.monitor = monitor
        self.metrics = ServingMetrics()
        # the engine-facing half of the loop (admission accounting,
        # stepping, spec rounds, capped reaping) — spec_k=None inherits
        # the engine config's spec_k; 0 disables; the proposer is
        # injectable (a small-model drafter satisfies the same protocol)
        from deepspeed_tpu.serving.cluster.core import EngineCore

        self.core = EngineCore(
            engine,
            name="replica0",
            role="both",
            decode_steps=self.decode_steps,
            kv_headroom=self.kv_headroom,
            spec_k=spec_k,
            spec_ngram=spec_ngram,
            proposer=proposer,
            metrics=self.metrics,
        )
        self.spec_k = self.core.spec_k
        self._spec_ctl = self.core.spec_ctl
        self.proposer = self.core.proposer

        self._cond = threading.Condition()
        self._queue: deque = deque()  # Requests awaiting admission
        self._active = self.core.requests  # uid -> Request in the scheduler
        self._cancel_uids: set = set()
        self._next_uid = 0
        self._draining = False
        self._stopping = False
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None
        self._kv_total = self.core.kv_total
        self.metrics.update_kv(self._free_blocks(), self._kv_total)
        # static pool byte accounting (int8 capacity multiplier etc.) —
        # getattr-guarded so minimal fake engines in tests stay minimal
        self._kv_info = self.core.kv_info
        if self._kv_info:
            self.metrics.update_kv_pool_info(self._kv_info)
        if hasattr(self.engine, "comm_wire_info"):
            self.metrics.update_comm_quant(self.engine.comm_wire_info())
        self.metrics.update_replica(
            self.core.name, self.core.replica_stats(), role=self.core.role
        )

    # -- engine accessors (guarded so fakes stay minimal) ----------------
    def _kv_cfg(self, name, default):
        return self.core._kv_cfg(name, default)

    def _sm_cfg(self, name, default):
        return self.core._sm_cfg(name, default)

    def _free_blocks(self) -> int:
        return self.core.free_blocks()

    def _prefix_cache(self):
        return self.core.prefix_cache()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "ServingDriver":
        if self._thread is not None:
            raise RuntimeError("driver already started")
        self._thread = threading.Thread(target=self._loop, name="serving-driver", daemon=True)
        self._thread.start()
        return self

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.shutdown(drain=exc_type is None)

    # -- public API ------------------------------------------------------
    def submit(
        self,
        prompt_tokens,
        params: Optional[SamplingParams] = None,
        timeout_s: Optional[float] = None,
        stop_fn=None,
    ) -> Request:
        """Accept a request into the admission queue and return it (its
        ``.stream`` is live immediately). Raises ``RequestRejected`` when the
        driver is draining/stopped, the queue is full, or the prompt can
        never be scheduled (max_context / per-sequence block cap)."""
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        params = params or SamplingParams()
        if len(prompt) == 0:
            self._reject("empty_prompt")
        total = len(prompt) + params.max_new_tokens
        max_ctx = self._sm_cfg("max_context", None)
        if max_ctx is not None and len(prompt) >= max_ctx:
            self._reject("max_context", f"prompt of {len(prompt)} tokens >= max_context={max_ctx}")
        check = getattr(self.engine.state_manager, "check_admissible", None)
        if check is not None:
            try:
                # the PROMPT must fit; generation may be cut short by the
                # block cap (reported as a length_cap finish)
                check(len(prompt))
            except ValueError as e:
                self._reject("inadmissible", str(e))
        timeout = timeout_s if timeout_s is not None else self.default_timeout_s
        with self._cond:
            if self._draining or self._stopping:
                self._reject("draining")
            if len(self._queue) >= self.max_queue:
                self._reject("queue_full", f"admission queue full ({self.max_queue})")
            req = Request(
                uid=self._next_uid,
                prompt_tokens=prompt,
                params=params,
                deadline=(time.monotonic() + timeout) if timeout else None,
                stop_fn=stop_fn,
            )
            self._next_uid += 1
            req.stream = TokenStream(req.uid)
            tracer = get_tracer()
            if tracer.enabled:
                begin_request_trace(tracer, req)
            self._queue.append(req)
            self._idle.clear()
            self.metrics.inc("requests_submitted_total")
            self.metrics.set_gauge("queue_depth", len(self._queue))
            self._cond.notify_all()
        return req

    def cancel(self, uid: int) -> bool:
        """Request cancellation; True if the uid was live. Queued requests
        cancel immediately; active ones are finished by the loop."""
        with self._cond:
            for req in list(self._queue):
                if req.uid == uid:
                    self._queue.remove(req)
                    self._terminate(req, RequestState.CANCELLED, "cancelled")
                    self.metrics.set_gauge("queue_depth", len(self._queue))
                    return True
            if uid in self._active:
                self._cancel_uids.add(uid)
                self._cond.notify_all()
                return True
        return False

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting new requests and run the accepted set (queued +
        active) to completion. Returns True once idle."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        return self._idle.wait(timeout)  # dstpu: noqa[guarded-read-unlocked] — Event is internally synchronized; _cond only coordinates the set/clear with the loop's idle accounting

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop the loop. ``drain=True`` completes accepted requests first;
        ``drain=False`` cancels everything in flight."""
        if drain:
            self.drain(timeout)
        with self._cond:
            self._stopping = True
            if not drain:
                for req in list(self._queue):
                    self._terminate(req, RequestState.CANCELLED, "shutdown")
                self._queue.clear()
                self._cancel_uids.update(self._active.keys())
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self._flush_monitor()

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    @property
    def num_active(self) -> int:
        with self._cond:
            return len(self._active)

    def health(self) -> Dict:
        with self._cond:
            snap = self.metrics.snapshot()
            replica = self.core.replica_stats()
            replica["role"] = self.core.role
            replica["health"] = self.core.health.snapshot()
            return {
                "status": "draining" if self._draining else "ok",
                "queue_depth": len(self._queue),
                "active_requests": len(self._active),
                "kv_free_blocks": self._free_blocks(),
                "kv_total_blocks": self._kv_total,
                "replicas": {self.core.name: replica},
                "kv_cache_dtype": self._kv_info.get("kv_cache_dtype", "bf16"),
                "kv_pool_bytes": self._kv_info.get("kv_pool_bytes", 0),
                "kv_capacity_multiplier": self._kv_info.get(
                    "kv_capacity_multiplier", 1.0
                ),
                "kv_host_tier": self._host_tier_health(),
                "spec": {
                    "enabled": self._spec_ctl is not None,
                    "k": self.spec_k,
                    "rounds": int(snap["spec_rounds_total"]),
                    "draft_tokens": int(snap["spec_draft_tokens_total"]),
                    "accepted_tokens": int(snap["spec_accepted_tokens_total"]),
                    "acceptance_rate": snap["spec_acceptance_rate"],
                },
                "events": get_event_log().stats(),
            }

    def _host_tier_health(self) -> Dict:
        tier = self.core.host_tier()
        if tier is None:
            return {"enabled": False}
        return {"enabled": True, **tier.stats()}

    # -- internals -------------------------------------------------------
    def _reject(self, reason: str, message: str = ""):
        self.metrics.inc("requests_rejected_total")
        raise RequestRejected(reason, message)

    def _terminate(self, req: Request, state: str, reason: str, error: Optional[str] = None):
        """Move a request to a terminal state (caller already detached it
        from queue/active and released scheduler state if needed)."""
        req.state = state
        req.finish_reason = reason
        req.error = error
        req.t_finish = time.monotonic()
        if req.stream is not None:
            req.stream.close(reason, error=error)
        req._done.set()
        if req.trace is not None:
            # traced path: histograms fold from the SPAN endpoints (same
            # numbers — the spans carry the request's own stamps), then
            # the tree is closed and retention policy runs
            self.metrics.observe_trace(req)
            finish_request_trace(req, reason=reason)
        else:
            self.metrics.observe_request(req)
        key = {
            RequestState.FINISHED: "requests_finished_total",
            RequestState.CANCELLED: "requests_cancelled_total",
            RequestState.TIMED_OUT: "requests_timed_out_total",
            RequestState.FAILED: "requests_failed_total",
        }.get(state)
        if key:
            self.metrics.inc(key)

    def _finish_active(self, req: Request, state: str, reason: str,
                       error: Optional[str] = None, scheduler_done: bool = False):
        """Terminal transition for an ACTIVE request: release its scheduler
        state (frees KV blocks + pending prompt chunks) and close out."""
        self.core.release(req.uid, scheduler_done=scheduler_done)
        with self._cond:  # cancel() adds uids under _cond from client threads
            self._cancel_uids.discard(req.uid)
        self._terminate(req, state, reason, error)

    # admission ---------------------------------------------------------
    def _blocks_needed(self, req: Request) -> int:
        return self.core.blocks_needed(req)

    def _admissible(self, req: Request) -> bool:
        return self.core.admissible(req)

    def _admit_locked(self) -> bool:
        admitted = False
        while self._queue:
            req = self._queue[0]
            if not self._admissible(req):
                self.metrics.inc("admission_blocked_total")
                break
            self._queue.popleft()
            try:
                self.core.admit(req)
            except Exception as e:
                # late inadmissibility (e.g. raced config change): isolate
                self._terminate(req, RequestState.REJECTED, "inadmissible", str(e))
                self.metrics.inc("requests_rejected_total")
                continue
            req.state = RequestState.PREFILL
            req.t_admitted = time.monotonic()
            if req.trace is not None:
                mark_admitted(req, core=self.core.name)
            self.metrics.inc("prefill_tokens_total", len(req.prompt_tokens))
            admitted = True
        self.metrics.set_gauge("queue_depth", len(self._queue))
        self.metrics.set_gauge("active_requests", len(self._active))
        return admitted

    # timeouts / cancels ------------------------------------------------
    def _next_deadline_locked(self) -> Optional[float]:
        deadlines = [r.deadline for r in self._queue if r.deadline is not None]
        deadlines += [r.deadline for r in self._active.values() if r.deadline is not None]
        return min(deadlines) if deadlines else None

    def _expire_locked(self):
        now = time.monotonic()
        for req in [r for r in self._queue if r.deadline is not None and now >= r.deadline]:
            self._queue.remove(req)
            self._terminate(req, RequestState.TIMED_OUT, "timeout")
        for req in [r for r in list(self._active.values())
                    if r.deadline is not None and now >= r.deadline]:
            self._finish_active(req, RequestState.TIMED_OUT, "timeout")
        for uid in list(self._cancel_uids):
            req = self._active.get(uid)
            if req is not None:
                self._finish_active(req, RequestState.CANCELLED, "cancelled")
            self._cancel_uids.discard(uid)

    # token delivery ----------------------------------------------------
    def _deliver(self, req: Request, token: int, feedback: bool = True) -> None:
        """One generated token for an active request: record, stream, stop.
        ``feedback=False`` for fused-round tokens — ``apply_decode_round``
        already advanced the scheduler, a second feedback would double-append.
        ``stop_fn`` exceptions propagate (caller isolates the request)."""
        now = time.monotonic()
        if req.t_first_token is None:
            req.t_first_token = now
            req.state = RequestState.DECODE
            if req.trace is not None:
                mark_first_token(req)
        req.generated.append(int(token))
        self.metrics.inc("decode_tokens_total")
        self.core.decode_tokens += 1
        req.stream.put(int(token))
        reason = req.should_stop(int(token), self.eos_token_id)
        if reason is not None:
            self._finish_active(req, RequestState.FINISHED, reason)
        elif feedback:
            self.engine.scheduler.feedback(req.uid, int(token))

    def _deliver_or_fail(self, req: Request, token: int, feedback: bool = True) -> bool:
        """Error isolation: a per-request failure finishes ONLY that request
        (blocks freed via scheduler.finish) and the loop keeps serving.
        Returns False when the request terminated."""
        try:
            self._deliver(req, token, feedback=feedback)
        except Exception as e:
            logger.warning(f"serving: request {req.uid} failed: {type(e).__name__}: {e}")
            self._finish_active(req, RequestState.FAILED, "error", error=f"{type(e).__name__}: {e}")
            return False
        return not req.is_terminal

    # engine stepping ---------------------------------------------------
    # The step body lives in EngineCore.step_once; the driver implements
    # the core's sink protocol (token delivery / engine failure / length
    # cap) over its single-engine request bookkeeping.
    def deliver(self, core, req: Request, token: int, feedback: bool = True) -> bool:
        return self._deliver_or_fail(req, token, feedback=feedback)

    def engine_failed(self, core, error: str):
        # engine-level failure: per-request state is unknowable, so the
        # in-flight set fails — but the driver survives for new requests
        for req in list(self._active.values()):
            self._finish_active(req, RequestState.FAILED, "engine_error", error=error)

    def finish_capped(self, core, req: Request):
        self._finish_active(req, RequestState.FINISHED, "length_cap",
                            scheduler_done=True)

    def _step_once(self) -> bool:
        """One engine step (or fused decode / speculative verify round).
        Returns True if any token landed / request advanced (progress)."""
        with self.core.step_lock:
            return self.core.step_once(self)

    def _flush_monitor(self):
        if self.monitor is not None:
            try:
                self.monitor.write_events(self.metrics.to_events())
            except Exception as e:
                logger.warning(f"serving: monitor write failed: {e}")

    # the loop ----------------------------------------------------------
    def _loop(self):
        stall_wait = False
        while True:
            with self._cond:
                while True:
                    if self._stopping and not self._active and not self._queue:
                        self._idle.set()
                        return
                    work = (
                        bool(self._cancel_uids)
                        or self.engine.scheduler.has_work()
                        or (self._queue and self._admissible(self._queue[0]))
                    )
                    now = time.monotonic()
                    deadline = self._next_deadline_locked()
                    if deadline is not None and now >= deadline:
                        break  # timeouts due
                    if work and not stall_wait:
                        break
                    if not self._active and not self._queue:
                        self._idle.set()
                        self._flush_monitor()
                    # sleep until: new submit/cancel (notify), the next
                    # deadline, or — when the scheduler is stalled on KV
                    # blocks — a short poll. NEVER a busy spin.
                    timeout = None
                    if deadline is not None:
                        timeout = max(0.0, deadline - now)
                    if stall_wait:
                        timeout = min(self.poll_interval_s, timeout) if timeout else self.poll_interval_s
                    self._cond.wait(timeout)
                    stall_wait = False
                self._idle.clear()
                self._expire_locked()
                self._admit_locked()
            stepped = False
            if self.engine.scheduler.has_work():
                stepped = self._step_once()
                with self._cond:
                    self._admit_locked()  # finished requests freed blocks
                    self.metrics.update_kv(self._free_blocks(), self._kv_total)
                    cache = self._prefix_cache()
                    if cache is not None:
                        self.metrics.update_prefix_cache(cache.stats())
                    tier = self.core.host_tier()
                    if tier is not None:
                        self.metrics.update_host_tier(tier.stats())
                    if hasattr(self.engine, "comm_wire_info"):
                        # wire counters accrue as step programs TRACE, so a
                        # per-step refresh catches late-compiled shapes
                        self.metrics.update_comm_quant(self.engine.comm_wire_info())
                    self.metrics.update_replica(
                        self.core.name, self.core.replica_stats(),
                        role=self.core.role,
                    )
                    self.metrics.set_gauge("active_requests", len(self._active))
                    if not self._active and not self._queue:
                        self._idle.set()
                        self._flush_monitor()
            # a zero-progress pass with work outstanding means the scheduler
            # is waiting on KV blocks (or the queue head is inadmissible):
            # back off onto the condition instead of spinning
            stall_wait = not stepped
