"""Stdlib-only HTTP front end for the serving driver.

Endpoints (the MII/FastGen RESTful surface, minus the gRPC layer):

  * ``POST /generate`` — body ``{"prompt": str | "tokens": [int], ...}``.
    With ``"stream": true`` the response is chunked (one piece per decode
    round: text when a tokenizer is loaded, else one token id per line);
    otherwise the full completion returns as one JSON object.
  * ``GET /health``  — driver liveness + queue/KV occupancy JSON.
  * ``GET /metrics`` — Prometheus text exposition (ServingMetrics).
  * ``GET /debug/trace`` — tracing index (enabled, active uids, retained
    trace summaries).  ``?uid=N`` returns one request's span tree as a
    Chrome-trace JSON document; ``?format=chrome`` dumps every retained
    trace plus the engine ring and control events (what ``dstpu trace
    dump`` fetches and Perfetto opens).
  * ``GET /debug/events`` — recent control-plane events, newest first.

No framework, no sockets beyond ``http.server``: the handler is a thin
adapter over ``ServingDriver.submit`` + ``TokenStream``, so everything
interesting is testable without binding a port (see ``parse_generate``)
and the server itself is one ``ThreadingHTTPServer`` away.
"""

import json
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from deepspeed_tpu.observability import (
    get_event_log,
    get_tracer,
    to_chrome_trace,
    trace_to_chrome,
)
from deepspeed_tpu.serving.driver import RequestRejected, ServingDriver
from deepspeed_tpu.serving.request import SamplingParams
from deepspeed_tpu.serving.streaming import IncrementalDetokenizer
from deepspeed_tpu.utils.logging import logger


def parse_generate(body: dict, tokenizer=None) -> Tuple[np.ndarray, SamplingParams, bool, Optional[float]]:
    """Validate a /generate JSON body → (prompt_tokens, params, stream,
    timeout_s). Raises ValueError with a client-facing message."""
    if not isinstance(body, dict):
        raise ValueError("request body must be a JSON object")
    if "tokens" in body:
        prompt = np.asarray(body["tokens"], np.int32).reshape(-1)
    elif "prompt" in body:
        if tokenizer is None:
            raise ValueError("server has no tokenizer: send \"tokens\" instead of \"prompt\"")
        prompt = tokenizer.encode(str(body["prompt"]))
    else:
        raise ValueError("body needs \"prompt\" (text) or \"tokens\" (ids)")
    if len(prompt) == 0:
        raise ValueError("empty prompt")
    spec = body.get("spec")
    if spec is not None and not isinstance(spec, dict):
        raise ValueError('"spec" must be an object, e.g. {"enabled": true, "k": 4}')
    try:
        params = SamplingParams(
            max_new_tokens=int(body.get("max_new_tokens", 64)),
            eos_token_id=body.get("eos_token_id"),
            ignore_eos=bool(body.get("ignore_eos", False)),
            stop_token_ids=tuple(body.get("stop_token_ids", ())),
            spec=spec,
            qos=str(body.get("qos", "standard")),
            tenant=str(body.get("tenant", "default")),
            trace_id=(str(body["trace_id"]) if body.get("trace_id") is not None else None),
        )
    except TypeError as e:  # unknown spec key → client error, not a 500
        raise ValueError(f"bad spec params: {e}")
    stream = bool(body.get("stream", False))
    timeout_s = body.get("timeout_s")
    if timeout_s is not None:
        timeout_s = float(timeout_s)
        if timeout_s <= 0:
            raise ValueError("timeout_s must be positive")
    return prompt, params, stream, timeout_s


def make_handler(driver: ServingDriver, tokenizer=None):
    """Build the request-handler class bound to one driver instance."""

    class ServingHandler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # route through our logger
            logger.debug("serving-http: " + fmt % args)

        # -- helpers ----------------------------------------------------
        def _json(self, code: int, obj: dict, headers: Optional[dict] = None):
            payload = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            for name, value in (headers or {}).items():
                self.send_header(name, str(value))
            self.end_headers()
            self.wfile.write(payload)

        def _chunk(self, data: bytes):
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")

        def _end_chunks(self):
            self.wfile.write(b"0\r\n\r\n")

        # -- endpoints ---------------------------------------------------
        def do_GET(self):
            url = urllib.parse.urlsplit(self.path)
            if url.path == "/health":
                self._json(200, driver.health())
            elif url.path == "/metrics":
                text = driver.metrics.prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            elif url.path == "/debug/trace":
                self._debug_trace(urllib.parse.parse_qs(url.query))
            elif url.path == "/debug/events":
                log = get_event_log()
                self._json(200, {**log.stats(), "events": log.recent()})
            else:
                self._json(404, {"error": f"no such path {self.path}"})

        def _debug_trace(self, query: dict):
            tracer = get_tracer()
            uid_q = query.get("uid", [None])[0]
            if uid_q is not None:
                try:
                    uid = int(uid_q)
                except ValueError:
                    self._json(400, {"error": f"bad uid {uid_q!r}"})
                    return
                trace = tracer.trace(uid)
                if trace is None:
                    self._json(404, {"error": f"no trace for uid {uid}"})
                    return
                self._json(200, trace_to_chrome(trace))
            elif query.get("format", [None])[0] == "chrome":
                self._json(200, to_chrome_trace(tracer=tracer, event_log=get_event_log()))
            else:
                active = tracer.active_keys() if tracer.enabled else []
                self._json(200, {
                    "enabled": tracer.enabled,
                    "stats": tracer.stats(),
                    "active": active,
                    "completed": tracer.recent(),
                })

        def do_POST(self):
            if self.path != "/generate":
                self._json(404, {"error": f"no such path {self.path}"})
                return
            tracer = get_tracer()
            t_parse0 = tracer.now() if tracer.enabled else 0.0
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                prompt, params, stream, timeout_s = parse_generate(body, tokenizer)
            except (ValueError, json.JSONDecodeError) as e:
                self._json(400, {"error": str(e)})
                return
            t_parse1 = tracer.now() if tracer.enabled else 0.0
            try:
                req = driver.submit(prompt, params=params, timeout_s=timeout_s)
            except RequestRejected as e:
                code = 503 if e.reason in ("queue_full", "draining", "shed") else 400
                out = {"error": str(e), "reason": e.reason}
                headers = {}
                if code == 503 and e.retry_after_s is not None:
                    # RFC 7231 delay-seconds (integral, at least 1)
                    retry = max(1, int(round(e.retry_after_s)))
                    out["retry_after_s"] = retry
                    headers["Retry-After"] = retry
                self._json(code, out, headers=headers)
                return
            if req.trace is not None:
                # parse happened just before submit rooted the tree, so
                # this slice sits a hair left of the root in the timeline
                tracer.complete("server.parse", t_parse0, t_parse1,
                                key=req.uid, parent=req.trace.root,
                                args={"bytes": length})
            if stream:
                self._stream_response(req)
            else:
                req.wait()
                out = {
                    "uid": req.uid,
                    "finish_reason": req.finish_reason,
                    "tokens": [int(t) for t in req.generated],
                }
                if tokenizer is not None:
                    out["text"] = tokenizer.decode(req.generated)
                if req.error:
                    out["error"] = req.error
                self._json(200, out)

        def _stream_response(self, req):
            self.send_response(200)
            ctype = "text/plain; charset=utf-8" if tokenizer is not None else "application/jsonl"
            self.send_header("Content-Type", ctype)
            self.send_header("Transfer-Encoding", "chunked")
            self.send_header("X-Request-Uid", str(req.uid))
            self.end_headers()
            detok = IncrementalDetokenizer(tokenizer) if tokenizer is not None else None
            try:
                for tok in req.stream:
                    if detok is not None:
                        piece = detok.push(tok)
                        if piece:
                            self._chunk(piece.encode())
                    else:
                        self._chunk(json.dumps({"token": int(tok)}).encode() + b"\n")
                if detok is not None:
                    tail = detok.flush()
                    if tail:
                        self._chunk(tail.encode())
                self._end_chunks()
            except (BrokenPipeError, ConnectionResetError):
                driver.cancel(req.uid)  # client went away: free the KV blocks

    return ServingHandler


class ServingHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True


def start_server(
    driver: ServingDriver, host: str = "127.0.0.1", port: int = 8000, tokenizer=None
) -> ServingHTTPServer:
    """Bind and serve in a daemon thread; returns the server (its bound port
    is ``server.server_address[1]`` — pass port 0 for an ephemeral one)."""
    server = ServingHTTPServer((host, port), make_handler(driver, tokenizer))
    t = threading.Thread(target=server.serve_forever, name="serving-http", daemon=True)
    t.start()
    return server


def get_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
