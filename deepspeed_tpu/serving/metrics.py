"""Serving telemetry: latency histograms, throughput counters, KV gauges.

The metric set is the FastGen/MII serving dashboard: TTFT (time to first
token — prefill + queueing), TPOT (time per output token — decode cadence),
e2e latency, queue depth, KV-block occupancy, and prefill-vs-decode token
throughput. Two sinks share one source: ``prometheus_text()`` renders the
text exposition for the HTTP ``/metrics`` endpoint, and ``to_events()``
bridges the same numbers into the ``monitor.Monitor`` writer interface
(TensorBoard/W&B/CSV/Comet/Prometheus-textfile) so serving telemetry lands
next to training telemetry.
"""

import threading
from typing import Dict, List, Optional, Tuple

from deepspeed_tpu.monitor.monitor import (
    prometheus_metric_name,
    render_prometheus_text,
)

# Latency buckets in seconds (log-ish spacing from 1 ms to 2 min): one set
# serves TTFT, TPOT, and e2e — cross-metric comparability beats per-metric
# tightness for dashboards.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)

# Accepted-draft-tokens-per-verify-round buckets: small integers (a round
# accepts 0..K drafts; K is single digits in practice).
SPEC_ACCEPT_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)


def _safe_rate(value: float) -> float:
    """Clamp a ratio gauge to a finite number: 0.0 in place of NaN/inf.
    A hit rate before any query (0/0) must render as 0.0 in ``/metrics``
    and ``health()``, not poison the JSON/exposition with NaN."""
    v = float(value)
    if v != v or v == float("inf") or v == float("-inf"):
        return 0.0
    return v


class Histogram:
    """Prometheus-style cumulative histogram (counts per le-bucket + sum)."""

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self.total = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Bucket-resolution quantile (upper bound of the bucket holding the
        q-th observation) — good enough for bench reporting."""
        if not self.count:
            return 0.0
        target = q * self.count
        seen = 0
        for i, b in enumerate(self.buckets):
            seen += self.counts[i]
            if seen >= target:
                return b
        # target lands in the +Inf bucket: clamp to the largest finite
        # edge (mirrors _safe_rate) so bench JSON and /metrics-derived
        # reports stay finite
        return self.buckets[-1] if self.buckets else 0.0

    def prom_samples(self, name: str) -> List[Tuple]:
        out = []
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self.counts[i]
            # bucket bounds are python floats, no device sync
            out.append((f"{name}_bucket", {"le": repr(float(b))}, cum, "histogram"))  # dstpu: noqa[host-sync-in-loop]
        out.append((f"{name}_bucket", {"le": "+Inf"}, self.count, "histogram"))
        out.append((f"{name}_sum", None, self.total, None))
        out.append((f"{name}_count", None, self.count, None))
        return out


class ServingMetrics:
    """Thread-safe registry the driver and server write into."""

    PREFIX = "dstpu_serving"

    def __init__(self, buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        self._lock = threading.Lock()
        self.ttft = Histogram(buckets)
        self.tpot = Histogram(buckets)
        self.e2e = Histogram(buckets)
        # accepted draft tokens per sequence per verify round (spec decode)
        self.spec_accepted = Histogram(SPEC_ACCEPT_BUCKETS)
        # per-handoff wall time (export dispatch -> import landed), all
        # transports folded into one histogram; the per-transport split
        # lives in the labeled _handoffs family
        self.handoff_seconds = Histogram(buckets)
        self.counters: Dict[str, float] = {
            "requests_submitted_total": 0,
            "requests_rejected_total": 0,
            "requests_finished_total": 0,
            "requests_cancelled_total": 0,
            "requests_timed_out_total": 0,
            "requests_failed_total": 0,
            "prefill_tokens_total": 0,
            "decode_tokens_total": 0,
            "engine_steps_total": 0,
            "admission_blocked_total": 0,
            # prefix cache (mirrors of PrefixCache's monotone counters)
            "prefix_queries_total": 0,
            "prefix_hits_total": 0,
            "prefix_hit_tokens_total": 0,
            "prefix_inserted_blocks_total": 0,
            "prefix_evictions_total": 0,
            # tiered KV host store (HostBlockStore.stats() rollup) + the
            # router's cross-replica prefix pulls
            "kv_host_tier_hits_total": 0,
            "kv_host_tier_misses_total": 0,
            "kv_host_tier_spills_total": 0,
            "kv_host_tier_readmits_total": 0,
            "kv_host_tier_evictions_total": 0,
            "prefix_peer_pulls_total": 0,
            "prefix_peer_pull_blocks_total": 0,
            # speculative decoding
            "spec_rounds_total": 0,
            "spec_draft_tokens_total": 0,
            "spec_accepted_tokens_total": 0,
            # elastic control plane: QoS preemption + shedding + scaling
            "requests_preempted_total": 0,
            "requests_resumed_total": 0,
            "requests_shed_total": 0,
            "scale_up_total": 0,
            "scale_down_total": 0,
            # fault tolerance: replica health transitions and request
            # recovery (checkpoint = KV export reused, replay = prompt +
            # generated resubmitted), plus bounded transfer retries
            "replica_failures_total": 0,
            "replica_quarantines_total": 0,
            "replica_probes_total": 0,
            "replica_probe_failures_total": 0,
            "requests_recovered_total": 0,
            "recovery_checkpoints_total": 0,
            "recovery_replays_total": 0,
            "handoff_retries_total": 0,
            "peer_pull_retries_total": 0,
            # handoffs abandoned after export (retries exhausted or the
            # request terminated mid-flight) — pairs with the inflight-
            # window gauge unwind in handoff_aborted()
            "kv_handoff_aborts_total": 0,
        }
        self.gauges: Dict[str, float] = {
            "queue_depth": 0,
            "active_requests": 0,
            "kv_free_blocks": 0,
            "kv_total_blocks": 0,
            "kv_blocks_in_use": 0,
            "kv_occupancy": 0.0,
            # KV-pool byte accounting (engine.kv_pool_info): payload dtype
            # as a 0/1 int8 flag, allocated HBM bytes, and the effective
            # block-capacity multiplier vs a bf16 pool at the same budget
            "kv_cache_int8": 0,
            "kv_pool_bytes": 0,
            "kv_capacity_multiplier": 1.0,
            # quantized-collectives flag (engine.comm_wire_info); per-wire
            # byte counters render as labeled comm_wire_* samples
            "comm_quant_int8": 0,
            "prefix_cached_blocks": 0,
            "prefix_cached_blocks_idle": 0,
            "prefix_hit_rate": 0.0,
            # host tier occupancy (bytes/blocks resident right now)
            "kv_host_tier_bytes": 0,
            "kv_host_tier_blocks": 0,
            "kv_host_tier_budget_bytes": 0,
            "kv_host_tier_hit_rate": 0.0,
            "spec_acceptance_rate": 0.0,
            "spec_mean_accepted_per_round": 0.0,
            # elastic control plane: live decode fleet size, parked warm
            # spares, and the degradation ladder's current rung (0..3)
            "decode_replicas": 0,
            "warm_spares": 0,
            "shed_level": 0,
            # KV handoff transport: in-flight export windows of the most
            # recent pipelined (device-transport) handoff — 0 for host /
            # in_process handoffs, which ship one monolithic payload
            "kv_handoff_inflight_windows": 0,
        }
        # per-wire collective byte accounting (comm.quantized.wire_stats
        # via engine.comm_wire_info): tag -> {sites, wire_bytes_int8,
        # wire_bytes_fp, reduction}; trace-time counts per compiled site
        self._comm_wires: Dict[str, Dict[str, float]] = {}
        # per-replica gauge snapshots (disaggregated serving): name ->
        # (role, {stat: value}); rendered as replica=/role=-labeled
        # dstpu_serving_replica_* samples. The unlabeled kv_*/queue/latency
        # gauges stay the router-level rollup.
        self._replicas: Dict[str, Tuple[str, Dict[str, float]]] = {}
        # per-(tenant, qos-tier) accounting: finished/preempted/shed
        # counters, live queue depth, and a TTFT sum/count pair; rendered
        # as tenant=/tier=-labeled dstpu_serving_tier_* samples so a burst
        # trace can prove WHO was shed and WHOSE latency was protected.
        self._tiers: Dict[Tuple[str, str], Dict[str, float]] = {}
        # per-transport KV handoff accounting (disagg prefill->decode
        # moves): transport -> {handoffs, bytes, chunks}; rendered as
        # transport=-labeled dstpu_serving_kv_handoff_* samples so an A/B
        # (host vs device wire) shows up as two label rows, not a reset
        self._handoffs: Dict[str, Dict[str, float]] = {}

    # -- writers ---------------------------------------------------------
    def inc(self, name: str, delta: float = 1) -> None:
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + delta

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def observe_request(self, req) -> None:
        """Fold a TERMINAL request's latencies in (whatever stamps exist)."""
        with self._lock:
            if req.ttft_s is not None:
                self.ttft.observe(req.ttft_s)
            if req.tpot_s is not None:
                self.tpot.observe(req.tpot_s)
            if req.e2e_s is not None:
                self.e2e.observe(req.e2e_s)

    def observe_trace(self, req) -> None:
        """Histogram bridge from SPAN endpoints, for traced requests.

        The trace helpers stamp phase boundaries with the request's own
        monotonic stamps, so this folds numbers numerically identical to
        ``observe_request`` (a unit test asserts it) — but when tracing
        is on the span tree is the source of truth, so the timeline view
        and the histogram view cannot drift apart.  Falls back to
        ``observe_request`` when the request carries no trace.
        """
        ctx = getattr(req, "trace", None)
        if ctx is None:
            self.observe_request(req)
            return
        t_submit = ctx.root.t0
        t_first = ctx.t_first
        t_finish = ctx.root.t1 if ctx.root.t1 is not None else req.t_finish
        with self._lock:
            if t_first is not None:
                self.ttft.observe(t_first - t_submit)
            if t_first is not None and t_finish is not None:
                n = len(req.generated) - 1
                if n >= 1:
                    self.tpot.observe((t_finish - t_first) / n)
            if t_finish is not None:
                self.e2e.observe(t_finish - t_submit)

    def update_kv(self, free_blocks: int, total_blocks: int) -> None:
        with self._lock:
            self.gauges["kv_free_blocks"] = free_blocks
            self.gauges["kv_total_blocks"] = total_blocks
            self.gauges["kv_blocks_in_use"] = max(0, total_blocks - free_blocks)
            if total_blocks:
                self.gauges["kv_occupancy"] = 1.0 - free_blocks / total_blocks

    def update_kv_pool_info(self, info: Dict[str, float]) -> None:
        """Mirror an ``engine.kv_pool_info()`` snapshot (static per engine,
        set once at driver start)."""
        with self._lock:
            self.gauges["kv_cache_int8"] = int(
                info.get("kv_cache_dtype") == "int8"
            )
            self.gauges["kv_pool_bytes"] = info.get("kv_pool_bytes", 0)
            self.gauges["kv_capacity_multiplier"] = info.get(
                "kv_capacity_multiplier", 1.0
            )

    def update_comm_quant(self, info: Dict) -> None:
        """Mirror an ``engine.comm_wire_info()`` snapshot: the comm_quant /
        comm_overlap modes as 0/1 gauges plus the per-wire trace-time
        counters (quantized vs replaced full-width bytes, the derived
        reduction ratio the A/B gate checks, and the tile-granular overlap
        factor each wire decomposed into)."""
        with self._lock:
            self.gauges["comm_quant_int8"] = int(info.get("comm_quant") == "int8")
            self.gauges["comm_overlap_tiled"] = int(
                info.get("comm_overlap") == "tiled"
            )
            self._comm_wires = {
                tag: dict(v) for tag, v in (info.get("wires") or {}).items()
            }

    def update_replica(
        self, name: str, stats: Dict[str, float], role: str = "both",
        remote: bool = False,
    ) -> None:
        """Per-replica gauge snapshot (disaggregated serving): KV blocks,
        resident requests, handoff/decode tallies for ONE engine, labeled
        ``replica=name`` / ``role=...`` / ``remote=...`` in the exposition
        (``remote="1"`` marks a replica served by a cross-process agent).
        Non-numeric entries are dropped (labels carry the strings)."""
        clean = {}
        for k, v in stats.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            clean[k] = v * 1.0
        with self._lock:
            self._replicas[name] = (str(role), bool(remote), clean)

    def replica_snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {name: dict(st)
                    for name, (_role, _remote, st) in self._replicas.items()}

    def _tier_cell(self, tenant: str, tier: str) -> Dict[str, float]:
        """Caller holds the lock."""
        key = (str(tenant), str(tier))
        cell = self._tiers.get(key)
        if cell is None:
            cell = self._tiers[key] = {
                "finished_total": 0.0,
                "preempted_total": 0.0,
                "shed_total": 0.0,
                "queue_depth": 0.0,
                "ttft_sum_s": 0.0,
                "ttft_count": 0.0,
            }
        return cell

    def observe_tier(self, tenant: str, tier: str, stat: str,
                     delta: float = 1.0) -> None:
        """Bump one per-(tenant, tier) counter (``finished_total``,
        ``preempted_total``, ``shed_total``) or fold a TTFT sample in
        (``stat="ttft_s"``, delta = the latency)."""
        with self._lock:
            cell = self._tier_cell(tenant, tier)
            if stat == "ttft_s":
                cell["ttft_sum_s"] += float(delta)  # dstpu: noqa[host-sync-in-loop] host wall-clock float, not a device scalar
                cell["ttft_count"] += 1.0
            else:
                cell[stat] = cell.get(stat, 0.0) + float(delta)  # dstpu: noqa[host-sync-in-loop] host counter delta, not a device scalar

    def set_tier_queue_depth(self, depths: Dict[Tuple[str, str], int]) -> None:
        """Replace the per-(tenant, tier) queue-depth gauges with a fresh
        census (cells absent from ``depths`` drop to 0 — a drained tier
        must not keep reporting its burst-time depth)."""
        with self._lock:
            for cell in self._tiers.values():
                cell["queue_depth"] = 0.0
            for (tenant, tier), depth in depths.items():
                self._tier_cell(tenant, tier)["queue_depth"] = float(depth)  # dstpu: noqa[host-sync-in-loop] host int census, not a device scalar

    def tier_snapshot(self) -> Dict[Tuple[str, str], Dict[str, float]]:
        with self._lock:
            return {key: dict(cell) for key, cell in self._tiers.items()}

    def update_prefix_cache(self, stats: Dict[str, float]) -> None:
        """Mirror a ``PrefixCache.stats()`` snapshot. The source counters
        are monotone, so assigning (not incrementing) keeps Prometheus
        counter semantics."""
        with self._lock:
            self.counters["prefix_queries_total"] = stats["queries"]
            self.counters["prefix_hits_total"] = stats["hits"]
            self.counters["prefix_hit_tokens_total"] = stats["hit_tokens"]
            self.counters["prefix_inserted_blocks_total"] = stats["inserted_blocks"]
            self.counters["prefix_evictions_total"] = stats["evictions"]
            self.gauges["prefix_cached_blocks"] = stats["cached_blocks"]
            self.gauges["prefix_cached_blocks_idle"] = stats["cached_blocks_idle"]
            # the source computes hits/queries; guard the 0/0 (and any
            # NaN that leaks through a zero-query snapshot) to 0.0
            self.gauges["prefix_hit_rate"] = _safe_rate(stats["hit_rate"])

    def update_host_tier(self, stats: Dict[str, float]) -> None:
        """Mirror a ``HostBlockStore.stats()`` snapshot (or a cross-replica
        sum of them, from the router rollup). Counters are monotone at the
        source, so assignment keeps Prometheus counter semantics."""
        with self._lock:
            self.gauges["kv_host_tier_bytes"] = stats.get("bytes", 0)
            self.gauges["kv_host_tier_blocks"] = stats.get("blocks", 0)
            self.gauges["kv_host_tier_budget_bytes"] = stats.get("budget_bytes", 0)
            hits = stats.get("hits", 0)
            misses = stats.get("misses", 0)
            self.counters["kv_host_tier_hits_total"] = hits
            self.counters["kv_host_tier_misses_total"] = misses
            self.counters["kv_host_tier_spills_total"] = stats.get("spills", 0)
            self.counters["kv_host_tier_readmits_total"] = stats.get("readmits", 0)
            self.counters["kv_host_tier_evictions_total"] = stats.get("evictions", 0)
            probes = hits + misses
            self.gauges["kv_host_tier_hit_rate"] = (
                _safe_rate(hits / probes) if probes else 0.0
            )

    def observe_spec_round(self, per_uid: Dict[int, Tuple[int, int]]) -> None:
        """Fold one verify round's (drafted, accepted) per sequence into the
        spec counters/histogram and refresh the derived gauges."""
        with self._lock:
            for drafted, accepted in per_uid.values():
                self.counters["spec_draft_tokens_total"] += drafted
                self.counters["spec_accepted_tokens_total"] += accepted
                self.spec_accepted.observe(float(accepted))  # dstpu: noqa[host-sync-in-loop] host int, not a device scalar
            self.counters["spec_rounds_total"] += 1
            drafted_total = self.counters["spec_draft_tokens_total"]
            if drafted_total:
                self.gauges["spec_acceptance_rate"] = (
                    self.counters["spec_accepted_tokens_total"] / drafted_total
                )
            self.gauges["spec_mean_accepted_per_round"] = self.spec_accepted.mean

    def observe_handoff(self, transport: str, nbytes: int = 0,
                        seconds: Optional[float] = None,
                        inflight_windows: int = 0) -> None:
        """Fold one completed KV handoff in: bytes moved over the chosen
        transport, end-to-end wall time (export dispatch -> import
        landed), and — for the pipelined device wire — how many chunked
        export windows were in flight."""
        with self._lock:
            cell = self._handoffs.setdefault(
                str(transport), {"handoffs": 0.0, "bytes": 0.0, "chunks": 0.0}
            )
            cell["handoffs"] += 1.0
            cell["bytes"] += float(nbytes)
            cell["chunks"] += float(inflight_windows)
            if seconds is not None:
                self.handoff_seconds.observe(float(seconds))
            self.gauges["kv_handoff_inflight_windows"] = float(inflight_windows)

    def handoff_aborted(self, transport: str) -> None:
        """Unwind one handoff that will never land (import retries
        exhausted, or the request died mid-flight). The inflight-window
        gauge MUST return to zero here: an aborted import unwound its
        pool blocks, so windows it claimed are no longer in flight — a
        nonzero residue after an abort is the credit leak the resilience
        suite asserts against."""
        with self._lock:
            self.counters["kv_handoff_aborts_total"] += 1
            cell = self._handoffs.setdefault(
                str(transport), {"handoffs": 0.0, "bytes": 0.0, "chunks": 0.0}
            )
            cell["aborts"] = cell.get("aborts", 0.0) + 1.0
            self.gauges["kv_handoff_inflight_windows"] = 0.0

    def handoff_snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {t: dict(cell) for t, cell in self._handoffs.items()}

    # -- readers ---------------------------------------------------------
    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self.counters)
            out.update(self.gauges)
            out["ttft_mean_s"] = self.ttft.mean
            out["tpot_mean_s"] = self.tpot.mean
            out["e2e_mean_s"] = self.e2e.mean
            for tag, w in self._comm_wires.items():
                out[f"comm_wire_{tag}_reduction"] = w.get("reduction", 0.0)
                out[f"comm_wire_{tag}_tiles"] = w.get("tiles", 1)
            for transport, cell in self._handoffs.items():
                for key, value in cell.items():
                    out[f"kv_handoff_{transport}_{key}"] = value
            out["kv_handoff_seconds_mean"] = self.handoff_seconds.mean
            for name, (_role, _remote, st) in self._replicas.items():
                for key, value in st.items():
                    out[f"replica_{name}_{key}"] = value
            for (tenant, tier), cell in self._tiers.items():
                for key, value in cell.items():
                    out[f"tier_{tenant}_{tier}_{key}"] = value
            return out

    def prometheus_text(self) -> str:
        p = self.PREFIX
        with self._lock:
            samples = []
            for name in sorted(self.counters):
                samples.append((f"{p}_{name}", None, self.counters[name], "counter"))
            for name in sorted(self.gauges):
                samples.append((f"{p}_{name}", None, self.gauges[name], "gauge"))
            for tag in sorted(self._comm_wires):
                w = self._comm_wires[tag]
                lbl = {"wire": tag}
                samples.append((f"{p}_comm_wire_sites", lbl, w.get("sites", 0), "gauge"))
                samples.append((f"{p}_comm_wire_bytes_quant", lbl, w.get("wire_bytes_int8", 0), "gauge"))
                samples.append((f"{p}_comm_wire_bytes_fp", lbl, w.get("wire_bytes_fp", 0), "gauge"))
                samples.append((f"{p}_comm_wire_reduction", lbl, w.get("reduction", 0.0), "gauge"))
                samples.append((f"{p}_comm_wire_tiles", lbl, w.get("tiles", 1), "gauge"))
            for transport in sorted(self._handoffs):
                cell = self._handoffs[transport]
                lbl = {"transport": transport}
                samples.append((f"{p}_kv_handoff_total", lbl, cell["handoffs"], "counter"))
                samples.append((f"{p}_kv_handoff_bytes", lbl, cell["bytes"], "counter"))
                samples.append((f"{p}_kv_handoff_chunks_total", lbl, cell["chunks"], "counter"))
                samples.append((f"{p}_kv_handoff_aborts_total", lbl, cell.get("aborts", 0.0), "counter"))
            for name in sorted(self._replicas):
                role, remote, st = self._replicas[name]
                lbl = {"replica": name, "role": role,
                       "remote": "1" if remote else "0"}
                for key in sorted(st):
                    samples.append((f"{p}_replica_{key}", lbl, st[key], "gauge"))
            for tenant, tier in sorted(self._tiers):
                cell = self._tiers[(tenant, tier)]
                lbl = {"tenant": tenant, "tier": tier}
                for key in sorted(cell):
                    kind = "counter" if key.endswith("_total") else "gauge"
                    samples.append((f"{p}_tier_{key}", lbl, cell[key], kind))
            for hname, hist in (
                ("ttft_seconds", self.ttft),
                ("tpot_seconds", self.tpot),
                ("e2e_latency_seconds", self.e2e),
                ("spec_accepted_per_round", self.spec_accepted),
                ("kv_handoff_seconds", self.handoff_seconds),
            ):
                samples.extend(hist.prom_samples(f"{p}_{hname}"))
        return render_prometheus_text(samples)

    def to_events(self, step: Optional[int] = None) -> List[Tuple]:
        """The Monitor-writer bridge: (name, value, step) triples. ``step``
        defaults to the finished-request count (a monotone serving clock)."""
        with self._lock:
            if step is None:
                step = int(self.counters["requests_finished_total"])
            events = []
            for name, value in {**self.counters, **self.gauges}.items():
                events.append((f"Serving/{name}", value, step))
            for hname, hist in (
                ("ttft_s", self.ttft),
                ("tpot_s", self.tpot),
                ("e2e_s", self.e2e),
                ("spec_accepted_per_round", self.spec_accepted),
                ("kv_handoff_s", self.handoff_seconds),
            ):
                if hist.count:
                    events.append((f"Serving/{hname}_mean", hist.mean, step))
                    events.append((f"Serving/{hname}_p95", hist.quantile(0.95), step))
            for transport, cell in self._handoffs.items():
                for key, value in cell.items():
                    events.append(
                        (f"Serving/kv_handoff_{transport}_{key}", value, step))
            # labeled families, flattened the same way snapshot() does, so
            # replica and tenant/tier telemetry reaches the file-backed
            # writers (CSV/TensorBoard/...) and not just /metrics
            for name, (_role, _remote, st) in self._replicas.items():
                for key, value in st.items():
                    events.append((f"Serving/replica_{name}_{key}", value, step))
            for (tenant, tier), cell in self._tiers.items():
                for key, value in cell.items():
                    events.append(
                        (f"Serving/tier_{tenant}_{tier}_{key}", value, step))
            return events


# re-export for callers that want consistent naming with the monitor sink
__all__ = [
    "DEFAULT_BUCKETS",
    "SPEC_ACCEPT_BUCKETS",
    "Histogram",
    "ServingMetrics",
    "prometheus_metric_name",
    "_safe_rate",
]
