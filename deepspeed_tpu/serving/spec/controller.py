"""Adaptive draft length: back off to plain decode when acceptance dies.

A draft token that gets rejected still paid for its verify slot — embed,
QKV, attention, lm-head — so on a workload the proposer cannot predict,
speculation is pure overhead. The controller tracks a per-request
acceptance-rate EMA and:

  * serves the full draft length while the EMA stays healthy;
  * drops the request to ``k=0`` (plain decode riding the same verify
    program, or the fused decode round when NO request drafts) once the
    EMA falls below ``min_accept``;
  * re-probes with a full draft every ``probe_interval`` rounds, so a
    request that enters a predictable stretch (a quoted span, a
    repetition) wins speculation back.

Everything is deterministic host arithmetic — the controller changes only
how many drafts are ATTEMPTED, never what is accepted, so spec output
stays bit-identical to spec-off regardless of its decisions.
"""

from typing import Dict


class AdaptiveSpecController:
    def __init__(
        self,
        k: int,
        min_accept: float = 0.3,
        ema: float = 0.5,
        probe_interval: int = 8,
    ):
        if k < 1:
            raise ValueError(f"spec controller needs k >= 1, got {k}")
        if not 0.0 < ema <= 1.0:
            raise ValueError(f"ema weight must be in (0, 1], got {ema}")
        self.k = int(k)
        self.min_accept = float(min_accept)
        self.ema = float(ema)
        self.probe_interval = max(1, int(probe_interval))
        # per-uid: acceptance EMA (starts optimistic — the first rounds
        # carry full drafts) and a fallback cooldown counter (0 = drafting)
        self._rate: Dict[int, float] = {}
        self._cooldown: Dict[int, int] = {}

    def current_k(self, uid: int, k_cap: int = None) -> int:
        """Draft length to attempt for ``uid`` this round (0 = plain
        decode). Counts down the fallback cooldown; when it expires the
        request gets one full-length probe draft."""
        cap = self.k if k_cap is None else min(int(k_cap), self.k)
        if cap < 1:
            return 0
        cd = self._cooldown.get(uid, 0)
        if cd > 0:
            self._cooldown[uid] = cd - 1
            if cd > 1:
                return 0
            # probe round: neutral EMA so one good draft re-enables spec
            self._rate[uid] = self.min_accept
        return cap

    def update(self, uid: int, drafted: int, accepted: int) -> None:
        """Fold one verify round's outcome in; collapse starts the
        fallback cooldown."""
        if drafted < 1:
            return
        rate = accepted / drafted
        prev = self._rate.get(uid, 1.0)
        now = self.ema * rate + (1.0 - self.ema) * prev
        self._rate[uid] = now
        if now < self.min_accept:
            self._cooldown[uid] = self.probe_interval

    def acceptance_rate(self, uid: int) -> float:
        return self._rate.get(uid, 1.0)

    def is_fallback(self, uid: int) -> bool:
        return self._cooldown.get(uid, 0) > 0

    def forget(self, uid: int) -> None:
        """Drop a finished request's state (uids are reused by tests)."""
        self._rate.pop(uid, None)
        self._cooldown.pop(uid, None)
