"""Draft proposers: where speculative tokens come from.

The serving driver asks a proposer for up to ``k`` draft tokens per
request per round; the engine's verify step then scores pending + drafts
in one forward pass and accepts the matching prefix. A proposer is pure
host-side policy — it never touches the device — so a bad guess costs
only the wasted verify slots, never correctness (acceptance is exact
match against the engine's own sampled targets).

``NgramProposer`` is the model-free prompt-lookup drafter (PLD /
"assisted generation without a draft model"): find the longest recent
n-gram suffix of the history elsewhere in the history and propose what
followed it there. Strong exactly where serving workloads repeat —
extractive answers over a long prompt, code editing, retry-heavy chat —
and free everywhere else.
"""

from typing import List, Protocol, Sequence, runtime_checkable


@runtime_checkable
class DraftProposer(Protocol):
    """Protocol for draft sources (n-gram lookup today; a small-model
    drafter later — anything that can turn a token history into guesses)."""

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        """Up to ``k`` guesses for the tokens FOLLOWING ``history`` (which
        already includes the pending not-yet-verified token). May return
        fewer than ``k`` — including none — when it has no basis to guess."""
        ...


class NgramProposer:
    """Prompt-lookup drafting: match the last ``n``-gram of the history
    (``max_ngram`` down to ``min_ngram``) against earlier occurrences and
    propose the continuation of the MOST RECENT match. Longer n-grams are
    tried first — a longer matched context is a stronger prediction."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need max_ngram >= min_ngram >= 1, got "
                f"max_ngram={max_ngram} min_ngram={min_ngram}"
            )
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        hist = [int(t) for t in history]
        n_hist = len(hist)
        if k < 1 or n_hist < self.min_ngram + 1:
            return []
        for n in range(min(self.max_ngram, n_hist - 1), self.min_ngram - 1, -1):
            suffix = hist[n_hist - n:]
            # scan right-to-left: the most recent prior occurrence wins
            # (recency tracks the current generation mode best)
            for start in range(n_hist - n - 1, -1, -1):
                if hist[start:start + n] == suffix:
                    cont = hist[start + n : start + n + k]
                    if cont:
                        return cont
        return []
