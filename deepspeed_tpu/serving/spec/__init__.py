"""Speculative decoding for the serving stack: draft-and-verify.

Decode is memory-bandwidth-bound (BENCH: batched decode at ~29% of the
HBM roofline), so one forward pass has idle FLOPs to score several tokens
for the price of one HBM sweep. The subsystem splits the classic
draft/verify loop across three owners:

  * ``proposer``   — where draft tokens come from. ``NgramProposer`` is the
                     model-free prompt-lookup drafter (matches the request's
                     own token history; zero extra weights); the
                     ``DraftProposer`` protocol leaves room for a
                     small-model drafter later.
  * ``controller`` — adaptive draft length per request: an acceptance-rate
                     EMA backs a request off to plain decode when drafting
                     stops paying, and periodically re-probes.
  * engine side    — ``InferenceEngineV2.spec_round()`` runs the jitted
                     K+1-token verify step and rolls the per-row KV write
                     cursor back past rejected drafts (``ragged_manager.
                     truncate_blocks``).

Acceptance is exact-match against the engine's content-addressed sampler
(``sampling.row_keys``): the verify step samples the target token for every
draft position with the same (seed, uid, position) key plain decode would
use, and accepts a draft token only when it EQUALS that target — so spec-on
output is bit-identical to spec-off for greedy and sampled streams alike.
"""

from dataclasses import dataclass

from deepspeed_tpu.serving.spec.controller import AdaptiveSpecController
from deepspeed_tpu.serving.spec.proposer import DraftProposer, NgramProposer


@dataclass
class SpecParams:
    """Per-request speculative-decoding knobs (``SamplingParams.spec``).

    ``k`` is clamped to the driver's engine-level ``spec_k`` (the compiled
    verify shape); ``enabled=False`` opts a request out entirely."""

    enabled: bool = True
    k: int = 4

    def __post_init__(self):
        self.k = int(self.k)
        if self.k < 0:
            raise ValueError(f"spec k must be >= 0, got {self.k}")


__all__ = [
    "AdaptiveSpecController",
    "DraftProposer",
    "NgramProposer",
    "SpecParams",
]
