"""Per-request token streaming + incremental detokenization.

The driver appends tokens to a ``TokenStream`` as decode rounds complete;
any number of consumer threads (HTTP handlers, bench clients) iterate it
concurrently with generation. ``IncrementalDetokenizer`` turns the id
stream into text pieces without re-emitting earlier text and without
splitting multi-token UTF-8 sequences (the classic streaming-detok bug:
byte-level BPE tokens are not codepoint-aligned, so a naive per-token
decode emits U+FFFD replacement chars mid-character).
"""

import threading
from collections import deque
from typing import Iterator, Optional


class StreamClosed(Exception):
    """Raised by ``get()`` when the stream ended and no tokens remain."""


class TokenStream:
    """Thread-safe token queue with an end-of-stream marker.

    Producer: ``put(token)`` then ``close(reason)``. Consumer: iterate, or
    ``get(timeout)``. Iteration ends when the stream is closed and drained;
    ``finish_reason`` is readable afterwards.
    """

    def __init__(self, uid: int):
        self.uid = uid
        self._q = deque()
        self._cond = threading.Condition()
        self._closed = False
        self.finish_reason: Optional[str] = None
        self.error: Optional[str] = None

    # -- producer (driver thread) ---------------------------------------
    def put(self, token: int) -> None:
        with self._cond:
            if self._closed:
                return  # late tokens after close (e.g. cancel) are dropped
            self._q.append(int(token))
            self._cond.notify_all()

    def put_many(self, tokens) -> None:
        """Append a burst (e.g. one speculative verify round's accepted run)
        under ONE lock acquisition/notify — consumers wake once per burst,
        not once per token."""
        with self._cond:
            if self._closed:
                return
            self._q.extend(int(t) for t in tokens)
            self._cond.notify_all()

    def close(self, finish_reason: str, error: Optional[str] = None) -> None:
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self.finish_reason = finish_reason
            self.error = error
            self._cond.notify_all()

    # -- consumer --------------------------------------------------------
    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed and not self._q

    def get(self, timeout: Optional[float] = None) -> int:
        """Next token; raises ``StreamClosed`` at end-of-stream, ``TimeoutError``
        if ``timeout`` elapses with the stream still open."""
        with self._cond:
            while not self._q:
                if self._closed:
                    raise StreamClosed(self.finish_reason)
                if not self._cond.wait(timeout):
                    raise TimeoutError(f"no token within {timeout}s (uid={self.uid})")
            return self._q.popleft()

    def __iter__(self) -> Iterator[int]:
        while True:
            try:
                yield self.get()
            except StreamClosed:
                return


class IncrementalDetokenizer:
    """Turn a token-id stream into text pieces, emitting only complete
    codepoints: decode the full generated prefix each push and emit the
    STABLE suffix past what was already emitted — everything up to (but not
    including) any trailing U+FFFD run, which marks a partial UTF-8
    sequence awaiting its next token. Emitting the stable prefix rather
    than withholding the whole piece matters for multi-token bursts
    (speculative decoding delivers several tokens per round): one
    incomplete trailing codepoint must not hold back the completed text in
    front of it."""

    def __init__(self, tokenizer):
        self._tok = tokenizer
        self._ids = []
        self._emitted = 0  # chars already handed out

    def _emit_stable(self) -> str:
        text = self._tok.decode(self._ids)
        stable = len(text)
        while stable > self._emitted and text[stable - 1] == "�":
            stable -= 1  # mid-codepoint tail: wait for the completing token
        piece = text[self._emitted:stable]
        self._emitted = stable
        return piece

    def push(self, token_id: int) -> str:
        self._ids.append(int(token_id))
        return self._emit_stable()

    def push_many(self, token_ids) -> str:
        """Burst entry point: fold several tokens, ONE decode of the prefix
        (vs one per token via repeated push) — the streaming-side analogue
        of the engine's multi-token verify rounds."""
        self._ids.extend(int(t) for t in token_ids)
        return self._emit_stable()

    def flush(self) -> str:
        """Emit whatever remains (end of stream: a trailing U+FFFD is real)."""
        text = self._tok.decode(self._ids)
        piece = text[self._emitted:]
        self._emitted = len(text)
        return piece


def stream_text(stream: TokenStream, tokenizer) -> Iterator[str]:
    """Iterate a ``TokenStream`` as incremental text pieces."""
    detok = IncrementalDetokenizer(tokenizer)
    for tok in stream:
        piece = detok.push(tok)
        if piece:
            yield piece
    tail = detok.flush()
    if tail:
        yield tail
