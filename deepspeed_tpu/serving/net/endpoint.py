"""KVEndpoint: the per-engine listener serving staged KV payloads.

Each prefill engine that exports over the ``remote`` transport owns one
:class:`KVEndpoint` — a stdlib-socket listener thread plus one handler
thread per connection. The exporter stages a handoff's host-representation
payload (immutable numpy planes) under a transfer id; the importer dials
the endpoint, handshakes versions (HELLO), and FETCHes block-granular
chunk windows. The wire is credit-flow-controlled (:mod:`.flow`): the
FETCH carries an initial grant of ``credit_windows * chunk_blocks``
blocks and CREDIT frames replenish it as the importer's donated scatters
are dispatched, so a slow decoder backpressures the exporter instead of
the socket buffering a whole KV cache.

Staged payloads are immutable and survive a failed transfer: the
importer's bounded retry (``resilience/retry.py``) can re-FETCH the same
transfer id after a mid-window fault, and only an explicit DONE (or the
router calling :meth:`KVEndpoint.release` after the import lands /
finally aborts) drops the stage. That makes the wire edge idempotent,
which is what lets the chaos harness kill it at ``net.connect`` /
``net.send`` / ``net.recv`` without losing a request.
"""

import socket
import threading
import uuid
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from deepspeed_tpu.serving.resilience.faults import (
    InjectedFault,
    get_fault_injector,
)
from deepspeed_tpu.serving.net import wire
from deepspeed_tpu.serving.net.flow import CreditError, CreditWindow

__all__ = ["KVEndpoint", "fetch_chunks", "DEFAULT_IO_TIMEOUT_S"]

DEFAULT_IO_TIMEOUT_S = 30.0


class _Stage:
    """One staged transfer: immutable planes + bookkeeping."""

    __slots__ = ("tid", "uid", "planes", "n_blocks", "chunk_blocks", "nbytes")

    def __init__(self, tid, uid, planes, chunk_blocks):
        self.tid = tid
        self.uid = uid
        self.planes = planes
        # every plane is [n_layers, n_blocks, ...]; axis 1 is the block axis
        self.n_blocks = int(next(iter(planes.values())).shape[1])
        self.chunk_blocks = int(chunk_blocks)
        self.nbytes = int(sum(a.nbytes for a in planes.values()))


class KVEndpoint:
    """Listener thread serving staged KV payloads as chunk windows.

    >>> ep = KVEndpoint(name="p0"); ep.start()
    >>> tid = ep.stage(uid, payload, chunk_blocks=8)
    >>> ep.address      # ("127.0.0.1", <port>) — goes into the handoff
    >>> ep.release(tid) # after the import lands (DONE also releases)
    >>> ep.close()
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 name: Optional[str] = None, max_staged: int = 64,
                 io_timeout_s: float = DEFAULT_IO_TIMEOUT_S,
                 advertise_host: Optional[str] = None):
        self.name = name or "kv-endpoint"
        self._io_timeout_s = float(io_timeout_s)
        self._max_staged = int(max_staged)
        self._lock = threading.Lock()
        self._staged: Dict[str, _Stage] = {}
        self._closed = False
        self._threads = []
        self._stats = {
            "staged": 0, "released": 0, "served": 0, "frames_sent": 0,
            "wire_bytes_sent": 0, "credit_stalls": 0, "errors": 0,
            "max_inflight_windows": 0,
        }
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._bind_address = self._listener.getsockname()[:2]
        # multi-host discovery: the address handed to IMPORTERS (health
        # metadata, handoff descriptors) may differ from the bind address —
        # a pod-facing endpoint binds 0.0.0.0/127.0.0.1 but must advertise
        # a host other machines can dial
        self._advertise_host = advertise_host or self._bind_address[0]
        self._accept_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        """The ADVERTISED ``(host, port)`` — what goes into handoff
        descriptors and /health metadata for remote importers to dial."""
        return (self._advertise_host, int(self._bind_address[1]))

    @property
    def bind_address(self) -> Tuple[str, int]:
        """The local ``(host, port)`` the listener socket is bound to."""
        return (self._bind_address[0], int(self._bind_address[1]))

    def start(self) -> "KVEndpoint":
        if self._accept_thread is None:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, name=f"{self.name}-accept",
                daemon=True)
            self._accept_thread.start()
        return self

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._staged.clear()
        # Closing the listener fd does NOT wake a thread blocked in accept()
        # on Linux — dial it once so the accept loop observes _closed and
        # exits instead of eating the full join timeout below. Dial the
        # BIND address: the advertised host may only resolve off-box.
        try:
            with socket.create_connection(self.bind_address, timeout=0.5):
                pass
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        # snapshot under the lock (handler threads deregister themselves);
        # the joins themselves must run unlocked or they would deadlock
        # with a handler blocked on _lock
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2.0)

    # -- staging -------------------------------------------------------------
    def stage(self, uid: int, payload: Dict[str, np.ndarray],
              chunk_blocks: int) -> str:
        """Stage an exported payload; returns the transfer id the importer
        FETCHes by. The planes are kept as-is (already host numpy — the
        export made the copy) and served read-only."""
        if not payload:
            raise ValueError(f"stage({uid}): empty payload")
        tid = uuid.uuid4().hex
        stage = _Stage(tid, int(uid), dict(payload), chunk_blocks)
        with self._lock:
            if self._closed:
                raise RuntimeError(f"{self.name}: endpoint closed")
            if len(self._staged) >= self._max_staged:
                raise RuntimeError(
                    f"{self.name}: {len(self._staged)} transfers staged "
                    f">= max_staged {self._max_staged} — importer side is "
                    "not releasing (leak or overload)")
            self._staged[tid] = stage
            self._stats["staged"] += 1
        return tid

    def release(self, tid: str) -> bool:
        """Drop a staged transfer (import landed or finally aborted).
        Idempotent; returns whether the stage was present."""
        with self._lock:
            present = self._staged.pop(tid, None) is not None
            if present:
                self._stats["released"] += 1
            return present

    def staged_count(self) -> int:
        with self._lock:
            return len(self._staged)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats, staged_now=len(self._staged))

    def _bump(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._stats[key] += n

    # -- server side ---------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f"{self.name}-conn", daemon=True)
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)
            t.start()

    def _send(self, conn: socket.socket, frame: bytes) -> None:
        conn.sendall(frame)
        self._bump("frames_sent")
        self._bump("wire_bytes_sent", len(frame))

    def _serve_conn(self, conn: socket.socket) -> None:
        faults = get_fault_injector()
        try:
            conn.settimeout(self._io_timeout_s)
            read = lambda n: wire.recv_exact(conn, n)
            # handshake: both sides announce their version SPAN before any
            # data; skew inside the supported range downgrades, no overlap
            # (or foreign magic) raises out of the negotiation
            ftype, payload = wire.read_frame(read)
            if ftype != wire.F_HELLO:
                raise wire.WireError(
                    f"expected HELLO, got {wire.FRAME_NAMES.get(ftype, ftype)}")
            wire.negotiate_version(wire.decode_hello(payload))
            self._send(conn, wire.encode_hello())
            ftype, payload = wire.read_frame(read)
            if ftype != wire.F_FETCH:
                raise wire.WireError(
                    f"expected FETCH, got {wire.FRAME_NAMES.get(ftype, ftype)}")
            req = wire.decode_json(payload, wire.F_FETCH)
            tid = str(req.get("tid", ""))
            start_block = int(req.get("start_block", 0))
            credit_blocks = int(req.get("credit_blocks", 0))
            with self._lock:
                stage = self._staged.get(tid)
            if stage is None:
                self._bump("errors")
                self._send(conn, wire.encode_json(wire.F_ERROR, {
                    "error": f"unknown transfer id {tid!r} on {self.name} "
                             "(released, expired, or never staged)"}))
                return
            self._stream_chunks(conn, read, stage, start_block,
                                credit_blocks, faults)
        except (wire.WireError, OSError, ValueError, CreditError,
                InjectedFault):
            # importer crashed / protocol break / chaos kill: drop the
            # connection (an InjectedFault at net.send IS the simulated
            # exporter crash — the importer sees a dead wire). The stage
            # stays — the importer's bounded retry re-FETCHes it.
            self._bump("errors")
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _stream_chunks(self, conn, read, stage: _Stage, start_block: int,
                       credit_blocks: int, faults) -> None:
        if not (0 <= start_block <= stage.n_blocks):
            raise wire.WireError(
                f"FETCH start_block {start_block} outside [0, {stage.n_blocks}]")
        window = CreditWindow(credit_blocks)
        chunk = max(1, stage.chunk_blocks)
        done = threading.Event()

        def credit_pump():
            # drains CREDIT frames (and the final DONE) off the socket so
            # the send loop can block on the window, not on recv. A CREDIT
            # both ACKS the oldest in-flight window (settle) and re-opens
            # the send window (grant) — so `window.outstanding` is the true
            # number of chunk windows on the wire at any instant.
            try:
                while not done.is_set():
                    ftype, payload = wire.read_frame(read)
                    if ftype == wire.F_CREDIT:
                        blocks = int(wire.decode_json(
                            payload, wire.F_CREDIT)["blocks"])
                        window.settle(blocks)
                        window.grant(blocks)
                    elif ftype == wire.F_DONE:
                        # tail windows are acknowledged wholesale by DONE
                        window.reset()
                        self.release(stage.tid)
                        return
                    else:
                        raise wire.WireError(
                            "expected CREDIT/DONE, got "
                            f"{wire.FRAME_NAMES.get(ftype, ftype)}")
            except (wire.WireError, OSError, ValueError, KeyError,
                    CreditError) as e:
                window.fail(f"{self.name}: credit pump died: {e}")

        pump = threading.Thread(target=credit_pump,
                                name=f"{self.name}-credit", daemon=True)
        pump.start()
        try:
            pos = start_block
            while pos < stage.n_blocks:
                width = min(chunk, stage.n_blocks - pos)
                try:
                    window.take(width, timeout=self._io_timeout_s)
                except Exception:
                    self._bump("credit_stalls")
                    raise
                # chaos seam: one arrival per chunk window, so a
                # FaultSpec("net.send", nth=k) kills exactly window k
                faults.check("net.send", replica=self.name)
                planes = {name: arr[:, pos:pos + width]
                          for name, arr in stage.planes.items()}
                self._send(conn, wire.encode_chunk(pos, pos + width, planes))
                pos += width
            self._bump("served")
            # wait for the importer's DONE so the stage releases; a peer
            # that dies here just leaves the stage for release()/retry
            pump.join(timeout=self._io_timeout_s)
        except BaseException:
            # unblock BOTH sides before unwinding: the importer wakes with
            # a dead-wire WireError, the pump's recv fails and exits
            done.set()
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            raise
        finally:
            done.set()
            with self._lock:
                self._stats["max_inflight_windows"] = max(
                    self._stats["max_inflight_windows"],
                    window.max_inflight_windows)


# -- importer-side client ----------------------------------------------------
def fetch_chunks(
    address: Tuple[str, int],
    transfer_id: str,
    *,
    start_block: int,
    n_blocks: int,
    chunk_blocks: int,
    on_chunk: Callable[[int, int, Dict[str, np.ndarray]], None],
    credit_windows: int = 2,
    io_timeout_s: float = DEFAULT_IO_TIMEOUT_S,
    replica: Optional[str] = None,
) -> Dict[str, int]:
    """Dial ``address`` and pull blocks ``[start_block, n_blocks)`` of
    ``transfer_id`` as chunk windows, invoking ``on_chunk(lo, hi, planes)``
    for each (the remote transport's callback dispatches the donated
    scatter — async, so the next window's recv overlaps it). The initial
    credit grant is ``credit_windows`` windows (double-buffered by
    default); each consumed window is re-granted after its scatter
    dispatches, which is the backpressure: a slow scatter starves the
    exporter of credit.

    Raises :class:`~.wire.WireError` on any protocol violation, checksum
    mismatch, version skew, truncation, or exporter-reported error, and
    ``OSError`` on plain socket failures; both are retryable — the staged
    payload survives on the exporter until DONE/release.
    """
    chunk = max(1, int(chunk_blocks))
    want = int(n_blocks) - int(start_block)
    if want <= 0:
        return {"windows": 0, "max_inflight_windows": 0, "wire_bytes": 0}
    faults = get_fault_injector()
    # chaos seam: dialing the exporter
    faults.check("net.connect", replica=replica)
    window = CreditWindow(0)
    initial_credit = max(1, int(credit_windows)) * chunk
    stats = {"windows": 0, "wire_bytes": 0}
    with socket.create_connection(
            (address[0], int(address[1])), timeout=io_timeout_s) as conn:
        conn.settimeout(io_timeout_s)
        read = lambda n: wire.recv_exact(conn, n)
        conn.sendall(wire.encode_hello())
        ftype, payload = wire.read_frame(read)
        if ftype != wire.F_HELLO:
            raise wire.WireError(
                f"expected HELLO, got {wire.FRAME_NAMES.get(ftype, ftype)}")
        wire.negotiate_version(wire.decode_hello(payload))
        conn.sendall(wire.encode_json(wire.F_FETCH, {
            "tid": str(transfer_id),
            "start_block": int(start_block),
            "credit_blocks": initial_credit,
        }))
        window.grant(initial_credit)
        got = 0
        expect_lo = int(start_block)
        while got < want:
            # chaos seam: one arrival per frame read off the wire
            faults.check("net.recv", replica=replica)
            ftype, payload = wire.read_frame(read)
            stats["wire_bytes"] += wire.HEADER_BYTES + len(payload)
            if ftype == wire.F_ERROR:
                msg = wire.decode_json(payload, wire.F_ERROR).get(
                    "error", "unspecified")
                raise wire.WireError(f"exporter error: {msg}")
            if ftype != wire.F_CHUNK:
                raise wire.WireError(
                    f"expected CHUNK, got {wire.FRAME_NAMES.get(ftype, ftype)}")
            lo, hi, planes = wire.decode_chunk(payload)
            if lo != expect_lo or hi > n_blocks:
                raise wire.WireError(
                    f"out-of-order CHUNK [{lo}, {hi}): expected window "
                    f"starting at {expect_lo} within {n_blocks} blocks")
            width = hi - lo
            # police the exporter's credit compliance: a window we never
            # granted credit for is a protocol violation, not data
            if not window.try_take(width):
                raise wire.WireError(
                    f"exporter overran its credit window: CHUNK [{lo}, {hi}) "
                    f"with only {window.available} blocks granted")
            on_chunk(lo, hi, planes)
            window.settle(width)
            got += width
            expect_lo = hi
            stats["windows"] += 1
            if got < want:
                # replenish the exporter — and mirror the grant locally so
                # the policing window stays in sync with what the peer sees
                conn.sendall(wire.encode_json(
                    wire.F_CREDIT, {"blocks": width}))
                window.grant(width)
        conn.sendall(wire.encode_frame(wire.F_DONE))
    leaked = window.reset()
    return {
        "windows": stats["windows"],
        # pipeline depth the credit grant permitted: the exporter may run
        # this many windows ahead of the scatters (exporter-side peak is
        # in KVEndpoint.stats()["max_inflight_windows"])
        "max_inflight_windows": min(max(1, int(credit_windows)),
                                    stats["windows"]),
        "wire_bytes": stats["wire_bytes"],
        "leaked_credits": leaked,
    }
