"""Block-granular credit flow control for the remote KV wire.

The importer grants the exporter a window of block credits (FETCH carries
the initial grant, CREDIT frames replenish it as scatters land). The
exporter takes credits before sending each chunk window and blocks when
the window is empty — so a slow decoder backpressures the wire instead of
the exporter buffering unboundedly. The same window object tracks the
peak number of chunk windows in flight, which is what feeds the existing
``kv_handoff_inflight_windows`` gauge in :mod:`serving.metrics`.

Both sides unwind through :meth:`CreditWindow.reset`, which returns the
outstanding (taken-but-unsettled) credit so an aborted transfer can prove
it leaked nothing — the gauge-conservation audit the resilience suite
asserts.
"""

import threading

__all__ = ["CreditWindow", "CreditError"]


class CreditError(RuntimeError):
    """The credit window was failed (peer died) or a take timed out."""


class CreditWindow:
    """Thread-safe block-credit window shared between the socket thread
    and the scatter thread on each side of a transfer.

    exporter side: ``take(n)`` before each chunk send, ``grant(n)`` when a
    CREDIT frame arrives. importer side: ``take(n)`` when a chunk arrives
    (policing the peer: an exporter overrunning its grant is a protocol
    violation), ``settle(n)`` once the scatter for that window is
    dispatched and the CREDIT replenishment goes out.
    """

    def __init__(self, initial_blocks: int = 0):
        if initial_blocks < 0:
            raise ValueError(f"initial_blocks {initial_blocks} < 0")
        self._cond = threading.Condition()
        self._available = int(initial_blocks)
        self._outstanding = 0      # taken but not yet settled
        self._granted = int(initial_blocks)
        self._settled = 0
        self._failure = None
        self._inflight_windows = 0
        self._max_inflight_windows = 0

    # -- introspection -------------------------------------------------------
    @property
    def available(self) -> int:
        with self._cond:
            return self._available

    @property
    def outstanding(self) -> int:
        with self._cond:
            return self._outstanding

    @property
    def granted(self) -> int:
        with self._cond:
            return self._granted

    @property
    def max_inflight_windows(self) -> int:
        with self._cond:
            return self._max_inflight_windows

    def snapshot(self) -> dict:
        with self._cond:
            return {
                "available": self._available,
                "outstanding": self._outstanding,
                "granted": self._granted,
                "settled": self._settled,
                "max_inflight_windows": self._max_inflight_windows,
            }

    # -- flow ----------------------------------------------------------------
    def grant(self, blocks: int) -> None:
        """Add ``blocks`` credits to the window (CREDIT frame arrived)."""
        if blocks <= 0:
            raise ValueError(f"grant of {blocks} blocks")
        with self._cond:
            self._available += blocks
            self._granted += blocks
            self._cond.notify_all()

    def take(self, blocks: int, timeout: float = None) -> None:
        """Consume ``blocks`` credits, blocking until available. Raises
        :class:`CreditError` on timeout (credit stall — the peer stopped
        replenishing) or if the window was failed."""
        if blocks <= 0:
            raise ValueError(f"take of {blocks} blocks")
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._failure is not None or self._available >= blocks,
                timeout=timeout)
            if self._failure is not None:
                raise CreditError(self._failure)
            if not ok:
                raise CreditError(
                    f"credit stall: waited {timeout}s for {blocks} blocks, "
                    f"{self._available} available — peer stopped granting")
            self._take_locked(blocks)

    def try_take(self, blocks: int) -> bool:
        """Non-blocking :meth:`take`; returns False if short of credit."""
        if blocks <= 0:
            raise ValueError(f"try_take of {blocks} blocks")
        with self._cond:
            if self._failure is not None:
                raise CreditError(self._failure)
            if self._available < blocks:
                return False
            self._take_locked(blocks)
            return True

    def _take_locked(self, blocks: int) -> None:
        self._available -= blocks
        self._outstanding += blocks
        self._inflight_windows += 1
        if self._inflight_windows > self._max_inflight_windows:
            self._max_inflight_windows = self._inflight_windows

    def settle(self, blocks: int) -> None:
        """Mark ``blocks`` taken credits as done (scatter dispatched /
        chunk acknowledged). Over-settling is a accounting bug and raises."""
        if blocks <= 0:
            raise ValueError(f"settle of {blocks} blocks")
        with self._cond:
            if blocks > self._outstanding:
                raise CreditError(
                    f"settle({blocks}) exceeds outstanding "
                    f"{self._outstanding} — double settle")
            self._outstanding -= blocks
            self._settled += blocks
            if self._inflight_windows > 0:
                self._inflight_windows -= 1
            self._cond.notify_all()

    def fail(self, message: str) -> None:
        """Poison the window: blocked takers wake with :class:`CreditError`
        carrying ``message``. Used when the peer connection dies."""
        with self._cond:
            if self._failure is None:
                self._failure = str(message)
            self._cond.notify_all()

    def reset(self) -> int:
        """Unwind after an abort: zero everything and return how much
        credit was outstanding (taken, never settled). A clean transfer
        returns 0 — this is the leak audit the resilience tests assert."""
        with self._cond:
            leaked = self._outstanding
            self._available = 0
            self._outstanding = 0
            self._inflight_windows = 0
            self._cond.notify_all()
            return leaked
