"""Versioned length-prefixed binary frames for the remote KV wire.

The remote KV transport serializes the ``host`` payload representation —
the per-plane numpy arrays ``export_kv_blocks`` produces — into framed
byte strings a stdlib socket can carry between processes/hosts. One frame
is::

    offset  size  field
    0       4     magic  b"DSKV"
    4       2     protocol version (u16 LE) == PROTOCOL_VERSION
    6       2     frame type (u16 LE, one of F_*)
    8       8     payload length (u64 LE)
    16      4     CRC32 of the payload (u32 LE)
    20      N     payload

Decode is STRICT: a frame with foreign magic, a protocol version outside
the supported range, an unknown type, a length beyond
``MAX_FRAME_BYTES``, a payload shorter than its header promises, or a
checksum mismatch raises :class:`WireError` naming exactly what was
wrong — a corrupt or truncated frame must never scatter garbage into a
live KV pool (the pool-side ``check_kv_payload`` contract is the second
fence, this is the first).

Version negotiation: HELLO payloads carry the sender's
``min_version``/``max_version`` span (an EMPTY payload is a legacy v1
peer) and :func:`negotiate_version` picks the highest common version —
skew inside ``[MIN_PROTOCOL_VERSION, PROTOCOL_VERSION]`` downgrades
instead of disconnecting. Truly foreign peers still fail strictly:
wrong magic, or a version span with no overlap.

Control frames (HELLO/FETCH/CREDIT/ERROR/META and the cluster
control-plane vocabulary SUBMIT/TOKEN/CANCEL/HEALTH/ADOPT/STATS/EVENT/
GOODBYE) carry JSON; CHUNK frames carry a binary plane dict — per plane:
name, dtype string, shape, raw bytes — so quantized int8 codes and their
fp32 scale planes cross the wire bit-exactly (no text re-encoding of
array data ever).
"""

import json
import struct
import zlib
from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = [
    "PROTOCOL_VERSION",
    "MIN_PROTOCOL_VERSION",
    "MAGIC",
    "MAX_FRAME_BYTES",
    "F_HELLO",
    "F_FETCH",
    "F_CHUNK",
    "F_CREDIT",
    "F_DONE",
    "F_ERROR",
    "F_META",
    "F_SUBMIT",
    "F_TOKEN",
    "F_CANCEL",
    "F_HEALTH",
    "F_ADOPT",
    "F_STATS",
    "F_EVENT",
    "F_GOODBYE",
    "FRAME_NAMES",
    "WireError",
    "encode_frame",
    "decode_frame",
    "read_frame",
    "recv_exact",
    "encode_json",
    "decode_json",
    "encode_hello",
    "decode_hello",
    "negotiate_version",
    "encode_planes",
    "decode_planes",
    "encode_chunk",
    "decode_chunk",
    "encode_handoff_meta",
    "decode_handoff_meta",
]

MAGIC = b"DSKV"
# v1: KV fetch wire (HELLO..META). v2 adds the cluster control-plane
# vocabulary (SUBMIT..GOODBYE). The span [MIN_PROTOCOL_VERSION,
# PROTOCOL_VERSION] is what this build can SPEAK; HELLO negotiation picks
# the highest version both spans share.
PROTOCOL_VERSION = 2
MIN_PROTOCOL_VERSION = 1
# header: magic, version, frame type, payload length, payload crc32
_HEADER = struct.Struct("<4sHHQI")
HEADER_BYTES = _HEADER.size
# one chunk window of KV blocks is at most a few hundred MB even at
# production shapes; anything past this is a corrupt length field, not a
# payload — reject before trying to allocate it
MAX_FRAME_BYTES = 1 << 32

F_HELLO = 1   # version handshake (both directions; {min_version, max_version})
F_FETCH = 2   # importer -> exporter: {tid, start_block, credit_blocks}
F_CHUNK = 3   # exporter -> importer: binary block-window planes
F_CREDIT = 4  # importer -> exporter: {blocks} replenishing the window
F_DONE = 5    # importer -> exporter: transfer landed, release the stage
F_ERROR = 6   # either direction: {error} then close
F_META = 7    # out-of-band handoff descriptor (cross-process bootstrap)
# -- control plane (v2): router <-> replica agent -----------------------------
F_SUBMIT = 8    # router -> agent: {uid, prompt, params} new resident request
F_TOKEN = 9     # agent -> router: {uid, tok} / {uid, fin} token pump
F_CANCEL = 10   # router -> agent: {uid} release a resident (cancel/finish)
F_HEALTH = 11   # router -> agent: probation probe; reply {ok} or ERROR
F_ADOPT = 12    # router -> agent: {req, meta} import a KV handoff and decode
F_STATS = 13    # agent -> router: replica stats + prefix advertisement
F_EVENT = 14    # agent -> router: lifecycle/control-plane event mirror
F_GOODBYE = 15  # either direction: clean teardown of a control channel

FRAME_NAMES = {
    F_HELLO: "HELLO", F_FETCH: "FETCH", F_CHUNK: "CHUNK",
    F_CREDIT: "CREDIT", F_DONE: "DONE", F_ERROR: "ERROR", F_META: "META",
    F_SUBMIT: "SUBMIT", F_TOKEN: "TOKEN", F_CANCEL: "CANCEL",
    F_HEALTH: "HEALTH", F_ADOPT: "ADOPT", F_STATS: "STATS",
    F_EVENT: "EVENT", F_GOODBYE: "GOODBYE",
}


class WireError(RuntimeError):
    """A frame failed the strict decode (truncated, corrupt, foreign
    version/magic, unknown type) or the peer broke protocol."""


def encode_frame(ftype: int, payload: bytes = b"",
                 version: int = PROTOCOL_VERSION) -> bytes:
    """One framed message: header (magic, version, type, length, crc32)
    followed by the payload bytes. ``version`` defaults to this build's
    newest; a channel that negotiated a downgrade passes the agreed
    version so the peer's strict decode accepts every frame."""
    if ftype not in FRAME_NAMES:
        raise ValueError(f"unknown frame type {ftype}")
    if not (MIN_PROTOCOL_VERSION <= int(version) <= PROTOCOL_VERSION):
        raise ValueError(
            f"cannot encode v{version} frames (this build speaks "
            f"v{MIN_PROTOCOL_VERSION}..v{PROTOCOL_VERSION})")
    payload = bytes(payload)
    return _HEADER.pack(MAGIC, int(version), ftype, len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _check_header(magic: bytes, version: int, ftype: int, length: int):
    if magic != MAGIC:
        raise WireError(
            f"foreign frame: magic {magic!r} != {MAGIC!r} — peer is not a "
            "dstpu KV endpoint")
    if not (MIN_PROTOCOL_VERSION <= version <= PROTOCOL_VERSION):
        raise WireError(
            f"protocol version skew: peer speaks v{version}, this build "
            f"speaks v{MIN_PROTOCOL_VERSION}..v{PROTOCOL_VERSION} — "
            "refusing to guess at the frame layout")
    if ftype not in FRAME_NAMES:
        raise WireError(f"unknown frame type {ftype} (v{PROTOCOL_VERSION} "
                        f"knows {sorted(FRAME_NAMES)})")
    if length > MAX_FRAME_BYTES:
        raise WireError(
            f"frame length {length} exceeds MAX_FRAME_BYTES "
            f"{MAX_FRAME_BYTES} — corrupt length field")


def _check_payload(payload: bytes, crc: int, ftype: int):
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise WireError(
            f"checksum mismatch on {FRAME_NAMES[ftype]} frame: payload "
            "corrupted in flight")


def decode_frame(buf: bytes, offset: int = 0) -> Tuple[int, bytes, int]:
    """Strictly decode one frame from ``buf`` at ``offset``. Returns
    ``(frame_type, payload, next_offset)``; raises :class:`WireError` on
    truncation, corruption, or version/magic skew."""
    view = memoryview(buf)
    if len(view) - offset < HEADER_BYTES:
        raise WireError(
            f"truncated frame: {len(view) - offset} bytes < "
            f"{HEADER_BYTES}-byte header")
    magic, version, ftype, length, crc = _HEADER.unpack_from(view, offset)
    _check_header(magic, version, ftype, length)
    start = offset + HEADER_BYTES
    if len(view) - start < length:
        raise WireError(
            f"truncated {FRAME_NAMES[ftype]} frame: header promises "
            f"{length} payload bytes, only {len(view) - start} present")
    payload = bytes(view[start:start + length])
    _check_payload(payload, crc, ftype)
    return ftype, payload, start + length


def recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes from a socket; a peer that hangs up
    mid-read surfaces as a :class:`WireError`, never a short buffer."""
    chunks = []
    remaining = n
    while remaining > 0:
        data = sock.recv(min(remaining, 1 << 20))
        if not data:
            raise WireError(
                f"connection closed mid-frame: wanted {n} bytes, got "
                f"{n - remaining} — peer crashed or hung up")
        chunks.append(data)
        remaining -= len(data)
    return b"".join(chunks)


def read_frame(read: Callable[[int], bytes]) -> Tuple[int, bytes]:
    """Read one frame through ``read(n)`` (which must return exactly ``n``
    bytes or raise). Returns ``(frame_type, payload)``."""
    header = read(HEADER_BYTES)
    magic, version, ftype, length, crc = _HEADER.unpack(header)
    _check_header(magic, version, ftype, length)
    payload = read(length) if length else b""
    _check_payload(payload, crc, ftype)
    return ftype, payload


# -- JSON control payloads ---------------------------------------------------
def encode_json(ftype: int, obj: Dict) -> bytes:
    return encode_frame(ftype, json.dumps(obj, separators=(",", ":"),
                                          sort_keys=True).encode("utf-8"))


def decode_json(payload: bytes, ftype: int = 0) -> Dict:
    try:
        obj = json.loads(payload.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        name = FRAME_NAMES.get(ftype, ftype)
        raise WireError(f"malformed JSON payload in {name} frame: {e}") from e
    if not isinstance(obj, dict):
        raise WireError(f"JSON payload must be an object, got {type(obj).__name__}")
    return obj


# -- HELLO version negotiation ------------------------------------------------
def encode_hello(extra: Optional[Dict] = None) -> bytes:
    """A HELLO frame carrying this build's speakable version span (plus
    optional channel metadata, e.g. the control plane's bootstrap role)."""
    obj = dict(extra or {})
    obj["min_version"] = MIN_PROTOCOL_VERSION
    obj["max_version"] = PROTOCOL_VERSION
    return encode_json(F_HELLO, obj)


def decode_hello(payload: bytes) -> Dict:
    """Decode a HELLO payload into its announcement dict. An EMPTY payload
    is a legacy v1 peer (v1 HELLOs carried nothing) — it reads as the
    span {1, 1} so negotiation downgrades instead of disconnecting."""
    if not payload:
        return {"min_version": 1, "max_version": 1}
    obj = decode_json(payload, F_HELLO)
    obj.setdefault("min_version", 1)
    obj.setdefault("max_version", obj["min_version"])
    return obj


def negotiate_version(hello: Dict) -> int:
    """Highest protocol version both the local build and the peer's HELLO
    span can speak. No overlap is a truly foreign peer — strict
    :class:`WireError`, exactly like bad magic."""
    try:
        peer_min = int(hello.get("min_version", 1))
        peer_max = int(hello.get("max_version", peer_min))
    except (TypeError, ValueError) as e:
        raise WireError(f"malformed HELLO version span: {e}") from e
    if peer_min > peer_max:
        raise WireError(
            f"malformed HELLO version span: min {peer_min} > max {peer_max}")
    agreed = min(PROTOCOL_VERSION, peer_max)
    if agreed < max(MIN_PROTOCOL_VERSION, peer_min):
        raise WireError(
            f"no common protocol version: peer speaks v{peer_min}..v{peer_max}, "
            f"this build speaks v{MIN_PROTOCOL_VERSION}..v{PROTOCOL_VERSION}")
    return agreed


# -- binary plane dicts (CHUNK frames) ---------------------------------------
_U8 = struct.Struct("<B")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


def encode_planes(planes: Dict[str, np.ndarray]) -> bytes:
    """Binary-serialize a plane dict (name -> ndarray) preserving dtype,
    shape, and every payload byte exactly. bf16 codes and fp32 scales
    cross as raw bytes — there is no text round-trip to lose bits in."""
    parts = [_U16.pack(len(planes))]
    for name in sorted(planes):
        arr = np.ascontiguousarray(planes[name])
        nb = name.encode("utf-8")
        db = str(np.dtype(arr.dtype)).encode("utf-8")
        parts.append(_U16.pack(len(nb)))
        parts.append(nb)
        parts.append(_U16.pack(len(db)))
        parts.append(db)
        parts.append(_U8.pack(arr.ndim))
        for dim in arr.shape:
            parts.append(_U32.pack(dim))
        raw = arr.tobytes()
        parts.append(_U64.pack(len(raw)))
        parts.append(raw)
    return b"".join(parts)


def decode_planes(payload: bytes, offset: int = 0
                  ) -> Tuple[Dict[str, np.ndarray], int]:
    """Strict inverse of :func:`encode_planes`; returns the plane dict and
    the next offset. Truncated or inconsistent plane records raise
    :class:`WireError` (shape/dtype validity against the live pool is the
    importer's ``check_kv_payload`` contract, applied after this)."""
    view = memoryview(payload)

    def take(n: int, what: str) -> memoryview:
        nonlocal offset
        if len(view) - offset < n:
            raise WireError(
                f"truncated plane record: wanted {n} bytes for {what}, "
                f"{len(view) - offset} left")
        out = view[offset:offset + n]
        offset += n
        return out

    (n_planes,) = _U16.unpack(take(2, "plane count"))
    planes: Dict[str, np.ndarray] = {}
    for _ in range(n_planes):
        (name_len,) = _U16.unpack(take(2, "name length"))
        name = bytes(take(name_len, "plane name")).decode("utf-8")
        (dtype_len,) = _U16.unpack(take(2, "dtype length"))
        dtype_s = bytes(take(dtype_len, "dtype string")).decode("utf-8")
        try:
            dtype = np.dtype(dtype_s)
        except TypeError as e:
            raise WireError(f"plane {name!r}: unknown dtype {dtype_s!r}") from e
        (ndim,) = _U8.unpack(take(1, "ndim"))
        shape = tuple(_U32.unpack(take(4, "dim"))[0] for _ in range(ndim))
        (raw_len,) = _U64.unpack(take(8, "payload length"))
        expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if raw_len != expect:
            raise WireError(
                f"plane {name!r}: {raw_len} payload bytes != {expect} for "
                f"shape {shape} dtype {dtype_s}")
        raw = take(raw_len, f"plane {name!r} data")
        planes[name] = np.frombuffer(raw, dtype=dtype).reshape(shape)
    return planes, offset


def encode_chunk(lo: int, hi: int, planes: Dict[str, np.ndarray]) -> bytes:
    """One block-granular chunk window: source block columns ``[lo, hi)``
    plus the plane slices covering them."""
    return encode_frame(
        F_CHUNK, _U32.pack(lo) + _U32.pack(hi) + encode_planes(planes))


def decode_chunk(payload: bytes) -> Tuple[int, int, Dict[str, np.ndarray]]:
    if len(payload) < 8:
        raise WireError("truncated CHUNK payload: missing block range")
    (lo,) = _U32.unpack_from(payload, 0)
    (hi,) = _U32.unpack_from(payload, 4)
    if hi <= lo:
        raise WireError(f"CHUNK block range [{lo}, {hi}) is empty or inverted")
    planes, end = decode_planes(payload, 8)
    if end != len(payload):
        raise WireError(
            f"CHUNK payload has {len(payload) - end} trailing bytes after "
            "the plane records")
    return lo, hi, planes


# -- handoff descriptors (cross-process bootstrap) ---------------------------
def encode_handoff_meta(handoff) -> bytes:
    """Frame a :class:`KVHandoff`'s METADATA (no payload planes) so a
    different process can import it: token history, cursors, and the
    exporter endpoint + transfer id the remote wire fetches from."""
    if handoff.endpoint is None or handoff.transfer_id is None:
        raise WireError(
            f"handoff {handoff.uid} has no endpoint/transfer_id — only "
            "remote-transport exports can cross a process boundary")
    return encode_json(F_META, {
        "uid": int(handoff.uid),
        "tokens": [int(t) for t in handoff.tokens],
        "seen_tokens": int(handoff.seen_tokens),
        "pending_token": int(handoff.pending_token),
        "n_blocks": int(handoff.n_blocks),
        "transport": handoff.transport,
        "chunk_blocks": int(handoff.chunk_blocks),
        "nbytes": int(handoff.nbytes),
        "endpoint": [str(handoff.endpoint[0]), int(handoff.endpoint[1])],
        "transfer_id": str(handoff.transfer_id),
    })


def decode_handoff_meta(data: bytes):
    """Strictly decode a META frame back into a payload-less
    :class:`KVHandoff` aimed at the exporter's endpoint."""
    from deepspeed_tpu.serving.cluster.handoff import KVHandoff

    ftype, payload, _ = decode_frame(data)
    if ftype != F_META:
        raise WireError(
            f"expected META frame, got {FRAME_NAMES.get(ftype, ftype)}")
    obj = decode_json(payload, F_META)
    missing = [k for k in ("uid", "tokens", "seen_tokens", "pending_token",
                           "n_blocks", "transport", "chunk_blocks",
                           "endpoint", "transfer_id") if k not in obj]
    if missing:
        raise WireError(f"META frame missing fields {missing}")
    return KVHandoff(
        uid=int(obj["uid"]),
        tokens=[int(t) for t in obj["tokens"]],
        seen_tokens=int(obj["seen_tokens"]),
        pending_token=int(obj["pending_token"]),
        n_blocks=int(obj["n_blocks"]),
        payload=None,
        transport=str(obj["transport"]),
        chunk_blocks=int(obj["chunk_blocks"]),
        nbytes=int(obj.get("nbytes", 0)),
        endpoint=(str(obj["endpoint"][0]), int(obj["endpoint"][1])),
        transfer_id=str(obj["transfer_id"]),
    )
