"""Cross-process KV + control wire for disaggregated serving
(docs/NETWORKING.md).

Layers, bottom up: :mod:`.wire` (versioned checksummed binary frames),
:mod:`.flow` (block-granular credit window), :mod:`.endpoint`
(per-engine listener + chunk-fetch client), :mod:`.transport`
(``RemoteTransport``, registered as ``--kv-transport remote``), and
:mod:`.control` (the multi-host control plane's RPC/events channels —
SUBMIT/TOKEN/CANCEL/HEALTH/ADOPT/STATS/EVENT/GOODBYE frames on the same
wire format).
"""

from deepspeed_tpu.serving.net.wire import (  # noqa: F401
    PROTOCOL_VERSION,
    WireError,
    decode_handoff_meta,
    encode_handoff_meta,
)
from deepspeed_tpu.serving.net.flow import CreditWindow, CreditError  # noqa: F401
from deepspeed_tpu.serving.net.endpoint import KVEndpoint, fetch_chunks  # noqa: F401
from deepspeed_tpu.serving.net.control import (  # noqa: F401
    ControlChannel,
    ControlEndpoint,
    ControlRefused,
    dial_control,
)

__all__ = [
    "PROTOCOL_VERSION",
    "WireError",
    "encode_handoff_meta",
    "decode_handoff_meta",
    "CreditWindow",
    "CreditError",
    "KVEndpoint",
    "fetch_chunks",
    "ControlChannel",
    "ControlEndpoint",
    "ControlRefused",
    "dial_control",
]
