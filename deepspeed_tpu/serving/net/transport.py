"""RemoteTransport: the cross-process KV handoff wire.

Export stages the ``host`` representation (the portable numpy planes
``export_kv_blocks`` already produces) at the source engine's
:class:`~.endpoint.KVEndpoint` and puts only ``(endpoint, transfer_id)``
in the handoff — no payload travels with the descriptor, so the handoff
itself can cross a process boundary as one META frame
(:func:`~.wire.encode_handoff_meta`). Import dials the endpoint and pulls
credit-flow-controlled chunk windows, scattering each through the same
fixed-window donated readmit program the host transport uses — the
scatter for window k dispatches while window k+1 is still on the wire,
so decode starts before the tail lands.

Failure semantics: wire faults (socket errors, checksum/version
rejections, credit stalls) surface as :class:`HandoffError` from the
import, which unwinds the target pool via ``import_sequence`` and rides
the router's bounded transfer-edge retries. The staged payload is
immutable and survives the failed attempt, so the retry re-FETCHes the
same transfer id; only the router's final success/abort releases it.
Injected chaos faults (``net.connect`` / ``net.send`` / ``net.recv``)
propagate raw so the harness can count them.
"""

import os
from typing import List, Optional

from deepspeed_tpu.serving.cluster.handoff import (
    HandoffError,
    KVHandoff,
    KVTransport,
    _payload_nbytes,
)
from deepspeed_tpu.serving.net import endpoint as net_endpoint
from deepspeed_tpu.serving.net import wire

__all__ = ["RemoteTransport", "ensure_endpoint", "DEFAULT_CREDIT_WINDOWS"]

# double-buffered by default: one window scattering, one on the wire
DEFAULT_CREDIT_WINDOWS = 2


def ensure_endpoint(engine, host: Optional[str] = None
                    ) -> net_endpoint.KVEndpoint:
    """The engine's lazily created KVEndpoint (one listener per engine,
    port 0 = ephemeral). Created on first remote export so a bare
    ``export_sequence(..., transport="remote")`` works without a Router;
    the Router reads/creates the same attribute for health metadata and
    closes it at shutdown.

    Binding and discovery are separate concerns: ``DSTPU_KV_BIND_HOST``
    (default 127.0.0.1) picks the interface the listener binds, while
    ``DSTPU_KV_ENDPOINT_HOST`` is the ADVERTISED host — the address
    handoff descriptors and /health metadata hand to importers on other
    machines. Unset, the endpoint advertises its bind address (the
    single-host behavior)."""
    ep = getattr(engine, "_kv_endpoint", None)
    if ep is None:
        bind = host or os.environ.get("DSTPU_KV_BIND_HOST", "127.0.0.1")
        ep = net_endpoint.KVEndpoint(
            host=bind,
            name=str(getattr(engine, "_trace_name", None) or "engine"),
            advertise_host=os.environ.get("DSTPU_KV_ENDPOINT_HOST"),
        ).start()
        engine._kv_endpoint = ep
    return ep


class RemoteTransport(KVTransport):
    """``host``-representation planes over a credit-flow-controlled
    socket wire; the only transport whose handoffs survive pickling away
    from the exporting process."""

    name = "remote"

    def __init__(self, credit_windows: int = DEFAULT_CREDIT_WINDOWS,
                 io_timeout_s: float = net_endpoint.DEFAULT_IO_TIMEOUT_S):
        self.credit_windows = int(credit_windows)
        self.io_timeout_s = float(io_timeout_s)

    def export(self, engine, blocks: List[int], handoff: KVHandoff) -> None:
        export = getattr(engine, "export_kv_blocks", None)
        if export is None:
            return  # compute-free fake: bookkeeping-only handoff
        payload = export(blocks)
        kv = getattr(getattr(engine, "config", None), "kv_cache", None)
        chunk = int(getattr(kv, "host_tier_chunk_blocks", 8) or 8)
        ep = ensure_endpoint(engine)
        handoff.transfer_id = ep.stage(handoff.uid, payload, chunk)
        handoff.endpoint = ep.address
        handoff.chunk_blocks = chunk
        handoff.nbytes = _payload_nbytes(payload)
        # the payload never rides the handoff object: a remote descriptor
        # must stay cheap enough to serialize as one META frame
        handoff.payload = None

    def _import_payload(self, engine, handoff: KVHandoff, seq,
                        n_cached: int, fresh: List[int]) -> None:
        if handoff.endpoint is None or not fresh:
            return  # fake-engine handoff (or fully trie-covered import)
        chunked = getattr(engine, "import_kv_blocks_chunked", None)
        plain = getattr(engine, "import_kv_blocks", None)
        if chunked is None and plain is None:
            raise HandoffError(
                f"import({handoff.uid}): target engine has no "
                "import_kv_blocks(_chunked) — remote-transport handoffs "
                "need an engine_v2 pool on the importing side"
            )
        chunk = max(1, int(handoff.chunk_blocks))

        def on_chunk(lo, hi, planes):
            # source columns [lo, hi) map to the fresh tail of the target
            # table; lo >= n_cached because the FETCH starts past the
            # trie/host-tier covered prefix
            dest = fresh[lo - n_cached:hi - n_cached]
            if len(dest) != hi - lo:
                raise wire.WireError(
                    f"CHUNK [{lo}, {hi}) outside the {len(fresh)} fresh "
                    f"blocks past n_cached={n_cached}")
            if chunked is not None:
                # fixed-window donated scatter (async dispatch): the wire
                # recv of the NEXT window overlaps this scatter
                chunked(dest, planes, chunk_blocks=chunk)
            else:
                plain(dest, planes)

        try:
            stats = net_endpoint.fetch_chunks(
                handoff.endpoint,
                handoff.transfer_id,
                start_block=n_cached,
                n_blocks=handoff.n_blocks,
                chunk_blocks=chunk,
                on_chunk=on_chunk,
                credit_windows=self.credit_windows,
                io_timeout_s=self.io_timeout_s,
                replica=getattr(engine, "_trace_name", None),
            )
        except (wire.WireError, OSError) as e:
            raise HandoffError(
                f"import({handoff.uid}): remote wire to "
                f"{handoff.endpoint[0]}:{handoff.endpoint[1]} failed: {e}"
            ) from e
        handoff.inflight_windows = int(stats.get("max_inflight_windows", 0))

    def abort(self, engine, handoff: KVHandoff) -> None:
        """Drop the staged transfer of a handoff that will never import
        (request terminated / retries exhausted) so the exporter's stage
        table cannot leak. ``engine`` is the SOURCE engine; a handoff
        staged by another process is released by that process's DONE/
        timeout path instead."""
        ep = getattr(engine, "_kv_endpoint", None)
        if ep is not None and handoff.transfer_id is not None:
            ep.release(handoff.transfer_id)
