"""Control-plane wire: the router <-> replica-agent frame channel.

The multi-host control plane rides the SAME versioned checksummed frame
protocol as the KV data wire (:mod:`.wire`) — UCCL-EP's portable-wire
stance: one strict frame layout under both control and data traffic, so
an RDMA-class transport later slots under either without a second
protocol. Control frames are the v2 vocabulary (SUBMIT/TOKEN/CANCEL/
HEALTH/ADOPT/STATS/EVENT/GOODBYE); a channel whose HELLO negotiation
lands below v2 cannot carry them and is refused at the handshake.

Topology: the ROUTER owns one :class:`ControlEndpoint` listener; each
replica agent DIALS it (:func:`dial_control`, bounded-retry via
``resilience/retry.py``) twice — an ``rpc`` channel the router sends
request frames down (the agent replies in order), and an ``events``
channel the agent pushes TOKEN/STATS/EVENT frames up. Both directions
originate at the agent, so a pod's workers need no inbound reachability
to the replicas (NAT/firewall friendly), and both channels traverse the
same chaos seams as the KV wire: ``net.connect`` at the dial,
``net.send``/``net.recv`` per frame.

Failure semantics: any wire fault (socket error, strict-decode
rejection, injected chaos) surfaces as :class:`~.wire.WireError`/
``OSError`` out of :meth:`ControlChannel.recv`/:meth:`~ControlChannel.call`;
the owner maps it onto the PR-15 resilience machinery (agent lost ->
quarantine -> replay recovery) — the channel itself never retries
mid-stream, only the initial dial is retried.
"""

import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from deepspeed_tpu.serving.net import wire
from deepspeed_tpu.serving.resilience.faults import get_fault_injector
from deepspeed_tpu.serving.resilience.retry import RetryPolicy, with_retries
from deepspeed_tpu.utils.logging import logger

__all__ = [
    "CONTROL_MIN_VERSION",
    "ControlChannel",
    "ControlEndpoint",
    "dial_control",
    "DEFAULT_CONTROL_TIMEOUT_S",
]

# the control vocabulary (SUBMIT..GOODBYE) exists from protocol v2 on; a
# peer whose span negotiates below this cannot serve as a replica agent
CONTROL_MIN_VERSION = 2

DEFAULT_CONTROL_TIMEOUT_S = 30.0


class ControlRefused(wire.WireError):
    """The router answered the bootstrap META with an F_ERROR — a
    protocol-level rejection (name collision, version floor), not a wire
    fault. Dial retries must NOT repeat it: the router gave a verdict,
    and hammering the same bootstrap just re-asks the same question."""


class ControlChannel:
    """One connected control channel speaking JSON frames.

    Thread model: ``send`` is safe from any thread (one writer lock
    serializes frame bytes onto the socket); ``recv`` is single-reader —
    exactly one pump/serve thread drains inbound frames. ``call`` is the
    router-side RPC helper (send request, read reply, one in flight at a
    time) and must own the read side of its channel.
    """

    def __init__(self, conn: socket.socket, *, name: str = "ctl",
                 version: int = wire.PROTOCOL_VERSION,
                 io_timeout_s: Optional[float] = None,
                 metrics=None):
        self.name = str(name)
        self.version = int(version)
        self.metrics = metrics
        self._conn = conn
        # None = blocking: persistent channels legitimately idle for long
        # stretches (an rpc channel between probes, an events channel
        # between tokens) — deadlines are per-call (``call(timeout_s=)``),
        # and a dead peer still surfaces as EOF/RST out of recv
        self._io_timeout_s = (None if io_timeout_s is None
                              else float(io_timeout_s))
        self._send_lock = threading.Lock()
        self._rpc_lock = threading.Lock()
        self._closed = False
        conn.settimeout(self._io_timeout_s)

    # -- framing -------------------------------------------------------------
    def _count(self) -> None:
        if self.metrics is not None:
            self.metrics.inc("control_frames_total")

    def send(self, ftype: int, obj: Dict) -> None:
        """Frame ``obj`` as ``ftype`` and write it. Raises ``OSError`` on a
        dead wire and ``InjectedFault`` at the ``net.send`` chaos seam."""
        faults = get_fault_injector()
        if faults.enabled:
            faults.check("net.send", replica=self.name)
        frame = wire.encode_json(ftype, obj)
        with self._send_lock:
            self._conn.sendall(frame)
        self._count()

    def recv(self, timeout_s: Optional[float] = None) -> Tuple[int, Dict]:
        """Read one frame; strict decode. Single-reader by contract."""
        faults = get_fault_injector()
        if faults.enabled:
            faults.check("net.recv", replica=self.name)
        if timeout_s is not None:
            self._conn.settimeout(float(timeout_s))
        try:
            ftype, payload = wire.read_frame(
                lambda n: wire.recv_exact(self._conn, n))
        finally:
            if timeout_s is not None:
                self._conn.settimeout(self._io_timeout_s)
        self._count()
        return ftype, wire.decode_json(payload, ftype) if payload else {}

    def call(self, ftype: int, obj: Dict,
             timeout_s: Optional[float] = None) -> Dict:
        """One request/reply round trip (router -> agent). The reply must
        echo the request's frame type; an ERROR frame raises with the
        agent's message. Serialized — one RPC in flight per channel."""
        t0 = time.monotonic()
        with self._rpc_lock:
            self.send(ftype, obj)
            rtype, reply = self.recv(timeout_s=timeout_s)  # dstpu: noqa[blocking-call-under-lock] — the recv IS the rpc: _rpc_lock exists to serialize request/reply pairs on this channel, nothing else contends on it, and agent loss unblocks it via socket close (WireError)
        if self.metrics is not None:
            self.metrics.inc("control_rpcs_total")
            self.metrics.inc("control_rpc_seconds", time.monotonic() - t0)
        if rtype == wire.F_ERROR:
            raise wire.WireError(
                f"{wire.FRAME_NAMES.get(ftype, ftype)} rpc failed on "
                f"{self.name}: {reply.get('error', 'unspecified')}")
        if rtype != ftype:
            raise wire.WireError(
                f"rpc reply type mismatch on {self.name}: sent "
                f"{wire.FRAME_NAMES.get(ftype, ftype)}, got "
                f"{wire.FRAME_NAMES.get(rtype, rtype)}")
        return reply

    # -- lifecycle -----------------------------------------------------------
    @property
    def closed(self) -> bool:
        return self._closed

    def goodbye(self, reason: str = "shutdown") -> None:
        """Best-effort clean teardown notice; never raises."""
        try:
            self.send(wire.F_GOODBYE, {"reason": str(reason)})
        except Exception:
            pass

    def close(self) -> None:
        self._closed = True
        try:
            self._conn.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._conn.close()
        except OSError:
            pass


def _handshake_accept(conn: socket.socket, io_timeout_s: float) -> Tuple[int, Dict]:
    """Server side of the channel bootstrap: HELLO span exchange (strict
    negotiation), then one META frame describing the channel (role, agent
    metadata). Returns ``(negotiated_version, bootstrap_meta)``."""
    conn.settimeout(io_timeout_s)
    read = lambda n: wire.recv_exact(conn, n)
    ftype, payload = wire.read_frame(read)
    if ftype != wire.F_HELLO:
        raise wire.WireError(
            f"expected HELLO, got {wire.FRAME_NAMES.get(ftype, ftype)}")
    version = wire.negotiate_version(wire.decode_hello(payload))
    if version < CONTROL_MIN_VERSION:
        raise wire.WireError(
            f"peer negotiated v{version} < v{CONTROL_MIN_VERSION} — no "
            "control-frame vocabulary before v2")
    conn.sendall(wire.encode_hello())
    ftype, payload = wire.read_frame(read)
    if ftype != wire.F_META:
        raise wire.WireError(
            f"expected META bootstrap, got {wire.FRAME_NAMES.get(ftype, ftype)}")
    return version, wire.decode_json(payload, wire.F_META)


class ControlEndpoint:
    """The router's control listener: accepts agent channels, handshakes
    them (HELLO negotiation + META bootstrap), and hands each
    :class:`ControlChannel` to ``on_channel(meta, channel)`` — whose dict
    return value is sent back as the META acknowledgment (e.g. the
    replica name the router assigned). Raising inside ``on_channel``
    refuses the channel with an ERROR frame."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 name: str = "control",
                 on_channel: Callable[[Dict, ControlChannel], Optional[Dict]],
                 io_timeout_s: float = DEFAULT_CONTROL_TIMEOUT_S,
                 metrics=None):
        self.name = str(name)
        self.metrics = metrics
        self._on_channel = on_channel
        self._io_timeout_s = float(io_timeout_s)
        self._lock = threading.Lock()
        self._closed = False
        self._threads = []
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._address = self._listener.getsockname()[:2]
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return (self._address[0], int(self._address[1]))

    def start(self) -> "ControlEndpoint":
        if self._accept_thread is None:
            self._accept_thread = threading.Thread(
                target=self._accept_loop, name=f"{self.name}-accept",
                daemon=True)
            self._accept_thread.start()
        return self

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            t = threading.Thread(target=self._bootstrap_conn, args=(conn,),
                                 name=f"{self.name}-hello", daemon=True)
            with self._lock:
                if self._closed:
                    conn.close()
                    return
                self._threads = [x for x in self._threads if x.is_alive()]
                self._threads.append(t)
            t.start()

    def _bootstrap_conn(self, conn: socket.socket) -> None:
        """Handshake one inbound channel and hand it to the owner. The
        thread exits after the META ack — pump/serve loops belong to the
        owner, not the endpoint."""
        channel = None
        try:
            version, meta = _handshake_accept(conn, self._io_timeout_s)
            channel = ControlChannel(
                conn, name=str(meta.get("channel", "ctl")), version=version,
                metrics=self.metrics)
            try:
                ack = self._on_channel(meta, channel) or {}
            except Exception as e:
                channel.send(wire.F_ERROR, {"error": f"{type(e).__name__}: {e}"})
                channel.close()
                return
            channel.send(wire.F_META, dict(ack, version=version))
        except (wire.WireError, OSError, ValueError) as e:
            logger.warning(f"control[{self.name}]: bootstrap failed: {e}")
            if channel is not None:
                channel.close()
            else:
                try:
                    conn.close()
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        # wake a blocked accept() (closing the fd does not, on Linux)
        try:
            with socket.create_connection(self.address, timeout=0.5):
                pass
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout=2.0)


def dial_control(
    address: Tuple[str, int],
    meta: Dict,
    *,
    retry_policy: Optional[RetryPolicy] = None,
    io_timeout_s: float = DEFAULT_CONTROL_TIMEOUT_S,
    name: str = "ctl",
    replica: Optional[str] = None,
    metrics=None,
) -> Tuple[ControlChannel, Dict]:
    """Agent side: dial the router's control endpoint, negotiate versions,
    send the META bootstrap, and return ``(channel, ack)`` where ``ack``
    is the router's META reply (assigned replica name, agreed version).

    ``retry_policy`` bounds the dial (``resilience/retry.py``): an agent
    started before its router retries with backoff instead of dying. Only
    the DIAL retries — a channel that fails mid-stream is the owner's
    failure plane, not the wire's.
    """

    def attempt() -> Tuple[ControlChannel, Dict]:
        faults = get_fault_injector()
        if faults.enabled:
            faults.check("net.connect", replica=replica or name)
        conn = socket.create_connection(
            (address[0], int(address[1])), timeout=io_timeout_s)
        try:
            conn.settimeout(io_timeout_s)
            read = lambda n: wire.recv_exact(conn, n)
            conn.sendall(wire.encode_hello())
            ftype, payload = wire.read_frame(read)
            if ftype != wire.F_HELLO:
                raise wire.WireError(
                    f"expected HELLO, got {wire.FRAME_NAMES.get(ftype, ftype)}")
            version = wire.negotiate_version(wire.decode_hello(payload))
            if version < CONTROL_MIN_VERSION:
                raise wire.WireError(
                    f"router negotiated v{version} < v{CONTROL_MIN_VERSION} — "
                    "no control-frame vocabulary before v2")
            conn.sendall(wire.encode_json(wire.F_META, meta))
            ftype, payload = wire.read_frame(read)
            if ftype == wire.F_ERROR:
                err = wire.decode_json(payload, wire.F_ERROR)
                raise ControlRefused(
                    f"router refused channel: {err.get('error', 'unspecified')}")
            if ftype != wire.F_META:
                raise wire.WireError(
                    f"expected META ack, got {wire.FRAME_NAMES.get(ftype, ftype)}")
            ack = wire.decode_json(payload, wire.F_META)
            # the handshake ran under a dial deadline; the long-lived
            # channel goes blocking (see ControlChannel.__init__)
            conn.settimeout(None)
            return (ControlChannel(conn, name=name, version=version,
                                   metrics=metrics), ack)
        except BaseException:
            try:
                conn.close()
            except OSError:
                pass
            raise

    if retry_policy is None:
        return attempt()

    def _refusals_are_final(_attempt: int, err: BaseException) -> None:
        if isinstance(err, ControlRefused):
            raise err

    return with_retries(attempt, retry_policy, label=f"control.dial:{name}",
                        on_retry=_refusals_are_final)
